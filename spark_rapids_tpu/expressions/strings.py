"""String expressions (reference: stringFunctions.scala ~3k LoC — GpuLength,
GpuUpper/GpuLower, GpuConcat, GpuSubstring, GpuStartsWith/EndsWith/Contains,
GpuLike, GpuStringTrim family...).

TPU-first design: device strings are uint8[rows, width] + lengths, so string
kernels are 2-D elementwise/reduction ops that vectorize across the padded
rectangle on VPU lanes — a different shape from cuDF's offsets+chars byte
kernels, chosen because TPU wants fixed strides.

CPU path operates on object arrays of python str and is the oracle.
Deviations (documented, mirroring reference docs/compatibility.md): device
Upper/Lower transform ASCII only (non-ASCII passes through).
"""

from __future__ import annotations

import numpy as np

from spark_rapids_tpu import types as T
from spark_rapids_tpu.expressions.base import (Expression, EvalContext, TCol,
                                               both_valid, jnp, materialize,
                                               valid_array)
from spark_rapids_tpu.expressions.arithmetic import BinaryExpr, UnaryExpr
from spark_rapids_tpu.expressions.predicates import _densify_string


def _dev_inputs(c: TCol, ctx, xp):
    c = _densify_string(c, ctx, xp)
    return c.data, c.lengths, valid_array(c, ctx)


def _cpu_str_map(c: TCol, ctx, fn):
    """Applies fn over a CPU object array with null passthrough."""
    data = materialize(c, ctx, np.dtype(object))
    valid = valid_array(c, ctx)
    out = np.empty(len(data), dtype=object)
    for i in range(len(data)):
        out[i] = fn(data[i]) if valid[i] and data[i] is not None else None
    return out, valid


class Length(UnaryExpr):
    """Character (not byte) length, per Spark semantics."""

    @property
    def data_type(self):
        return T.INT

    def eval_tpu(self, ctx):
        xp = jnp()
        c = self.child.eval(ctx)
        chars, lens, valid = _dev_inputs(c, ctx, xp)
        w = chars.shape[1]
        pos = xp.arange(w)[None, :]
        in_len = pos < lens[:, None]
        # UTF-8 char count = bytes that are not continuation bytes (10xxxxxx)
        not_cont = (chars & 0xC0) != 0x80
        count = xp.sum((not_cont & in_len).astype(np.int32), axis=1)
        return TCol(count, valid, T.INT)

    def eval_cpu(self, ctx):
        c = self.child.eval(ctx)
        out, valid = _cpu_str_map(c, ctx, len)
        data = np.array([0 if v is None else v for v in out], dtype=np.int32)
        return TCol(data, valid, T.INT)


class _AsciiMap(UnaryExpr):
    """ASCII case transform on device; full unicode on CPU oracle for ASCII
    inputs they agree (documented deviation otherwise)."""

    lower = False

    @property
    def data_type(self):
        return T.STRING

    def eval_tpu(self, ctx):
        xp = jnp()
        c = self.child.eval(ctx)
        chars, lens, valid = _dev_inputs(c, ctx, xp)
        if self.lower:
            is_tgt = (chars >= ord("A")) & (chars <= ord("Z"))
            out = xp.where(is_tgt, chars + 32, chars)
        else:
            is_tgt = (chars >= ord("a")) & (chars <= ord("z"))
            out = xp.where(is_tgt, chars - 32, chars)
        return TCol(out, valid, T.STRING, lengths=lens)

    def eval_cpu(self, ctx):
        c = self.child.eval(ctx)
        fn = str.lower if self.lower else str.upper
        out, valid = _cpu_str_map(c, ctx, fn)
        return TCol(out, valid, T.STRING)


class Upper(_AsciiMap):
    lower = False


class Lower(_AsciiMap):
    lower = True


class Concat(Expression):
    """concat(...): NULL if any input is NULL (Spark semantics)."""

    def __init__(self, *exprs):
        super().__init__(list(exprs))

    @property
    def data_type(self):
        return T.STRING

    def eval_tpu(self, ctx):
        xp = jnp()
        cols = [self.children[0].eval(ctx)]
        for c in self.children[1:]:
            cols.append(c.eval(ctx))
        parts = [_dev_inputs(c, ctx, xp) for c in cols]
        total_w = sum(p[0].shape[1] for p in parts)
        n = parts[0][0].shape[0]
        out = xp.zeros((n, total_w), dtype=np.uint8)
        acc_len = xp.zeros(n, dtype=np.int32)
        valid = xp.ones(n, dtype=bool)
        j = xp.arange(total_w)[None, :]
        for chars, lens, v in parts:
            w = chars.shape[1]
            # scatter this part at offset acc_len: out[r, acc_len+k] = chars[r, k]
            src_idx = j - acc_len[:, None]
            in_part = (src_idx >= 0) & (src_idx < lens[:, None])
            gathered = xp.take_along_axis(
                chars, xp.clip(src_idx, 0, w - 1).astype(np.int32), axis=1)
            out = xp.where(in_part, gathered, out)
            acc_len = acc_len + lens
            valid = valid & v
        return TCol(out, valid, T.STRING, lengths=acc_len)

    def eval_cpu(self, ctx):
        cols = [c.eval(ctx) for c in self.children]
        datas = [materialize(c, ctx, np.dtype(object)) for c in cols]
        valids = [valid_array(c, ctx) for c in cols]
        n = len(datas[0])
        out = np.empty(n, dtype=object)
        valid = np.ones(n, dtype=bool)
        for v in valids:
            valid &= v
        for i in range(n):
            if valid[i] and all(d[i] is not None for d in datas):
                out[i] = "".join(d[i] for d in datas)
            else:
                out[i] = None
                valid[i] = False
        return TCol(out, valid, T.STRING)


class Substring(Expression):
    """substring(str, pos, len): 1-based pos; negative pos counts from end.

    NOTE: device kernel operates on BYTES; Spark semantics are characters.
    For ASCII they agree; multi-byte inputs are tagged incompat (reference
    documents similar unicode caveats for some string ops).
    """

    def __init__(self, child, pos, length=None):
        from spark_rapids_tpu.expressions.base import Literal
        pos = pos if isinstance(pos, Expression) else Literal(int(pos))
        kids = [child, pos]
        if length is not None:
            length = length if isinstance(length, Expression) else \
                Literal(int(length))
            kids.append(length)
        super().__init__(kids)

    @property
    def data_type(self):
        return T.STRING

    def eval_tpu(self, ctx):
        xp = jnp()
        c = self.children[0].eval(ctx)
        chars, lens, valid = _dev_inputs(c, ctx, xp)
        p = self.children[1].eval(ctx)
        pos = materialize(p, ctx, np.dtype(np.int32))
        valid = valid & valid_array(p, ctx) if not p.is_scalar else valid
        if len(self.children) > 2:
            le = self.children[2].eval(ctx)
            slen = materialize(le, ctx, np.dtype(np.int32))
        else:
            slen = xp.full(chars.shape[0], 2**30, dtype=np.int32)
        start = xp.where(pos > 0, pos - 1,
                         xp.where(pos < 0, xp.maximum(lens + pos, 0), 0))
        start = xp.minimum(start.astype(np.int32), lens)
        out_len = xp.clip(xp.minimum(slen, lens - start), 0, None)
        w = chars.shape[1]
        j = xp.arange(w)[None, :]
        src = j + start[:, None]
        gathered = xp.take_along_axis(chars, xp.clip(src, 0, w - 1), axis=1)
        out = xp.where(j < out_len[:, None], gathered, 0)
        return TCol(out, valid, T.STRING, lengths=out_len.astype(np.int32))

    def eval_cpu(self, ctx):
        c = self.children[0].eval(ctx)
        p = self.children[1].eval(ctx)
        pos = materialize(p, ctx, np.dtype(np.int32))
        if len(self.children) > 2:
            slen = materialize(self.children[2].eval(ctx), ctx,
                               np.dtype(np.int32))
        else:
            slen = np.full(ctx.row_count, 2**30, dtype=np.int32)
        data = materialize(c, ctx, np.dtype(object))
        valid = valid_array(c, ctx)
        out = np.empty(len(data), dtype=object)
        for i in range(len(data)):
            if not valid[i] or data[i] is None:
                out[i] = None
                continue
            s = data[i]
            po = int(pos[i])
            start = po - 1 if po > 0 else (max(len(s) + po, 0) if po < 0 else 0)
            out[i] = s[start:start + max(int(slen[i]), 0)] if start >= 0 else ""
        return TCol(out, valid, T.STRING)


class _FixedCompare(BinaryExpr):
    """startswith/endswith/contains with an arbitrary string RHS."""

    @property
    def data_type(self):
        return T.BOOLEAN

    def eval_tpu(self, ctx):
        xp = jnp()
        a = self.left.eval(ctx)
        b = self.right.eval(ctx)
        valid = both_valid(a, b, ctx)
        if a.is_scalar and b.is_scalar:
            if not valid:
                return TCol.scalar(None, T.BOOLEAN)
            return TCol.scalar(self._py(a.data, b.data), T.BOOLEAN)
        achars, alens, av = _dev_inputs(a, ctx, xp)
        bchars, blens, bv = _dev_inputs(b, ctx, xp)
        out = self._dev(achars, alens, bchars, blens, xp)
        return TCol(out, av & bv, T.BOOLEAN)

    def eval_cpu(self, ctx):
        a = self.left.eval(ctx)
        b = self.right.eval(ctx)
        ad = materialize(a, ctx, np.dtype(object))
        bd = materialize(b, ctx, np.dtype(object))
        valid = valid_array(a, ctx) & valid_array(b, ctx)
        out = np.zeros(len(ad), dtype=bool)
        for i in range(len(ad)):
            if valid[i] and ad[i] is not None and bd[i] is not None:
                out[i] = self._py(ad[i], bd[i])
        return TCol(out, valid, T.BOOLEAN)


class StartsWith(_FixedCompare):
    symbol = "startswith"

    def _py(self, s, p):
        return s.startswith(p)

    def _dev(self, ac, al, bc, bl, xp):
        w = min(ac.shape[1], bc.shape[1])
        eq = ac[:, :w] == bc[:, :w]
        pos = xp.arange(w)[None, :]
        in_pat = pos < bl[:, None]
        return xp.all(eq | ~in_pat, axis=1) & (bl <= al)


class EndsWith(_FixedCompare):
    symbol = "endswith"

    def _py(self, s, p):
        return s.endswith(p)

    def _dev(self, ac, al, bc, bl, xp):
        w = bc.shape[1]
        j = xp.arange(w)[None, :]
        src = al[:, None] - bl[:, None] + j
        gathered = xp.take_along_axis(
            ac, xp.clip(src, 0, ac.shape[1] - 1), axis=1) \
            if ac.shape[1] else ac
        in_pat = j < bl[:, None]
        eq = gathered == bc[:, :w]
        return xp.all(eq | ~in_pat, axis=1) & (bl <= al)


class Contains(_FixedCompare):
    symbol = "contains"

    def _py(self, s, p):
        return p in s

    def _dev(self, ac, al, bc, bl, xp):
        wa, wb = ac.shape[1], bc.shape[1]
        # sliding window compare: for each start s in [0, wa), check pattern
        j = xp.arange(wb)[None, None, :]           # [1,1,wb]
        starts = xp.arange(wa)[None, :, None]      # [1,wa,1]
        src = starts + j                           # [1,wa,wb]
        src_c = xp.broadcast_to(xp.clip(src, 0, wa - 1),
                                (ac.shape[0], wa, wb))
        gathered = xp.take_along_axis(ac[:, None, :], src_c, axis=2)
        in_pat = j < bl[:, None, None]
        eq = gathered == bc[:, None, :]
        match_at = xp.all(eq | ~in_pat, axis=2)    # [n, wa]
        starts_ok = starts[0, :, 0][None, :] <= (al - bl)[:, None]
        return xp.any(match_at & starts_ok, axis=1)


class Like(BinaryExpr):
    """SQL LIKE with % and _ (reference GpuLike; escapes default '\\').

    Device: handled by the planner rewriting pure-prefix/suffix/infix
    patterns to StartsWith/EndsWith/Contains (the reference's
    RegexRewriteUtils does the same trick); general patterns run on CPU.
    """
    symbol = "like"

    @property
    def data_type(self):
        return T.BOOLEAN

    def tpu_supported(self, conf):
        return "general LIKE runs on host (planner rewrites simple patterns)"

    def _match(self, s, pattern):
        import re
        regex = "^"
        i = 0
        while i < len(pattern):
            ch = pattern[i]
            if ch == "\\" and i + 1 < len(pattern):
                regex += re.escape(pattern[i + 1])
                i += 2
                continue
            if ch == "%":
                regex += ".*"
            elif ch == "_":
                regex += "."
            else:
                regex += re.escape(ch)
            i += 1
        return re.match(regex + "$", s, flags=re.DOTALL) is not None

    def eval_cpu(self, ctx):
        a = self.left.eval(ctx)
        b = self.right.eval(ctx)
        ad = materialize(a, ctx, np.dtype(object))
        bd = materialize(b, ctx, np.dtype(object))
        valid = valid_array(a, ctx) & valid_array(b, ctx)
        out = np.zeros(len(ad), dtype=bool)
        for i in range(len(ad)):
            if valid[i] and ad[i] is not None and bd[i] is not None:
                out[i] = self._match(ad[i], bd[i])
        return TCol(out, valid, T.BOOLEAN)

    eval_tpu = eval_cpu  # host fallback even when called on device path


class _Trim(UnaryExpr):
    trim_left = True
    trim_right = True

    @property
    def data_type(self):
        return T.STRING

    def eval_tpu(self, ctx):
        xp = jnp()
        c = self.child.eval(ctx)
        chars, lens, valid = _dev_inputs(c, ctx, xp)
        w = chars.shape[1]
        pos = xp.arange(w)[None, :]
        in_len = pos < lens[:, None]
        is_space = (chars == 32) & in_len
        non_space = (~is_space) & in_len
        any_ns = xp.any(non_space, axis=1)
        first = xp.where(any_ns, xp.argmax(non_space, axis=1), 0) \
            if self.trim_left else xp.zeros_like(lens)
        if self.trim_right:
            last = xp.where(any_ns,
                            w - 1 - xp.argmax(non_space[:, ::-1], axis=1),
                            -1)
        else:
            last = lens - 1
        # all-space input trims to empty in every mode
        new_len = xp.clip(xp.where(any_ns, last - first + 1, 0), 0, None)
        j = xp.arange(w)[None, :]
        src = j + first[:, None]
        gathered = xp.take_along_axis(chars, xp.clip(src, 0, w - 1), axis=1)
        out = xp.where(j < new_len[:, None], gathered, 0)
        return TCol(out, valid, T.STRING, lengths=new_len.astype(np.int32))

    def eval_cpu(self, ctx):
        c = self.child.eval(ctx)
        if self.trim_left and self.trim_right:
            fn = lambda s: s.strip(" ")
        elif self.trim_left:
            fn = lambda s: s.lstrip(" ")
        else:
            fn = lambda s: s.rstrip(" ")
        out, valid = _cpu_str_map(c, ctx, fn)
        return TCol(out, valid, T.STRING)


class Trim(_Trim):
    trim_left = True
    trim_right = True


class LTrim(_Trim):
    trim_left = True
    trim_right = False


class RTrim(_Trim):
    trim_left = False
    trim_right = True


# ---------------------------------------------------------------------------
# Regular expressions (reference: GpuRLike/GpuRegExpReplace/GpuRegExpExtract
# in stringFunctions.scala + the RegexParser.scala transpiler).
#
# The Java-dialect pattern is transpiled once at planning time
# (spark_rapids_tpu/regexp.py).  Patterns that reduce to fixed-string
# prefix/suffix/contains/equals run as device kernels (the reference's
# RegexRewriteUtils rewrite); everything else runs on the host tier with
# honest fallback tagging.
# ---------------------------------------------------------------------------

class _RegexExpr(Expression):
    """Shared machinery: literal-pattern requirement + cached transpile."""

    mode = "FIND"

    def _pattern_literal(self):
        from spark_rapids_tpu.expressions.base import Literal
        p = self.children[1]
        if isinstance(p, Literal) and isinstance(p.value, str):
            return p.value
        return None

    def _transpiled(self):
        from spark_rapids_tpu import regexp as RX
        if not hasattr(self, "_tx_cache"):
            pat = self._pattern_literal()
            self._tx_cache = None if pat is None else RX.transpile(
                pat, self.mode)
        return self._tx_cache

    @staticmethod
    def _best_effort_compile(pattern: str):
        """Transpiled when possible; raw host-dialect otherwise.  The CPU
        fallback path must execute even transpiler-rejected patterns (the
        reference's CPU fallback runs Java regex natively); divergences for
        exotic escapes are documented compatibility deviations."""
        import re
        from spark_rapids_tpu import regexp as RX
        try:
            return re.compile(RX.transpile(pattern).pattern)
        except RX.RegexUnsupported:
            return re.compile(pattern)

    def _compiled(self):
        if not hasattr(self, "_re_cache"):
            self._re_cache = self._best_effort_compile(self._pattern_literal())
        return self._re_cache

    def _pattern_regexes(self, ctx, n):
        """Per-row compiled patterns: the cached literal regex, or per-row
        compilation when the pattern is itself a column (Spark recompiles
        non-foldable patterns per row)."""
        if self._pattern_literal() is not None:
            rx = self._compiled()
            return [rx] * n
        pats = self.children[1].eval(ctx)
        data = materialize(pats, ctx, np.dtype(object))
        cache = {}
        out = []
        for p in data:
            if p is None:
                out.append(None)
            else:
                if p not in cache:
                    cache[p] = self._best_effort_compile(p)
                out.append(cache[p])
        return out

    def tpu_supported(self, conf):
        from spark_rapids_tpu import config as C
        from spark_rapids_tpu import regexp as RX
        if not conf.get(C.ENABLE_REGEX.key):
            return "regular expressions disabled by spark.rapids.sql.regexp.enabled"
        if self._pattern_literal() is None:
            return "only literal regex patterns are supported"
        try:
            tx = self._transpiled()
        except RX.RegexUnsupported as e:
            return f"regex not supported: {e}"
        r = self._extra_checks(tx)
        if r is not None:
            return r
        return self._tag_transpiled(tx)

    def _extra_checks(self, tx):
        """Subclass validation that should surface before the generic
        host-tier reason (mirrors the reference's per-op tag rules)."""
        return None

    def _tag_transpiled(self, tx):
        return "general regex runs on host (planner rewrites simple patterns)"


class RLike(_RegexExpr):
    """str RLIKE pattern (reference: GpuRLike; Java Pattern.find semantics)."""

    def __init__(self, subject: Expression, pattern: Expression):
        super().__init__([subject, pattern])

    @property
    def data_type(self):
        return T.BOOLEAN

    def sql(self):
        return f"{self.children[0].sql()} RLIKE {self.children[1].sql()}"

    def _tag_transpiled(self, tx):
        if tx.rewrite is not None:
            return None  # runs as a fixed-string device kernel
        return super()._tag_transpiled(tx)

    def _rewritten(self):
        """The device-kernel equivalent for simple patterns."""
        from spark_rapids_tpu.expressions.base import Literal
        from spark_rapids_tpu.expressions.predicates import EqualTo
        kind, lit = self._transpiled().rewrite
        subject = self.children[0]
        litex = Literal(lit, T.STRING)
        return {"equals": EqualTo, "prefix": StartsWith,
                "suffix": EndsWith, "contains": Contains}[kind](subject, litex)

    def eval_tpu(self, ctx):
        tx = self._transpiled()
        if tx is not None and tx.rewrite is not None:
            return self._rewritten().eval(ctx)
        return self.eval_cpu(ctx)

    def eval_cpu(self, ctx):
        c = self.children[0].eval(ctx)
        data = materialize(c, ctx, np.dtype(object))
        rxs = self._pattern_regexes(ctx, len(data))
        valid = valid_array(c, ctx) & valid_array(
            self.children[1].eval(ctx), ctx)
        out = np.zeros(len(data), dtype=bool)
        for i in range(len(data)):
            if valid[i] and data[i] is not None and rxs[i] is not None:
                out[i] = rxs[i].search(data[i]) is not None
        return TCol(out, valid, T.BOOLEAN)


class RegExpReplace(_RegexExpr):
    """regexp_replace(str, pattern, replacement)
    (reference: GpuRegExpReplace + GpuRegExpUtils.backrefConversion)."""

    mode = "REPLACE"

    def __init__(self, subject, pattern, replacement):
        super().__init__([subject, pattern, replacement])

    @property
    def data_type(self):
        return T.STRING

    def _extra_checks(self, tx):
        from spark_rapids_tpu.expressions.base import Literal
        repl = self.children[2]
        if not (isinstance(repl, Literal) and isinstance(repl.value, str)):
            return "only literal replacement strings are supported"
        return None

    def _py_replacement(self):
        from spark_rapids_tpu import regexp as RX
        from spark_rapids_tpu.expressions.base import Literal
        repl = self.children[2]
        if not (isinstance(repl, Literal) and isinstance(repl.value, str)):
            raise NotImplementedError(
                "regexp_replace requires a literal replacement string")
        tx = self._transpiled()
        return RX.transpile_replacement(
            repl.value, None if tx is None else tx.num_groups)

    def eval_cpu(self, ctx):
        repl = self._py_replacement()
        c = self.children[0].eval(ctx)
        data = materialize(c, ctx, np.dtype(object))
        rxs = self._pattern_regexes(ctx, len(data))
        # a null pattern row nulls the output (Spark null propagation)
        valid = valid_array(c, ctx) & valid_array(
            self.children[1].eval(ctx), ctx)
        out = np.empty(len(data), dtype=object)
        for i in range(len(data)):
            if valid[i] and data[i] is not None and rxs[i] is not None:
                out[i] = rxs[i].sub(repl, data[i])
            else:
                out[i] = None
        return TCol(out, valid, T.STRING)

    eval_tpu = eval_cpu  # host tier (tagging routes here only on fallback)


class RegExpExtract(_RegexExpr):
    """regexp_extract(str, pattern, idx) — group idx of the first match,
    empty string when no match (Spark semantics; reference GpuRegExpExtract)."""

    def __init__(self, subject, pattern, idx: Expression = None):
        from spark_rapids_tpu.expressions.base import Literal
        if idx is None:
            idx = Literal(1, T.INT)
        super().__init__([subject, pattern, idx])

    @property
    def data_type(self):
        return T.STRING

    def _extra_checks(self, tx):
        from spark_rapids_tpu.expressions.base import Literal
        idx = self.children[2]
        if not (isinstance(idx, Literal) and isinstance(idx.value, int)):
            return "group index must be a literal integer"
        if not (0 <= idx.value <= tx.num_groups):
            return (f"group index {idx.value} out of range "
                    f"(pattern has {tx.num_groups} groups)")
        return None

    def eval_cpu(self, ctx):
        from spark_rapids_tpu.expressions.base import Literal
        if not isinstance(self.children[2], Literal):
            raise NotImplementedError(
                "regexp_extract requires a literal group index")
        idx = self.children[2].value
        c = self.children[0].eval(ctx)
        data = materialize(c, ctx, np.dtype(object))
        rxs = self._pattern_regexes(ctx, len(data))
        valid = valid_array(c, ctx) & valid_array(
            self.children[1].eval(ctx), ctx)
        out = np.empty(len(data), dtype=object)
        for i in range(len(data)):
            if valid[i] and data[i] is not None and rxs[i] is not None:
                m = rxs[i].search(data[i])
                g = m.group(idx) if m is not None else ""
                out[i] = "" if g is None else g
            else:
                out[i] = None
        return TCol(out, valid, T.STRING)

    eval_tpu = eval_cpu
