"""String expressions (reference: stringFunctions.scala ~3k LoC — GpuLength,
GpuUpper/GpuLower, GpuConcat, GpuSubstring, GpuStartsWith/EndsWith/Contains,
GpuLike, GpuStringTrim family...).

TPU-first design: device strings are uint8[rows, width] + lengths, so string
kernels are 2-D elementwise/reduction ops that vectorize across the padded
rectangle on VPU lanes — a different shape from cuDF's offsets+chars byte
kernels, chosen because TPU wants fixed strides.

CPU path operates on object arrays of python str and is the oracle.
Deviations (documented, mirroring reference docs/compatibility.md): device
Upper/Lower transform ASCII only (non-ASCII passes through).
"""

from __future__ import annotations

import numpy as np

from spark_rapids_tpu import types as T
from spark_rapids_tpu.expressions.base import (Expression, EvalContext, TCol,
                                               both_valid, jnp, materialize,
                                               valid_array)
from spark_rapids_tpu.expressions.arithmetic import BinaryExpr, UnaryExpr
from spark_rapids_tpu.expressions.predicates import _densify_string


def _dev_inputs(c: TCol, ctx, xp):
    c = _densify_string(c, ctx, xp)
    return c.data, c.lengths, valid_array(c, ctx)


def _cpu_str_map(c: TCol, ctx, fn):
    """Applies fn over a CPU object array with null passthrough."""
    data = materialize(c, ctx, np.dtype(object))
    valid = valid_array(c, ctx)
    out = np.empty(len(data), dtype=object)
    for i in range(len(data)):
        out[i] = fn(data[i]) if valid[i] and data[i] is not None else None
    return out, valid


class Length(UnaryExpr):
    """Character (not byte) length, per Spark semantics."""

    @property
    def data_type(self):
        return T.INT

    def eval_tpu(self, ctx):
        xp = jnp()
        c = self.child.eval(ctx)
        chars, lens, valid = _dev_inputs(c, ctx, xp)
        w = chars.shape[1]
        pos = xp.arange(w)[None, :]
        in_len = pos < lens[:, None]
        # UTF-8 char count = bytes that are not continuation bytes (10xxxxxx)
        not_cont = (chars & 0xC0) != 0x80
        count = xp.sum((not_cont & in_len).astype(np.int32), axis=1)
        return TCol(count, valid, T.INT)

    def eval_cpu(self, ctx):
        c = self.child.eval(ctx)
        out, valid = _cpu_str_map(c, ctx, len)
        data = np.array([0 if v is None else v for v in out], dtype=np.int32)
        return TCol(data, valid, T.INT)


class _AsciiMap(UnaryExpr):
    """ASCII case transform on device; full unicode on CPU oracle for ASCII
    inputs they agree (documented deviation otherwise)."""

    lower = False

    @property
    def data_type(self):
        return T.STRING

    def eval_tpu(self, ctx):
        xp = jnp()
        c = self.child.eval(ctx)
        chars, lens, valid = _dev_inputs(c, ctx, xp)
        if self.lower:
            is_tgt = (chars >= ord("A")) & (chars <= ord("Z"))
            out = xp.where(is_tgt, chars + 32, chars)
        else:
            is_tgt = (chars >= ord("a")) & (chars <= ord("z"))
            out = xp.where(is_tgt, chars - 32, chars)
        return TCol(out, valid, T.STRING, lengths=lens)

    def eval_cpu(self, ctx):
        c = self.child.eval(ctx)
        fn = str.lower if self.lower else str.upper
        out, valid = _cpu_str_map(c, ctx, fn)
        return TCol(out, valid, T.STRING)


class Upper(_AsciiMap):
    lower = False


class Lower(_AsciiMap):
    lower = True


class Concat(Expression):
    """concat(...): NULL if any input is NULL (Spark semantics)."""

    def __init__(self, *exprs):
        super().__init__(list(exprs))

    @property
    def data_type(self):
        return T.STRING

    def eval_tpu(self, ctx):
        xp = jnp()
        cols = [self.children[0].eval(ctx)]
        for c in self.children[1:]:
            cols.append(c.eval(ctx))
        parts = [_dev_inputs(c, ctx, xp) for c in cols]
        total_w = sum(p[0].shape[1] for p in parts)
        n = parts[0][0].shape[0]
        out = xp.zeros((n, total_w), dtype=np.uint8)
        acc_len = xp.zeros(n, dtype=np.int32)
        valid = xp.ones(n, dtype=bool)
        j = xp.arange(total_w)[None, :]
        for chars, lens, v in parts:
            w = chars.shape[1]
            # scatter this part at offset acc_len: out[r, acc_len+k] = chars[r, k]
            src_idx = j - acc_len[:, None]
            in_part = (src_idx >= 0) & (src_idx < lens[:, None])
            gathered = xp.take_along_axis(
                chars, xp.clip(src_idx, 0, w - 1).astype(np.int32), axis=1)
            out = xp.where(in_part, gathered, out)
            acc_len = acc_len + lens
            valid = valid & v
        return TCol(out, valid, T.STRING, lengths=acc_len)

    def eval_cpu(self, ctx):
        cols = [c.eval(ctx) for c in self.children]
        datas = [materialize(c, ctx, np.dtype(object)) for c in cols]
        valids = [valid_array(c, ctx) for c in cols]
        n = len(datas[0])
        out = np.empty(n, dtype=object)
        valid = np.ones(n, dtype=bool)
        for v in valids:
            valid &= v
        for i in range(n):
            if valid[i] and all(d[i] is not None for d in datas):
                out[i] = "".join(d[i] for d in datas)
            else:
                out[i] = None
                valid[i] = False
        return TCol(out, valid, T.STRING)


class Substring(Expression):
    """substring(str, pos, len): 1-based pos; negative pos counts from end.

    NOTE: device kernel operates on BYTES; Spark semantics are characters.
    For ASCII they agree; multi-byte inputs are tagged incompat (reference
    documents similar unicode caveats for some string ops).
    """

    def __init__(self, child, pos, length=None):
        from spark_rapids_tpu.expressions.base import Literal
        pos = pos if isinstance(pos, Expression) else Literal(int(pos))
        kids = [child, pos]
        if length is not None:
            length = length if isinstance(length, Expression) else \
                Literal(int(length))
            kids.append(length)
        super().__init__(kids)

    @property
    def data_type(self):
        return T.STRING

    def eval_tpu(self, ctx):
        xp = jnp()
        c = self.children[0].eval(ctx)
        chars, lens, valid = _dev_inputs(c, ctx, xp)
        p = self.children[1].eval(ctx)
        pos = materialize(p, ctx, np.dtype(np.int32))
        valid = valid & valid_array(p, ctx) if not p.is_scalar else valid
        if len(self.children) > 2:
            le = self.children[2].eval(ctx)
            slen = materialize(le, ctx, np.dtype(np.int32))
        else:
            slen = xp.full(chars.shape[0], 2**30, dtype=np.int32)
        start = xp.where(pos > 0, pos - 1,
                         xp.where(pos < 0, xp.maximum(lens + pos, 0), 0))
        start = xp.minimum(start.astype(np.int32), lens)
        out_len = xp.clip(xp.minimum(slen, lens - start), 0, None)
        w = chars.shape[1]
        j = xp.arange(w)[None, :]
        src = j + start[:, None]
        gathered = xp.take_along_axis(chars, xp.clip(src, 0, w - 1), axis=1)
        out = xp.where(j < out_len[:, None], gathered, 0)
        return TCol(out, valid, T.STRING, lengths=out_len.astype(np.int32))

    def eval_cpu(self, ctx):
        c = self.children[0].eval(ctx)
        p = self.children[1].eval(ctx)
        pos = materialize(p, ctx, np.dtype(np.int32))
        if len(self.children) > 2:
            slen = materialize(self.children[2].eval(ctx), ctx,
                               np.dtype(np.int32))
        else:
            slen = np.full(ctx.row_count, 2**30, dtype=np.int32)
        data = materialize(c, ctx, np.dtype(object))
        valid = valid_array(c, ctx)
        out = np.empty(len(data), dtype=object)
        for i in range(len(data)):
            if not valid[i] or data[i] is None:
                out[i] = None
                continue
            s = data[i]
            po = int(pos[i])
            start = po - 1 if po > 0 else (max(len(s) + po, 0) if po < 0 else 0)
            out[i] = s[start:start + max(int(slen[i]), 0)] if start >= 0 else ""
        return TCol(out, valid, T.STRING)


class _FixedCompare(BinaryExpr):
    """startswith/endswith/contains with an arbitrary string RHS."""

    @property
    def data_type(self):
        return T.BOOLEAN

    def eval_tpu(self, ctx):
        xp = jnp()
        a = self.left.eval(ctx)
        b = self.right.eval(ctx)
        valid = both_valid(a, b, ctx)
        if a.is_scalar and b.is_scalar:
            if not valid:
                return TCol.scalar(None, T.BOOLEAN)
            return TCol.scalar(self._py(a.data, b.data), T.BOOLEAN)
        achars, alens, av = _dev_inputs(a, ctx, xp)
        bchars, blens, bv = _dev_inputs(b, ctx, xp)
        out = self._dev(achars, alens, bchars, blens, xp)
        return TCol(out, av & bv, T.BOOLEAN)

    def eval_cpu(self, ctx):
        a = self.left.eval(ctx)
        b = self.right.eval(ctx)
        ad = materialize(a, ctx, np.dtype(object))
        bd = materialize(b, ctx, np.dtype(object))
        valid = valid_array(a, ctx) & valid_array(b, ctx)
        out = np.zeros(len(ad), dtype=bool)
        for i in range(len(ad)):
            if valid[i] and ad[i] is not None and bd[i] is not None:
                out[i] = self._py(ad[i], bd[i])
        return TCol(out, valid, T.BOOLEAN)


class StartsWith(_FixedCompare):
    symbol = "startswith"

    def _py(self, s, p):
        return s.startswith(p)

    def _dev(self, ac, al, bc, bl, xp):
        w = min(ac.shape[1], bc.shape[1])
        eq = ac[:, :w] == bc[:, :w]
        pos = xp.arange(w)[None, :]
        in_pat = pos < bl[:, None]
        return xp.all(eq | ~in_pat, axis=1) & (bl <= al)


class EndsWith(_FixedCompare):
    symbol = "endswith"

    def _py(self, s, p):
        return s.endswith(p)

    def _dev(self, ac, al, bc, bl, xp):
        w = bc.shape[1]
        j = xp.arange(w)[None, :]
        src = al[:, None] - bl[:, None] + j
        gathered = xp.take_along_axis(
            ac, xp.clip(src, 0, ac.shape[1] - 1), axis=1) \
            if ac.shape[1] else ac
        in_pat = j < bl[:, None]
        eq = gathered == bc[:, :w]
        return xp.all(eq | ~in_pat, axis=1) & (bl <= al)


class Contains(_FixedCompare):
    symbol = "contains"

    def _py(self, s, p):
        return p in s

    def _dev(self, ac, al, bc, bl, xp):
        wa, wb = ac.shape[1], bc.shape[1]
        # sliding window compare: for each start s in [0, wa), check pattern
        j = xp.arange(wb)[None, None, :]           # [1,1,wb]
        starts = xp.arange(wa)[None, :, None]      # [1,wa,1]
        src = starts + j                           # [1,wa,wb]
        src_c = xp.broadcast_to(xp.clip(src, 0, wa - 1),
                                (ac.shape[0], wa, wb))
        gathered = xp.take_along_axis(ac[:, None, :], src_c, axis=2)
        in_pat = j < bl[:, None, None]
        eq = gathered == bc[:, None, :]
        match_at = xp.all(eq | ~in_pat, axis=2)    # [n, wa]
        starts_ok = starts[0, :, 0][None, :] <= (al - bl)[:, None]
        return xp.any(match_at & starts_ok, axis=1)


class Like(BinaryExpr):
    """SQL LIKE with % and _ (reference GpuLike; escapes default '\\').

    Device: handled by the planner rewriting pure-prefix/suffix/infix
    patterns to StartsWith/EndsWith/Contains (the reference's
    RegexRewriteUtils does the same trick); general patterns run on CPU.
    """
    symbol = "like"

    @property
    def data_type(self):
        return T.BOOLEAN

    def tpu_supported(self, conf):
        return "general LIKE runs on host (planner rewrites simple patterns)"

    def _match(self, s, pattern):
        import re
        regex = "^"
        i = 0
        while i < len(pattern):
            ch = pattern[i]
            if ch == "\\" and i + 1 < len(pattern):
                regex += re.escape(pattern[i + 1])
                i += 2
                continue
            if ch == "%":
                regex += ".*"
            elif ch == "_":
                regex += "."
            else:
                regex += re.escape(ch)
            i += 1
        return re.match(regex + "$", s, flags=re.DOTALL) is not None

    def eval_cpu(self, ctx):
        a = self.left.eval(ctx)
        b = self.right.eval(ctx)
        ad = materialize(a, ctx, np.dtype(object))
        bd = materialize(b, ctx, np.dtype(object))
        valid = valid_array(a, ctx) & valid_array(b, ctx)
        out = np.zeros(len(ad), dtype=bool)
        for i in range(len(ad)):
            if valid[i] and ad[i] is not None and bd[i] is not None:
                out[i] = self._match(ad[i], bd[i])
        return TCol(out, valid, T.BOOLEAN)

    eval_tpu = eval_cpu  # host fallback even when called on device path


class _Trim(UnaryExpr):
    trim_left = True
    trim_right = True

    @property
    def data_type(self):
        return T.STRING

    def eval_tpu(self, ctx):
        xp = jnp()
        c = self.child.eval(ctx)
        chars, lens, valid = _dev_inputs(c, ctx, xp)
        w = chars.shape[1]
        pos = xp.arange(w)[None, :]
        in_len = pos < lens[:, None]
        is_space = (chars == 32) & in_len
        non_space = (~is_space) & in_len
        any_ns = xp.any(non_space, axis=1)
        first = xp.where(any_ns, xp.argmax(non_space, axis=1), 0) \
            if self.trim_left else xp.zeros_like(lens)
        if self.trim_right:
            last = xp.where(any_ns,
                            w - 1 - xp.argmax(non_space[:, ::-1], axis=1),
                            -1)
        else:
            last = lens - 1
        # all-space input trims to empty in every mode
        new_len = xp.clip(xp.where(any_ns, last - first + 1, 0), 0, None)
        j = xp.arange(w)[None, :]
        src = j + first[:, None]
        gathered = xp.take_along_axis(chars, xp.clip(src, 0, w - 1), axis=1)
        out = xp.where(j < new_len[:, None], gathered, 0)
        return TCol(out, valid, T.STRING, lengths=new_len.astype(np.int32))

    def eval_cpu(self, ctx):
        c = self.child.eval(ctx)
        if self.trim_left and self.trim_right:
            fn = lambda s: s.strip(" ")
        elif self.trim_left:
            fn = lambda s: s.lstrip(" ")
        else:
            fn = lambda s: s.rstrip(" ")
        out, valid = _cpu_str_map(c, ctx, fn)
        return TCol(out, valid, T.STRING)


class Trim(_Trim):
    trim_left = True
    trim_right = True


class LTrim(_Trim):
    trim_left = True
    trim_right = False


class RTrim(_Trim):
    trim_left = False
    trim_right = True


# ---------------------------------------------------------------------------
# Regular expressions (reference: GpuRLike/GpuRegExpReplace/GpuRegExpExtract
# in stringFunctions.scala + the RegexParser.scala transpiler).
#
# The Java-dialect pattern is transpiled once at planning time
# (spark_rapids_tpu/regexp.py).  Patterns that reduce to fixed-string
# prefix/suffix/contains/equals run as device kernels (the reference's
# RegexRewriteUtils rewrite); everything else runs on the host tier with
# honest fallback tagging.
# ---------------------------------------------------------------------------

class _RegexExpr(Expression):
    """Shared machinery: literal-pattern requirement + cached transpile."""

    mode = "FIND"

    def _pattern_literal(self):
        from spark_rapids_tpu.expressions.base import Literal
        p = self.children[1]
        if isinstance(p, Literal) and isinstance(p.value, str):
            return p.value
        return None

    def _transpiled(self):
        from spark_rapids_tpu import regexp as RX
        if not hasattr(self, "_tx_cache"):
            pat = self._pattern_literal()
            self._tx_cache = None if pat is None else RX.transpile(
                pat, self.mode)
        return self._tx_cache

    @staticmethod
    def _best_effort_compile(pattern: str):
        """Transpiled when possible; raw host-dialect otherwise.  The CPU
        fallback path must execute even transpiler-rejected patterns (the
        reference's CPU fallback runs Java regex natively); divergences for
        exotic escapes are documented compatibility deviations."""
        import re
        from spark_rapids_tpu import regexp as RX
        try:
            return re.compile(RX.transpile(pattern).pattern)
        except RX.RegexUnsupported:
            return re.compile(pattern)

    def _compiled(self):
        if not hasattr(self, "_re_cache"):
            self._re_cache = self._best_effort_compile(self._pattern_literal())
        return self._re_cache

    def _pattern_regexes(self, ctx, n):
        """Per-row compiled patterns: the cached literal regex, or per-row
        compilation when the pattern is itself a column (Spark recompiles
        non-foldable patterns per row)."""
        if self._pattern_literal() is not None:
            rx = self._compiled()
            return [rx] * n
        pats = self.children[1].eval(ctx)
        data = materialize(pats, ctx, np.dtype(object))
        cache = {}
        out = []
        for p in data:
            if p is None:
                out.append(None)
            else:
                if p not in cache:
                    cache[p] = self._best_effort_compile(p)
                out.append(cache[p])
        return out

    def tpu_supported(self, conf):
        from spark_rapids_tpu import config as C
        from spark_rapids_tpu import regexp as RX
        if not conf.get(C.ENABLE_REGEX.key):
            return "regular expressions disabled by spark.rapids.sql.regexp.enabled"
        if self._pattern_literal() is None:
            return "only literal regex patterns are supported"
        try:
            tx = self._transpiled()
        except RX.RegexUnsupported as e:
            return f"regex not supported: {e}"
        r = self._extra_checks(tx)
        if r is not None:
            return r
        return self._tag_transpiled(tx)

    def _extra_checks(self, tx):
        """Subclass validation that should surface before the generic
        host-tier reason (mirrors the reference's per-op tag rules)."""
        return None

    def _tag_transpiled(self, tx):
        return "general regex runs on host (planner rewrites simple patterns)"


class RLike(_RegexExpr):
    """str RLIKE pattern (reference: GpuRLike; Java Pattern.find semantics)."""

    def __init__(self, subject: Expression, pattern: Expression):
        super().__init__([subject, pattern])

    @property
    def data_type(self):
        return T.BOOLEAN

    def sql(self):
        return f"{self.children[0].sql()} RLIKE {self.children[1].sql()}"

    def _tag_transpiled(self, tx):
        if tx.rewrite is not None:
            return None  # runs as a fixed-string device kernel
        return super()._tag_transpiled(tx)

    def _rewritten(self):
        """The device-kernel equivalent for simple patterns."""
        from spark_rapids_tpu.expressions.base import Literal
        from spark_rapids_tpu.expressions.predicates import EqualTo
        kind, lit = self._transpiled().rewrite
        subject = self.children[0]
        litex = Literal(lit, T.STRING)
        return {"equals": EqualTo, "prefix": StartsWith,
                "suffix": EndsWith, "contains": Contains}[kind](subject, litex)

    def eval_tpu(self, ctx):
        tx = self._transpiled()
        if tx is not None and tx.rewrite is not None:
            return self._rewritten().eval(ctx)
        return self.eval_cpu(ctx)

    def eval_cpu(self, ctx):
        c = self.children[0].eval(ctx)
        data = materialize(c, ctx, np.dtype(object))
        rxs = self._pattern_regexes(ctx, len(data))
        valid = valid_array(c, ctx) & valid_array(
            self.children[1].eval(ctx), ctx)
        out = np.zeros(len(data), dtype=bool)
        for i in range(len(data)):
            if valid[i] and data[i] is not None and rxs[i] is not None:
                out[i] = rxs[i].search(data[i]) is not None
        return TCol(out, valid, T.BOOLEAN)


class RegExpReplace(_RegexExpr):
    """regexp_replace(str, pattern, replacement)
    (reference: GpuRegExpReplace + GpuRegExpUtils.backrefConversion)."""

    mode = "REPLACE"

    def __init__(self, subject, pattern, replacement):
        super().__init__([subject, pattern, replacement])

    @property
    def data_type(self):
        return T.STRING

    def _extra_checks(self, tx):
        from spark_rapids_tpu.expressions.base import Literal
        repl = self.children[2]
        if not (isinstance(repl, Literal) and isinstance(repl.value, str)):
            return "only literal replacement strings are supported"
        return None

    def _py_replacement(self):
        from spark_rapids_tpu import regexp as RX
        from spark_rapids_tpu.expressions.base import Literal
        repl = self.children[2]
        if not (isinstance(repl, Literal) and isinstance(repl.value, str)):
            raise NotImplementedError(
                "regexp_replace requires a literal replacement string")
        tx = self._transpiled()
        return RX.transpile_replacement(
            repl.value, None if tx is None else tx.num_groups)

    def eval_cpu(self, ctx):
        repl = self._py_replacement()
        c = self.children[0].eval(ctx)
        data = materialize(c, ctx, np.dtype(object))
        rxs = self._pattern_regexes(ctx, len(data))
        # a null pattern row nulls the output (Spark null propagation)
        valid = valid_array(c, ctx) & valid_array(
            self.children[1].eval(ctx), ctx)
        out = np.empty(len(data), dtype=object)
        for i in range(len(data)):
            if valid[i] and data[i] is not None and rxs[i] is not None:
                out[i] = rxs[i].sub(repl, data[i])
            else:
                out[i] = None
        return TCol(out, valid, T.STRING)

    eval_tpu = eval_cpu  # host tier (tagging routes here only on fallback)


class RegExpExtract(_RegexExpr):
    """regexp_extract(str, pattern, idx) — group idx of the first match,
    empty string when no match (Spark semantics; reference GpuRegExpExtract)."""

    def __init__(self, subject, pattern, idx: Expression = None):
        from spark_rapids_tpu.expressions.base import Literal
        if idx is None:
            idx = Literal(1, T.INT)
        super().__init__([subject, pattern, idx])

    @property
    def data_type(self):
        return T.STRING

    def _extra_checks(self, tx):
        from spark_rapids_tpu.expressions.base import Literal
        idx = self.children[2]
        if not (isinstance(idx, Literal) and isinstance(idx.value, int)):
            return "group index must be a literal integer"
        if not (0 <= idx.value <= tx.num_groups):
            return (f"group index {idx.value} out of range "
                    f"(pattern has {tx.num_groups} groups)")
        return None

    def eval_cpu(self, ctx):
        from spark_rapids_tpu.expressions.base import Literal
        if not isinstance(self.children[2], Literal):
            raise NotImplementedError(
                "regexp_extract requires a literal group index")
        idx = self.children[2].value
        c = self.children[0].eval(ctx)
        data = materialize(c, ctx, np.dtype(object))
        rxs = self._pattern_regexes(ctx, len(data))
        valid = valid_array(c, ctx) & valid_array(
            self.children[1].eval(ctx), ctx)
        out = np.empty(len(data), dtype=object)
        for i in range(len(data)):
            if valid[i] and data[i] is not None and rxs[i] is not None:
                m = rxs[i].search(data[i])
                g = m.group(idx) if m is not None else ""
                out[i] = "" if g is None else g
            else:
                out[i] = None
        return TCol(out, valid, T.STRING)

    eval_tpu = eval_cpu


# ---------------------------------------------------------------------------
# volume string functions (reference: stringFunctions.scala — GpuReverse,
# GpuInitCap, GpuStringRepeat, GpuStringLPad/RPad, GpuStringLocate,
# GpuStringTranslate, GpuStringSplit, GpuConcatWs)
# ---------------------------------------------------------------------------

class Reverse(UnaryExpr):
    """reverse(str): per-row byte reversal within the row's length — one
    gather over the padded byte plane."""

    @property
    def data_type(self):
        return T.STRING

    def eval_tpu(self, ctx):
        xp = jnp()
        c = self.child.eval(ctx)
        chars, lens, valid = _dev_inputs(c, ctx, xp)
        w = chars.shape[1]
        pos = xp.arange(w)[None, :]
        src = xp.clip(lens[:, None] - 1 - pos, 0, w - 1)
        rev = xp.take_along_axis(chars, src, axis=1)
        out = xp.where(pos < lens[:, None], rev, 0)
        return TCol(out, valid, T.STRING, lengths=lens)

    def eval_cpu(self, ctx):
        c = self.child.eval(ctx)
        out, valid = _cpu_str_map(c, ctx, lambda s: s[::-1])
        return TCol(out, valid, T.STRING)


class InitCap(UnaryExpr):
    """initcap: uppercase the first letter of each word, lowercase the rest
    (ASCII on device, like Upper/Lower)."""

    @property
    def data_type(self):
        return T.STRING

    def eval_tpu(self, ctx):
        xp = jnp()
        c = self.child.eval(ctx)
        chars, lens, valid = _dev_inputs(c, ctx, xp)
        lower = xp.where((chars >= ord("A")) & (chars <= ord("Z")),
                         chars + 32, chars)
        is_alpha = ((lower >= ord("a")) & (lower <= ord("z")))
        prev_alpha = xp.concatenate(
            [xp.zeros_like(is_alpha[:, :1]), is_alpha[:, :-1]], axis=1)
        word_start = is_alpha & ~prev_alpha
        out = xp.where(word_start & (lower >= ord("a"))
                       & (lower <= ord("z")), lower - 32, lower)
        return TCol(out, valid, T.STRING, lengths=lens)

    def eval_cpu(self, ctx):
        import re as _re
        c = self.child.eval(ctx)

        def cap(s):
            return _re.sub(r"\w+", lambda m: m.group(0).capitalize()
                           if m.group(0)[0].isascii() else m.group(0),
                           s.lower())
        out, valid = _cpu_str_map(c, ctx, cap)
        return TCol(out, valid, T.STRING)


class StringRepeat(BinaryExpr):
    """repeat(str, n) — device for literal n (static output width)."""

    symbol = "repeat"

    @property
    def data_type(self):
        return T.STRING

    def tpu_supported(self, conf):
        from spark_rapids_tpu.expressions.base import Literal
        if not isinstance(self.right, Literal):
            return "repeat count must be a literal on the device"
        return None

    def eval_tpu(self, ctx):
        xp = jnp()
        from spark_rapids_tpu.columnar.column import bucket_strlen
        n = max(0, int(self.right.value or 0))
        c = self.left.eval(ctx)
        chars, lens, valid = _dev_inputs(c, ctx, xp)
        w = chars.shape[1]
        if n == 0:
            z = xp.zeros((ctx.row_count, 1), dtype=chars.dtype)
            return TCol(z, valid, T.STRING,
                        lengths=xp.zeros(ctx.row_count, dtype=np.int32))
        out_w = bucket_strlen(w * n)
        pos = xp.arange(out_w)[None, :]
        src = pos % xp.maximum(lens[:, None], 1)
        gathered = xp.take_along_axis(
            xp.pad(chars, ((0, 0), (0, max(0, out_w - w)))),
            xp.clip(src, 0, out_w - 1), axis=1)
        new_len = (lens * n).astype(np.int32)
        out = xp.where(pos < new_len[:, None], gathered, 0)
        return TCol(out, valid, T.STRING, lengths=new_len)

    def eval_cpu(self, ctx):
        from spark_rapids_tpu.expressions.base import materialize, valid_array
        c = self.left.eval(ctx)
        nt = self.right.eval(ctx)
        ns = materialize(nt, ctx, np.dtype(np.int64))
        nv = valid_array(nt, ctx)
        data = materialize(c, ctx, np.dtype(object))
        valid = valid_array(c, ctx) & nv
        out = np.empty(len(data), dtype=object)
        for i in range(len(data)):
            out[i] = data[i] * max(0, int(ns[i])) \
                if valid[i] and data[i] is not None else None
        return TCol(out, valid, T.STRING)


class _Pad(Expression):
    left_pad = True

    def __init__(self, child, length, pad=None):
        from spark_rapids_tpu.expressions.base import Literal
        if pad is None:
            pad = Literal(" ", T.STRING)
        super().__init__([child, length, pad])

    @property
    def data_type(self):
        return T.STRING

    def tpu_supported(self, conf):
        from spark_rapids_tpu.expressions.base import Literal
        if not (isinstance(self.children[1], Literal)
                and isinstance(self.children[2], Literal)):
            return "pad length/fill must be literals on the device"
        return None

    def eval_tpu(self, ctx):
        xp = jnp()
        from spark_rapids_tpu.columnar.column import bucket_strlen
        tgt = max(0, int(self.children[1].value or 0))
        pad = self.children[2].value or ""
        c = self.children[0].eval(ctx)
        chars, lens, valid = _dev_inputs(c, ctx, xp)
        w = chars.shape[1]
        out_w = bucket_strlen(max(1, tgt))
        pad_bytes = np.frombuffer((pad * (tgt or 1))[:max(1, tgt)]
                                  .encode()[:max(1, tgt)], dtype=np.uint8)
        pad_row = xp.asarray(np.pad(pad_bytes,
                                    (0, max(0, out_w - len(pad_bytes)))))
        pos = xp.arange(out_w)[None, :]
        trunc = xp.minimum(lens, tgt)
        if self.left_pad:
            n_pad = xp.maximum(tgt - lens, 0)[:, None]
            src = xp.clip(pos - n_pad, 0, max(w - 1, 0))
            from_str = xp.take_along_axis(
                xp.pad(chars, ((0, 0), (0, max(0, out_w - w)))),
                xp.clip(src, 0, out_w - 1), axis=1)
            out = xp.where(pos < n_pad, pad_row[None, :][
                xp.zeros_like(pos), xp.clip(pos, 0, out_w - 1)], from_str)
        else:
            padded = xp.pad(chars, ((0, 0), (0, max(0, out_w - w))))
            pad_region = pad_row[None, :][
                xp.zeros_like(pos),
                xp.clip(pos - trunc[:, None], 0, out_w - 1)]
            out = xp.where(pos < trunc[:, None], padded[:, :out_w],
                           pad_region)
        new_len = xp.full(ctx.row_count, tgt, dtype=np.int32)
        out = xp.where(pos < tgt, out, 0)
        return TCol(out, valid, T.STRING, lengths=new_len)

    def eval_cpu(self, ctx):
        from spark_rapids_tpu.expressions.base import materialize, valid_array
        c = self.children[0].eval(ctx)
        ln = self.children[1].eval(ctx)
        pd = self.children[2].eval(ctx)
        data = materialize(c, ctx, np.dtype(object))
        lens = materialize(ln, ctx, np.dtype(np.int64))
        pads = materialize(pd, ctx, np.dtype(object))
        valid = valid_array(c, ctx) & valid_array(ln, ctx) \
            & valid_array(pd, ctx)
        out = np.empty(len(data), dtype=object)
        for i in range(len(data)):
            if not valid[i] or data[i] is None or pads[i] is None:
                out[i] = None
                continue
            t = max(0, int(lens[i]))
            s = data[i]
            if len(s) >= t:
                out[i] = s[:t]
            elif not pads[i]:
                out[i] = s
            else:
                fill = (pads[i] * t)[:t - len(s)]
                out[i] = fill + s if self.left_pad else s + fill
        return TCol(out, valid, T.STRING)


class LPad(_Pad):
    left_pad = True


class RPad(_Pad):
    left_pad = False


class StringLocate(Expression):
    """locate(substr, str[, pos]) — 1-based index of the first occurrence at
    or after pos; 0 when absent (Spark semantics).  Device via the sliding
    window used by Contains."""

    def __init__(self, substr, string, start=None):
        from spark_rapids_tpu.expressions.base import Literal
        if start is None:
            start = Literal(1, T.INT)
        super().__init__([substr, string, start])

    @property
    def data_type(self):
        return T.INT

    def eval_tpu(self, ctx):
        xp = jnp()
        from spark_rapids_tpu.expressions.base import materialize, valid_array
        sub = self.children[0].eval(ctx)
        s = self.children[1].eval(ctx)
        st = self.children[2].eval(ctx)
        bc, bl, bvalid = _dev_inputs(sub, ctx, xp)
        ac, al, avalid = _dev_inputs(s, ctx, xp)
        starts0 = materialize(st, ctx, np.dtype(np.int64))
        wa, wb = ac.shape[1], bc.shape[1]
        j = xp.arange(wb)[None, None, :]
        starts = xp.arange(wa)[None, :, None]
        src = starts + j
        src_c = xp.broadcast_to(xp.clip(src, 0, wa - 1),
                                (ac.shape[0], wa, wb))
        gathered = xp.take_along_axis(ac[:, None, :], src_c, axis=2)
        in_pat = j < bl[:, None, None]
        eq = gathered == bc[:, None, :]
        match_at = xp.all(eq | ~in_pat, axis=2)          # [n, wa]
        pos_ok = (xp.arange(wa)[None, :] <= (al - bl)[:, None]) & \
            (xp.arange(wa)[None, :] >= (starts0[:, None] - 1))
        cand = xp.where(match_at & pos_ok, xp.arange(wa)[None, :], wa)
        first = xp.min(cand, axis=1)
        found = first < wa
        out = xp.where(found, first + 1, 0).astype(np.int32)
        # Spark: pos <= 0 -> 0; null substr/str -> null
        out = xp.where(starts0 <= 0, 0, out)
        valid = avalid & bvalid & valid_array(st, ctx)
        return TCol(out, valid, T.INT)

    def eval_cpu(self, ctx):
        from spark_rapids_tpu.expressions.base import materialize, valid_array
        sub = self.children[0].eval(ctx)
        s = self.children[1].eval(ctx)
        st = self.children[2].eval(ctx)
        subs = materialize(sub, ctx, np.dtype(object))
        strs = materialize(s, ctx, np.dtype(object))
        starts = materialize(st, ctx, np.dtype(np.int64))
        valid = valid_array(sub, ctx) & valid_array(s, ctx) \
            & valid_array(st, ctx)
        out = np.zeros(ctx.row_count, dtype=np.int32)
        for i in range(ctx.row_count):
            if not valid[i] or subs[i] is None or strs[i] is None:
                continue
            p = int(starts[i])
            if p <= 0:
                out[i] = 0
            else:
                out[i] = strs[i].find(subs[i], p - 1) + 1
        return TCol(out, valid, T.INT)


class StringTranslate(Expression):
    """translate(str, from, to) — per-byte substitution via a 256-entry
    lookup table built from the LITERAL from/to strings (device gather)."""

    def __init__(self, child, from_str, to_str):
        super().__init__([child, from_str, to_str])

    @property
    def data_type(self):
        return T.STRING

    def tpu_supported(self, conf):
        from spark_rapids_tpu.expressions.base import Literal
        if not (isinstance(self.children[1], Literal)
                and isinstance(self.children[2], Literal)):
            return "translate from/to must be literals on the device"
        f, t = self.children[1].value, self.children[2].value
        if any(ord(ch) > 127 for ch in (f or "") + (t or "")):
            return "non-ASCII translate is host tier"
        if len(f or "") > len(t or ""):
            return "translate with deletions is host tier (ragged output)"
        return None

    def _table(self):
        f = self.children[1].value or ""
        t = self.children[2].value or ""
        tab = np.arange(256, dtype=np.uint8)
        for fc, tc in zip(f, t):
            tab[ord(fc)] = ord(tc)
        return tab

    def eval_tpu(self, ctx):
        xp = jnp()
        c = self.children[0].eval(ctx)
        chars, lens, valid = _dev_inputs(c, ctx, xp)
        tab = xp.asarray(self._table())
        out = xp.take(tab, chars.astype(np.int32))
        pos = xp.arange(chars.shape[1])[None, :]
        out = xp.where(pos < lens[:, None], out, 0)
        return TCol(out, valid, T.STRING, lengths=lens)

    def eval_cpu(self, ctx):
        f = self.children[1].value or ""
        t = self.children[2].value or ""
        # Spark translate: chars beyond `to` are DELETED
        table = {ord(fc): (ord(t[i]) if i < len(t) else None)
                 for i, fc in enumerate(f)}
        c = self.children[0].eval(ctx)
        out, valid = _cpu_str_map(c, ctx, lambda s: s.translate(table))
        return TCol(out, valid, T.STRING)


class StringSplit(Expression):
    """split(str, delim[, limit]) -> array<string> (host tier: string-array
    outputs have no device plane; reference GpuStringSplit gates on the
    regex transpiler the same way)."""

    def __init__(self, child, delim, limit=None):
        from spark_rapids_tpu.expressions.base import Literal
        if limit is None:
            limit = Literal(-1, T.INT)
        super().__init__([child, delim, limit])

    @property
    def data_type(self):
        return T.ArrayType(T.STRING)

    def tpu_supported(self, conf):
        return "string-array output is host tier"

    def eval_cpu(self, ctx):
        import re as _re
        from spark_rapids_tpu import regexp as RX
        from spark_rapids_tpu.expressions.base import (Literal, materialize,
                                                       valid_array)
        delim = self.children[1]
        if not isinstance(delim, Literal):
            raise NotImplementedError("split delimiter must be a literal")
        limit = int(self.children[2].value)
        tx = RX.transpile(delim.value, RX.SPLIT)
        rx = _re.compile(tx.pattern)
        c = self.children[0].eval(ctx)
        data = materialize(c, ctx, np.dtype(object))
        valid = valid_array(c, ctx)
        out = np.empty(ctx.row_count, dtype=object)
        for i in range(ctx.row_count):
            if not valid[i] or data[i] is None:
                out[i] = None
                continue
            parts = rx.split(data[i], maxsplit=0 if limit <= 0
                             else limit - 1)
            if limit <= 0:
                # Spark drops trailing empty strings when limit <= 0
                while parts and parts[-1] == "":
                    parts.pop()
            out[i] = parts
        return TCol(out, valid, self.data_type)

    eval_tpu = eval_cpu


class ConcatWs(Expression):
    """concat_ws(sep, e1, ..., en): null inputs are SKIPPED (not nulling),
    per Spark semantics."""

    def __init__(self, sep, *exprs):
        super().__init__([sep] + list(exprs))

    @property
    def data_type(self):
        return T.STRING

    @property
    def nullable(self):
        return self.children[0].nullable

    def tpu_supported(self, conf):
        return "concat_ws is host tier (ragged skip-null concat)"

    def eval_cpu(self, ctx):
        from spark_rapids_tpu.expressions.base import materialize, valid_array
        sep_tc = self.children[0].eval(ctx)
        seps = materialize(sep_tc, ctx, np.dtype(object))
        sep_valid = valid_array(sep_tc, ctx)
        parts = [self.children[i].eval(ctx)
                 for i in range(1, len(self.children))]
        datas = [materialize(p, ctx, np.dtype(object)) for p in parts]
        valids = [valid_array(p, ctx) for p in parts]
        out = np.empty(ctx.row_count, dtype=object)
        ok = np.zeros(ctx.row_count, dtype=bool)
        for i in range(ctx.row_count):
            if not sep_valid[i] or seps[i] is None:
                out[i] = None
                continue
            vals = [d[i] for d, v in zip(datas, valids)
                    if v[i] and d[i] is not None]
            out[i] = seps[i].join(vals)
            ok[i] = True
        return TCol(out, ok, T.STRING)

    eval_tpu = eval_cpu
