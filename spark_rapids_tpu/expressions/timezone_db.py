"""Timezone database + UTC<->zone conversion kernels.

Reference: TimeZoneDB.scala (188) + JNI ``GpuTimeZoneDB`` — the reference
loads the tz database's transition tables to the device once and converts
timestamps with a binary-search kernel; non-UTC session timezones gate on
it (GpuOverrides nonUTC checks).

TPU design: parse the TZif files (RFC 8536) straight from the zoneinfo
path into numpy transition tables (UTC transition instants in MICROSECONDS
+ UTC offsets in seconds); conversion is ``searchsorted`` + gather — pure
elementwise device work that fuses like any other expression kernel.

Local->UTC handles the classic DST edge cases the way java.time (and so
Spark) does: ambiguous local times (fall-back overlap) take the EARLIER
offset; non-existent local times (spring-forward gap) shift forward by the
gap."""

from __future__ import annotations

import os
import struct
import threading
from typing import Dict, Optional, Tuple

import numpy as np

from spark_rapids_tpu import types as T
from spark_rapids_tpu.expressions.base import (EvalContext, TCol, jnp,
                                               valid_array)

_US = 1_000_000


def _find_tzfile(zone: str) -> str:
    import zoneinfo
    for base in zoneinfo.TZPATH:
        p = os.path.join(base, zone)
        if os.path.exists(p):
            return p
    # pip tzdata package fallback
    try:
        import importlib.resources as res
        import tzdata  # noqa: F401
        parts = zone.split("/")
        ref = res.files("tzdata.zoneinfo").joinpath(*parts)
        if ref.is_file():
            return str(ref)
    except Exception:   # noqa: BLE001
        pass
    raise KeyError(f"unknown timezone {zone!r}")


def _parse_tzif(path: str) -> Tuple[np.ndarray, np.ndarray]:
    """(transition instants in us, offsets in seconds) — offsets[i] applies
    from transitions[i] (transitions[0] = -inf sentinel)."""
    with open(path, "rb") as f:
        data = f.read()
    if data[:4] != b"TZif":
        raise ValueError(f"{path} is not a TZif file")

    def parse_block(off: int, long_format: bool):
        (isutcnt, isstdcnt, leapcnt, timecnt, typecnt,
         charcnt) = struct.unpack_from(">6I", data, off + 20)
        pos = off + 44
        tsize = 8 if long_format else 4
        fmt = ">%dq" % timecnt if long_format else ">%di" % timecnt
        trans = np.array(struct.unpack_from(fmt, data, pos), dtype=np.int64)
        pos += timecnt * tsize
        idx = np.frombuffer(data, dtype=np.uint8, count=timecnt,
                            offset=pos)
        pos += timecnt
        ttinfo = []
        for i in range(typecnt):
            utoff, isdst, abbrind = struct.unpack_from(">iBB", data, pos)
            ttinfo.append(utoff)
            pos += 6
        pos += charcnt + leapcnt * (tsize + 4) + isstdcnt + isutcnt
        return trans, idx, np.array(ttinfo, dtype=np.int64), pos

    version = data[4:5]
    trans, idx, offs, end = parse_block(0, False)
    if version in (b"2", b"3"):
        # v2+ block follows with 64-bit transitions (authoritative)
        trans, idx, offs, _ = parse_block(end, True)
    if len(trans) == 0:
        base = offs[0] if len(offs) else 0
        return (np.array([np.iinfo(np.int64).min // 2], dtype=np.int64),
                np.array([base], dtype=np.int64))
    # initial offset: the first ttinfo (per RFC, the type used before the
    # first transition is the first non-dst type; first entry is close
    # enough for the reference's supported range)
    instants = np.concatenate(
        [[np.iinfo(np.int64).min // 2], trans * _US])
    offsets = np.concatenate([[offs[idx[0]]], offs[idx]])
    return instants, offsets


class TimeZoneDB:
    """Per-zone transition tables, parsed once and cached (reference:
    GpuTimeZoneDB.cacheDatabase)."""

    _lock = threading.Lock()
    _cache: Dict[str, Tuple[np.ndarray, np.ndarray]] = {}

    @classmethod
    def tables(cls, zone: str) -> Tuple[np.ndarray, np.ndarray]:
        with cls._lock:
            t = cls._cache.get(zone)
        if t is not None:
            return t
        if zone in ("UTC", "Z", "GMT", "+00:00"):
            t = (np.array([np.iinfo(np.int64).min // 2], dtype=np.int64),
                 np.array([0], dtype=np.int64))
        else:
            t = _parse_tzif(_find_tzfile(zone))
        with cls._lock:
            cls._cache[zone] = t
        return t

    @classmethod
    def utc_to_local_us(cls, ts_us, zone: str, xp):
        """timestamp (us since epoch, UTC) -> local wall-clock micros."""
        instants, offsets = cls.tables(zone)
        instants = xp.asarray(instants)
        offsets = xp.asarray(offsets)
        i = xp.searchsorted(instants, ts_us, side="right") - 1
        i = xp.clip(i, 0, len(offsets) - 1)
        return ts_us + xp.take(offsets, i) * _US

    @classmethod
    def local_to_utc_us(cls, local_us, zone: str, xp):
        """local wall-clock micros -> UTC micros (earlier offset on
        overlap; gap times shift forward, java.time semantics)."""
        instants, offsets = cls.tables(zone)
        # each interval's local-time start, using its own offset
        lb = xp.asarray(instants + offsets * _US)
        offs = xp.asarray(offsets)
        inst = xp.asarray(instants)
        i = xp.searchsorted(lb, local_us, side="right") - 1
        i = xp.clip(i, 0, len(offs) - 1)
        # fall-back overlap: the PREVIOUS interval's local window ends at
        # instants[i] + offs[i-1] (its offset applied to its utc end); a
        # value still inside it is ambiguous -> earlier offset wins
        prev = xp.clip(i - 1, 0, len(offs) - 1)
        prev_end_local = xp.take(inst, i) + xp.take(offs, prev) * _US
        amb = (local_us < prev_end_local) & (i > 0)
        idx = xp.where(amb, prev, i)
        # spring-forward gap values resolve against the pre-transition
        # offset naturally (searchsorted lands on it), which shifts them
        # forward by the gap — java.time semantics
        return local_us - xp.take(offs, idx) * _US


# ---------------------------------------------------------------------------
# expressions
# ---------------------------------------------------------------------------

from spark_rapids_tpu.expressions.arithmetic import UnaryExpr  # noqa: E402


class _TzConvert(UnaryExpr):
    to_local = True

    def __init__(self, child, zone: str):
        super().__init__(child)
        if not isinstance(zone, str):
            raise TypeError("timezone must be a literal string")
        self.zone = zone
        TimeZoneDB.tables(zone)   # validate eagerly (planner-time error)

    @property
    def data_type(self):
        return T.TIMESTAMP

    def sql(self):
        return f"{self.name}({self.child.sql()}, '{self.zone}')"

    def _eval(self, ctx, xp):
        from spark_rapids_tpu.expressions.base import materialize
        c = self.child.eval(ctx)
        data = materialize(c, ctx, np.dtype(np.int64))
        if self.to_local:
            out = TimeZoneDB.utc_to_local_us(data, self.zone, xp)
        else:
            out = TimeZoneDB.local_to_utc_us(data, self.zone, xp)
        return TCol(out, valid_array(c, ctx), T.TIMESTAMP)

    def eval_tpu(self, ctx):
        return self._eval(ctx, jnp())

    def eval_cpu(self, ctx):
        return self._eval(ctx, np)


class FromUTCTimestamp(_TzConvert):
    """from_utc_timestamp(ts, zone) (reference GpuFromUTCTimestamp via
    GpuTimeZoneDB)."""
    to_local = True


class ToUTCTimestamp(_TzConvert):
    """to_utc_timestamp(ts, zone)."""
    to_local = False


# ---------------------------------------------------------------------------
# julian <-> proleptic-gregorian rebase (reference: DateTimeRebase JNI +
# datetimeRebaseUtils.scala — parquet LEGACY mode wrote julian days)
# ---------------------------------------------------------------------------

_SWITCH_DAYS = -141427          # 1582-10-15 in proleptic gregorian days
_JDN_EPOCH = 2440588            # julian day number of 1970-01-01 gregorian


def _julian_civil_from_days(n: np.ndarray):
    """Hybrid day count (julian calendar) -> (y, m, d), vectorized
    (standard JDN->julian-calendar arithmetic)."""
    jdn = n + _JDN_EPOCH
    a = jdn + 32082
    b = (4 * a + 3) // 1461
    c = a - (1461 * b) // 4
    d2 = (5 * c + 2) // 153
    day = c - (153 * d2 + 2) // 5 + 1
    month = d2 + 3 - 12 * (d2 // 10)
    year = b - 4800 + d2 // 10
    return year, month, day


def _days_from_julian_civil(y, m, d):
    """(julian calendar y, m, d) -> hybrid day count, vectorized."""
    a = (14 - m) // 12
    y2 = y + 4800 - a
    m2 = m + 12 * a - 3
    jdn = d + (153 * m2 + 2) // 5 + 365 * y2 + y2 // 4 - 32083
    return jdn - _JDN_EPOCH


def rebase_julian_to_gregorian_days(days: np.ndarray) -> np.ndarray:
    """Legacy hybrid-calendar day counts (julian before the 1582-10-15
    switch) -> proleptic gregorian for the SAME civil date — exact via
    JDN round-trip, not a drift table (reference: DateTimeRebase JNI /
    RebaseDateTime.rebaseJulianToGregorianDays)."""
    from spark_rapids_tpu.expressions.datetime_exprs import _days_from_civil
    days = np.asarray(days, dtype=np.int64)
    old = days < _SWITCH_DAYS
    if not old.any():
        return days.copy()
    y, m, d = _julian_civil_from_days(days[old])
    out = days.copy()
    out[old] = _days_from_civil(np.asarray(y, dtype=np.int64),
                                np.asarray(m, dtype=np.int64),
                                np.asarray(d, dtype=np.int64), np)
    return out


def rebase_gregorian_to_julian_days(days: np.ndarray) -> np.ndarray:
    from spark_rapids_tpu.expressions.datetime_exprs import _civil_from_days
    days = np.asarray(days, dtype=np.int64)
    old = days < _SWITCH_DAYS
    if not old.any():
        return days.copy()
    y, m, d = _civil_from_days(days[old], np)
    out = days.copy()
    out[old] = _days_from_julian_civil(y.astype(np.int64),
                                       m.astype(np.int64),
                                       d.astype(np.int64))
    return out


def rebase_julian_to_gregorian_micros(us: np.ndarray) -> np.ndarray:
    us = np.asarray(us, dtype=np.int64)
    days = np.floor_divide(us, 86400 * _US)
    rem = us - days * 86400 * _US
    return rebase_julian_to_gregorian_days(days) * 86400 * _US + rem


def rebase_gregorian_to_julian_micros(us: np.ndarray) -> np.ndarray:
    us = np.asarray(us, dtype=np.int64)
    days = np.floor_divide(us, 86400 * _US)
    rem = us - days * 86400 * _US
    return rebase_gregorian_to_julian_days(days) * 86400 * _US + rem


# plan-rewrite registrations
from spark_rapids_tpu.plan import typechecks as TS  # noqa: E402
from spark_rapids_tpu.plan.overrides import register_expr  # noqa: E402

for _cls in (FromUTCTimestamp, ToUTCTimestamp):
    register_expr(_cls, TS.ALL_BASIC)
