"""Window expressions: specs, frames, ranking/offset functions.

Reference: GpuWindowExpression.scala (2133 LoC) maps Spark window specs to
cuDF rolling/scan aggregations; GpuWindowExec variants pick batched
algorithms (window/GpuWindowExecMeta).  Here a window expression =
``WindowExpression(function, WindowSpecDef)`` where the function is either
a ranking/offset function (RowNumber/Rank/DenseRank/Lag/Lead) or a regular
AggregateFunction evaluated over a frame; the device lowering is one fused
sort + segmented-scan program per spec (ops/window_ops.py).
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Sequence, Tuple

from spark_rapids_tpu import types as T
from spark_rapids_tpu.expressions.base import Expression, Literal

# frame bound sentinels (Spark Window.unboundedPreceding/Following analogs)
UNBOUNDED_PRECEDING = -(1 << 62)
UNBOUNDED_FOLLOWING = (1 << 62)
CURRENT_ROW = 0


@dataclasses.dataclass(frozen=True)
class WindowFrame:
    """kind: "rows" or "range"; bounds are row/peer offsets with the
    sentinels above.  Spark default with an ORDER BY: RANGE BETWEEN
    UNBOUNDED PRECEDING AND CURRENT ROW (peer rows included); without:
    the whole partition."""
    kind: str = "range"
    lo: int = UNBOUNDED_PRECEDING
    hi: int = CURRENT_ROW

    @property
    def lo_unbounded(self) -> bool:
        return self.lo <= UNBOUNDED_PRECEDING

    @property
    def hi_unbounded(self) -> bool:
        return self.hi >= UNBOUNDED_FOLLOWING

    def sig(self) -> Tuple:
        lo = None if self.lo_unbounded else int(self.lo)
        hi = None if self.hi_unbounded else int(self.hi)
        return (self.kind, lo, hi)

    def desc(self) -> str:
        def b(v, side):
            if v <= UNBOUNDED_PRECEDING:
                return "UNBOUNDED PRECEDING"
            if v >= UNBOUNDED_FOLLOWING:
                return "UNBOUNDED FOLLOWING"
            if v == 0:
                return "CURRENT ROW"
            return f"{-v} PRECEDING" if v < 0 else f"{v} FOLLOWING"
        return f"{self.kind.upper()} BETWEEN {b(self.lo, 0)} AND {b(self.hi, 1)}"


WHOLE_PARTITION = WindowFrame("range", UNBOUNDED_PRECEDING,
                              UNBOUNDED_FOLLOWING)


@dataclasses.dataclass
class WindowSpecDef:
    """partition_exprs + order (expr, ascending, nulls_first) + frame."""
    partition_exprs: List[Expression]
    order_specs: List[Tuple[Expression, bool, bool]]
    frame: Optional[WindowFrame] = None

    def effective_frame(self) -> WindowFrame:
        if self.frame is not None:
            return self.frame
        if self.order_specs:
            return WindowFrame("range", UNBOUNDED_PRECEDING, CURRENT_ROW)
        return WHOLE_PARTITION

    def group_key(self) -> Tuple:
        """Specs with the same partition/order share one WindowExec pass
        (structural equality; the frame may differ per expression)."""
        return (tuple(e.sql() for e in self.partition_exprs),
                tuple((e.sql(), a, nf) for e, a, nf in self.order_specs))

    def desc(self) -> str:
        p = ", ".join(e.sql() for e in self.partition_exprs)
        o = ", ".join(f"{e.sql()} {'ASC' if a else 'DESC'}"
                      for e, a, nf in self.order_specs)
        return (f"PARTITION BY {p} ORDER BY {o} "
                f"{self.effective_frame().desc()}")


class WindowExpression(Expression):
    """function OVER spec — the planner extracts these from projections and
    lowers each spec group to one WindowExec (reference: Spark's
    ExtractWindowExpressions + GpuWindowExecMeta).

    The spec's partition/order expressions ARE children (after the
    function) so generic tree transforms — reference binding above all —
    reach them; ``with_children`` rebuilds the spec from the new list."""

    foldable = False   # never constant-fold aggregation/window context

    def __init__(self, function: Expression, spec: WindowSpecDef):
        super().__init__([function] + list(spec.partition_exprs) +
                         [e for e, _, _ in spec.order_specs])
        self._n_part = len(spec.partition_exprs)
        self._order_dirs = [(a, nf) for _, a, nf in spec.order_specs]
        self._frame = spec.frame

    @property
    def function(self) -> Expression:
        return self.children[0]

    @property
    def spec(self) -> WindowSpecDef:
        pk = self.children[1:1 + self._n_part]
        ok = self.children[1 + self._n_part:]
        return WindowSpecDef(list(pk),
                             [(e, a, nf) for e, (a, nf) in
                              zip(ok, self._order_dirs)], self._frame)

    @property
    def data_type(self):
        return self.function.data_type

    def sql(self):
        return f"{self.function.sql()} OVER ({self.spec.desc()})"


class WindowFunction(Expression):
    """Ranking/offset functions valid only inside a window spec."""

    foldable = False   # never constant-fold aggregation/window context
    is_window_function = True

    def over(self, spec) -> WindowExpression:
        return WindowExpression(self, _to_spec(spec))


def _to_spec(spec) -> WindowSpecDef:
    from spark_rapids_tpu.functions import WindowBuilder
    if isinstance(spec, WindowSpecDef):
        return spec
    if isinstance(spec, WindowBuilder):
        return spec._spec
    raise TypeError(f"not a window spec: {spec!r}")


class RowNumber(WindowFunction):
    @property
    def data_type(self):
        return T.INT

    @property
    def nullable(self):
        return False

    def sql(self):
        return "row_number()"


class Rank(WindowFunction):
    @property
    def data_type(self):
        return T.INT

    @property
    def nullable(self):
        return False

    def sql(self):
        return "rank()"


class DenseRank(WindowFunction):
    @property
    def data_type(self):
        return T.INT

    @property
    def nullable(self):
        return False

    def sql(self):
        return "dense_rank()"


class NTile(WindowFunction):
    def __init__(self, n: int):
        super().__init__([])
        if int(n) < 1:
            raise ValueError(f"ntile() requires n >= 1, got {n}")
        self.n = int(n)

    @property
    def data_type(self):
        return T.INT

    def sql(self):
        return f"ntile({self.n})"


class _OffsetFunction(WindowFunction):
    """lag/lead: value at a fixed row offset within the partition; out of
    range yields the default (reference: GpuLag/GpuLead in
    GpuWindowExpression.scala)."""

    direction = 0

    def __init__(self, child: Expression, offset: int = 1,
                 default: Optional[Expression] = None):
        super().__init__([child])
        self.offset = int(offset)
        self.default = default

    @property
    def data_type(self):
        return self.children[0].data_type

    def sql(self):
        return (f"{type(self).__name__.lower()}({self.children[0].sql()}, "
                f"{self.offset})")


class Lag(_OffsetFunction):
    direction = -1


class Lead(_OffsetFunction):
    direction = 1
