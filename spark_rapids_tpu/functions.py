"""User-facing expression constructors (pyspark.sql.functions analog).

The reference exposes Spark's own function surface; this module is the
standalone equivalent for our DataFrame API.  Grows with each expression /
aggregate milestone.
"""

from __future__ import annotations

from spark_rapids_tpu.expressions.base import (Alias, Expression,  # noqa: F401
                                               col, lit)


def pandas_udf(fn, return_type):
    """Scalar pandas UDF factory (reference: PythonUDF +
    GpuArrowEvalPythonExec); see expressions.python_udf."""
    from spark_rapids_tpu.expressions.python_udf import pandas_udf as _pu
    return _pu(fn, return_type)


def _expr(e) -> Expression:
    if isinstance(e, Expression):
        return e
    if isinstance(e, str):
        return col(e)
    return lit(e)


def asc(e, nulls_first: bool = True):
    from spark_rapids_tpu.exec.sort import SortSpec
    return SortSpec(_expr(e), True, nulls_first)


def desc(e, nulls_first: bool = False):
    from spark_rapids_tpu.exec.sort import SortSpec
    return SortSpec(_expr(e), False, nulls_first)


# -- aggregates --------------------------------------------------------------

def sum(e):  # noqa: A001 - mirrors pyspark.sql.functions naming
    from spark_rapids_tpu.expressions.aggregates import Sum
    return Sum(_expr(e))


def count(e="*"):
    from spark_rapids_tpu.expressions.aggregates import Count
    if isinstance(e, str) and e == "*":
        return Count(lit(1))
    return Count(_expr(e))


def count_distinct(e):
    from spark_rapids_tpu.expressions.aggregates import CountDistinct
    return CountDistinct(_expr(e))


countDistinct = count_distinct


def min(e):  # noqa: A001
    from spark_rapids_tpu.expressions.aggregates import Min
    return Min(_expr(e))


def max(e):  # noqa: A001
    from spark_rapids_tpu.expressions.aggregates import Max
    return Max(_expr(e))


def avg(e):
    from spark_rapids_tpu.expressions.aggregates import Average
    return Average(_expr(e))


mean = avg


def first(e, ignore_nulls=False):
    from spark_rapids_tpu.expressions.aggregates import First
    return First(_expr(e), ignore_nulls)


def last(e, ignore_nulls=False):
    from spark_rapids_tpu.expressions.aggregates import Last
    return Last(_expr(e), ignore_nulls)


def var_samp(e):
    from spark_rapids_tpu.expressions.aggregates import VarianceSamp
    return VarianceSamp(_expr(e))


def var_pop(e):
    from spark_rapids_tpu.expressions.aggregates import VariancePop
    return VariancePop(_expr(e))


def stddev(e):
    from spark_rapids_tpu.expressions.aggregates import StddevSamp
    return StddevSamp(_expr(e))


stddev_samp = stddev


def stddev_pop(e):
    from spark_rapids_tpu.expressions.aggregates import StddevPop
    return StddevPop(_expr(e))


# -- hints -------------------------------------------------------------------

def broadcast(df):
    """Marks a DataFrame as broadcastable for joins (pyspark
    functions.broadcast analog; reference: GpuBroadcastHashJoinExec)."""
    import copy
    out = copy.copy(df)
    out._broadcast_hint = True
    return out


# -- window functions --------------------------------------------------------

class WindowBuilder:
    """pyspark.sql.Window analog: Window.partition_by("k").order_by("v")
    [.rows_between(a, b) | .range_between(a, b)]."""

    def __init__(self, spec=None):
        from spark_rapids_tpu.expressions.window_exprs import WindowSpecDef
        self._spec = spec or WindowSpecDef([], [], None)

    def partition_by(self, *cols) -> "WindowBuilder":
        from spark_rapids_tpu.expressions.window_exprs import WindowSpecDef
        return WindowBuilder(WindowSpecDef(
            [_expr(c) for c in cols], self._spec.order_specs,
            self._spec.frame))

    partitionBy = partition_by

    def order_by(self, *cols) -> "WindowBuilder":
        from spark_rapids_tpu.exec.sort import SortSpec
        from spark_rapids_tpu.expressions.window_exprs import WindowSpecDef
        specs = []
        for c in cols:
            if isinstance(c, SortSpec):
                specs.append((c.expr, c.ascending, c.effective_nulls_first))
            else:
                specs.append((_expr(c), True, True))
        return WindowBuilder(WindowSpecDef(
            self._spec.partition_exprs, specs, self._spec.frame))

    orderBy = order_by

    def rows_between(self, start: int, end: int) -> "WindowBuilder":
        from spark_rapids_tpu.expressions.window_exprs import (WindowFrame,
                                                               WindowSpecDef)
        return WindowBuilder(WindowSpecDef(
            self._spec.partition_exprs, self._spec.order_specs,
            WindowFrame("rows", int(start), int(end))))

    rowsBetween = rows_between

    def range_between(self, start: int, end: int) -> "WindowBuilder":
        from spark_rapids_tpu.expressions.window_exprs import (WindowFrame,
                                                               WindowSpecDef)
        return WindowBuilder(WindowSpecDef(
            self._spec.partition_exprs, self._spec.order_specs,
            WindowFrame("range", int(start), int(end))))

    rangeBetween = range_between


class _WindowNamespace:
    """The class-level entry points: Window.partition_by(...), plus the
    frame-bound sentinels."""

    @property
    def unboundedPreceding(self):
        from spark_rapids_tpu.expressions import window_exprs as W
        return W.UNBOUNDED_PRECEDING

    unbounded_preceding = unboundedPreceding

    @property
    def unboundedFollowing(self):
        from spark_rapids_tpu.expressions import window_exprs as W
        return W.UNBOUNDED_FOLLOWING

    unbounded_following = unboundedFollowing

    currentRow = current_row = 0

    def partition_by(self, *cols):
        return WindowBuilder().partition_by(*cols)

    partitionBy = partition_by

    def order_by(self, *cols):
        return WindowBuilder().order_by(*cols)

    orderBy = order_by


Window = _WindowNamespace()


def row_number():
    from spark_rapids_tpu.expressions.window_exprs import RowNumber
    return RowNumber([])


def rank():
    from spark_rapids_tpu.expressions.window_exprs import Rank
    return Rank([])


def dense_rank():
    from spark_rapids_tpu.expressions.window_exprs import DenseRank
    return DenseRank([])


def ntile(n: int):
    from spark_rapids_tpu.expressions.window_exprs import NTile
    return NTile(n)


def lag(e, offset: int = 1, default=None):
    from spark_rapids_tpu.expressions.window_exprs import Lag
    return Lag(_expr(e), offset, None if default is None else lit(default))


def lead(e, offset: int = 1, default=None):
    from spark_rapids_tpu.expressions.window_exprs import Lead
    return Lead(_expr(e), offset, None if default is None else lit(default))


# -- regular expressions (reference: RLike/RegExpReplace/RegExpExtract rules) --

def rlike(e, pattern):
    from spark_rapids_tpu.expressions.strings import RLike
    return RLike(_expr(e), _pattern_expr(pattern))


def regexp_replace(e, pattern, replacement: str):
    from spark_rapids_tpu.expressions.strings import RegExpReplace
    from spark_rapids_tpu.expressions.base import lit
    return RegExpReplace(_expr(e), _pattern_expr(pattern), lit(replacement))


def regexp_extract(e, pattern, idx: int = 1):
    from spark_rapids_tpu.expressions.strings import RegExpExtract
    from spark_rapids_tpu.expressions.base import lit
    return RegExpExtract(_expr(e), _pattern_expr(pattern), lit(idx))


def _pattern_expr(pattern) -> Expression:
    """Literal string patterns stay literals (transpiled + taggable for the
    device tier); Expression patterns are per-row (host tier, like Spark's
    non-foldable regexp arguments)."""
    from spark_rapids_tpu.expressions.base import lit
    return pattern if isinstance(pattern, Expression) else lit(pattern)


# -- collection functions (reference: collectionOperations registrations) ----

def array(*cols):
    from spark_rapids_tpu.expressions.collections import CreateArray
    return CreateArray(*[_expr(c) for c in cols])


def size(e):
    from spark_rapids_tpu.expressions.collections import Size
    return Size(_expr(e))


def element_at(e, idx):
    from spark_rapids_tpu.expressions.base import lit
    from spark_rapids_tpu.expressions.collections import ElementAt
    idx = idx if isinstance(idx, Expression) else lit(idx)
    return ElementAt(_expr(e), idx)


def get_array_item(e, idx):
    from spark_rapids_tpu.expressions.base import lit
    from spark_rapids_tpu.expressions.collections import GetArrayItem
    idx = idx if isinstance(idx, Expression) else lit(idx)
    return GetArrayItem(_expr(e), idx)


def array_contains(e, value):
    from spark_rapids_tpu.expressions.base import lit
    from spark_rapids_tpu.expressions.collections import ArrayContains
    value = value if isinstance(value, Expression) else lit(value)
    return ArrayContains(_expr(e), value)


def array_min(e):
    from spark_rapids_tpu.expressions.collections import ArrayMin
    return ArrayMin(_expr(e))


def array_max(e):
    from spark_rapids_tpu.expressions.collections import ArrayMax
    return ArrayMax(_expr(e))


def sort_array(e, asc: bool = True):
    from spark_rapids_tpu.expressions.base import lit
    from spark_rapids_tpu.expressions.collections import SortArray
    return SortArray(_expr(e), lit(asc))


def slice(e, start, length):  # noqa: A001 - pyspark naming
    from spark_rapids_tpu.expressions.base import lit
    from spark_rapids_tpu.expressions.collections import Slice
    start = start if isinstance(start, Expression) else lit(start)
    length = length if isinstance(length, Expression) else lit(length)
    return Slice(_expr(e), start, length)


def array_repeat(value, count):
    from spark_rapids_tpu.expressions.base import lit
    from spark_rapids_tpu.expressions.collections import ArrayRepeat
    value = value if isinstance(value, Expression) else lit(value)
    count = count if isinstance(count, Expression) else lit(count)
    return ArrayRepeat(value, count)


def transform(e, fn):
    from spark_rapids_tpu.expressions.collections import ArrayTransform
    return ArrayTransform(_expr(e), fn)


def exists(e, fn):
    from spark_rapids_tpu.expressions.collections import ArrayExists
    return ArrayExists(_expr(e), fn)


def forall(e, fn):
    from spark_rapids_tpu.expressions.collections import ArrayForAll
    return ArrayForAll(_expr(e), fn)


def filter(e, fn):  # noqa: A001 - pyspark naming
    from spark_rapids_tpu.expressions.collections import ArrayFilter
    return ArrayFilter(_expr(e), fn)


def aggregate(e, zero, merge, finish=None):
    from spark_rapids_tpu.expressions.collections import ArrayAggregate
    return ArrayAggregate(_expr(e), zero, merge, finish)


def named_struct(**fields):
    from spark_rapids_tpu.expressions.collections import CreateNamedStruct
    return CreateNamedStruct(list(fields.keys()),
                             [_expr(v) for v in fields.values()])


def create_map(*kv):
    from spark_rapids_tpu.expressions.collections import CreateMap
    return CreateMap(*[_expr(c) for c in kv])


def map_keys(e):
    from spark_rapids_tpu.expressions.collections import MapKeys
    return MapKeys(_expr(e))


def map_values(e):
    from spark_rapids_tpu.expressions.collections import MapValues
    return MapValues(_expr(e))


def get_struct_field(e, name: str):
    from spark_rapids_tpu.expressions.collections import GetStructField
    return GetStructField(_expr(e), name)


# -- JSON / URL functions (reference: JSONUtils + ParseURI JNI kernels) ------

def get_json_object(e, path: str):
    from spark_rapids_tpu.expressions.base import lit
    from spark_rapids_tpu.expressions.json_exprs import GetJsonObject
    return GetJsonObject(_expr(e), path if isinstance(path, Expression)
                         else lit(path))


def json_tuple(e, *fields: str):
    from spark_rapids_tpu.expressions.json_exprs import JsonTuple
    return JsonTuple(_expr(e), *fields)


def from_json(e, schema):
    from spark_rapids_tpu.expressions.json_exprs import JsonToStructs
    return JsonToStructs(_expr(e), schema)


def to_json(e):
    from spark_rapids_tpu.expressions.json_exprs import StructsToJson
    return StructsToJson(_expr(e))


def parse_url(e, part: str, key=None):
    from spark_rapids_tpu.expressions.base import lit
    from spark_rapids_tpu.expressions.json_exprs import ParseUrl
    part = part if isinstance(part, Expression) else lit(part)
    if key is not None and not isinstance(key, Expression):
        key = lit(key)
    return ParseUrl(_expr(e), part, key)


# -- string function wrappers ------------------------------------------------

def length(e):
    from spark_rapids_tpu.expressions.strings import Length
    return Length(_expr(e))


def upper(e):
    from spark_rapids_tpu.expressions.strings import Upper
    return Upper(_expr(e))


def lower(e):
    from spark_rapids_tpu.expressions.strings import Lower
    return Lower(_expr(e))


def substring(e, pos: int, length_: int):
    from spark_rapids_tpu.expressions.base import lit
    from spark_rapids_tpu.expressions.strings import Substring
    return Substring(_expr(e), lit(pos), lit(length_))


def concat(*cols):
    from spark_rapids_tpu.expressions.strings import Concat
    return Concat(*[_expr(c) for c in cols])


def trim(e):
    from spark_rapids_tpu.expressions.strings import Trim
    return Trim(_expr(e))


def from_utc_timestamp(e, zone: str):
    from spark_rapids_tpu.expressions.timezone_db import FromUTCTimestamp
    return FromUTCTimestamp(_expr(e), zone)


def to_utc_timestamp(e, zone: str):
    from spark_rapids_tpu.expressions.timezone_db import ToUTCTimestamp
    return ToUTCTimestamp(_expr(e), zone)


# -- collection/percentile aggregates ---------------------------------------

def collect_list(e):
    from spark_rapids_tpu.expressions.aggregates import CollectList
    return CollectList(_expr(e))


def collect_set(e):
    from spark_rapids_tpu.expressions.aggregates import CollectSet
    return CollectSet(_expr(e))


def percentile(e, percentage):
    from spark_rapids_tpu.expressions.aggregates import Percentile
    return Percentile(_expr(e), percentage)


def approx_percentile(e, percentage, accuracy: int = 10000):
    from spark_rapids_tpu.expressions.aggregates import ApproximatePercentile
    return ApproximatePercentile(_expr(e), percentage, accuracy)


def reverse(e):
    from spark_rapids_tpu.expressions.strings import Reverse
    return Reverse(_expr(e))


def initcap(e):
    from spark_rapids_tpu.expressions.strings import InitCap
    return InitCap(_expr(e))


def repeat(e, n: int):
    from spark_rapids_tpu.expressions.base import lit
    from spark_rapids_tpu.expressions.strings import StringRepeat
    return StringRepeat(_expr(e), n if isinstance(n, Expression) else lit(n))


def lpad(e, length_: int, pad: str = " "):
    from spark_rapids_tpu.expressions.base import lit
    from spark_rapids_tpu.expressions.strings import LPad
    return LPad(_expr(e), lit(length_), lit(pad))


def rpad(e, length_: int, pad: str = " "):
    from spark_rapids_tpu.expressions.base import lit
    from spark_rapids_tpu.expressions.strings import RPad
    return RPad(_expr(e), lit(length_), lit(pad))


def locate(substr, e, pos: int = 1):
    from spark_rapids_tpu.expressions.base import lit
    from spark_rapids_tpu.expressions.strings import StringLocate
    return StringLocate(lit(substr) if not isinstance(substr, Expression)
                        else substr, _expr(e), lit(pos))


def instr(e, substr):
    return locate(substr, e, 1)


def translate(e, from_str: str, to_str: str):
    from spark_rapids_tpu.expressions.base import lit
    from spark_rapids_tpu.expressions.strings import StringTranslate
    return StringTranslate(_expr(e), lit(from_str), lit(to_str))


def split(e, pattern: str, limit: int = -1):
    from spark_rapids_tpu.expressions.base import lit
    from spark_rapids_tpu.expressions.strings import StringSplit
    return StringSplit(_expr(e), lit(pattern), lit(limit))


def concat_ws(sep: str, *cols):
    from spark_rapids_tpu.expressions.base import lit
    from spark_rapids_tpu.expressions.strings import ConcatWs
    return ConcatWs(lit(sep) if not isinstance(sep, Expression) else sep,
                    *[_expr(c) for c in cols])


def bloom_filter(df, column, num_bits: int = 1 << 20, num_hashes: int = 3):
    """Builds a BloomFilter from a DataFrame column (join pruning)."""
    from spark_rapids_tpu.expressions.bloom import BloomFilter
    return BloomFilter.build(df, column, num_bits, num_hashes)


def might_contain(bloom, e):
    from spark_rapids_tpu.expressions.bloom import BloomMightContain
    return BloomMightContain(bloom, _expr(e))


# -- datetime function wrappers ----------------------------------------------

def add_months(e, n):
    from spark_rapids_tpu.expressions.base import lit
    from spark_rapids_tpu.expressions.datetime_exprs import AddMonths
    return AddMonths(_expr(e), n if isinstance(n, Expression) else lit(n))


def months_between(end, start):
    from spark_rapids_tpu.expressions.datetime_exprs import MonthsBetween
    return MonthsBetween(_expr(end), _expr(start))


def next_day(e, day_of_week: str):
    from spark_rapids_tpu.expressions.datetime_exprs import NextDay
    return NextDay(_expr(e), day_of_week)


def trunc(e, fmt: str):
    from spark_rapids_tpu.expressions.datetime_exprs import TruncDate
    return TruncDate(_expr(e), fmt)


def date_format(e, pattern: str):
    from spark_rapids_tpu.expressions.datetime_exprs import DateFormat
    return DateFormat(_expr(e), pattern)
