"""User-facing expression constructors (pyspark.sql.functions analog).

The reference exposes Spark's own function surface; this module is the
standalone equivalent for our DataFrame API.  Grows with each expression /
aggregate milestone.
"""

from __future__ import annotations

from spark_rapids_tpu.expressions.base import (Alias, Expression,  # noqa: F401
                                               col, lit)


def _expr(e) -> Expression:
    if isinstance(e, Expression):
        return e
    if isinstance(e, str):
        return col(e)
    return lit(e)


def asc(e, nulls_first: bool = True):
    from spark_rapids_tpu.exec.sort import SortSpec
    return SortSpec(_expr(e), True, nulls_first)


def desc(e, nulls_first: bool = False):
    from spark_rapids_tpu.exec.sort import SortSpec
    return SortSpec(_expr(e), False, nulls_first)


# -- aggregates --------------------------------------------------------------

def sum(e):  # noqa: A001 - mirrors pyspark.sql.functions naming
    from spark_rapids_tpu.expressions.aggregates import Sum
    return Sum(_expr(e))


def count(e="*"):
    from spark_rapids_tpu.expressions.aggregates import Count
    if isinstance(e, str) and e == "*":
        return Count(lit(1))
    return Count(_expr(e))


def min(e):  # noqa: A001
    from spark_rapids_tpu.expressions.aggregates import Min
    return Min(_expr(e))


def max(e):  # noqa: A001
    from spark_rapids_tpu.expressions.aggregates import Max
    return Max(_expr(e))


def avg(e):
    from spark_rapids_tpu.expressions.aggregates import Average
    return Average(_expr(e))


mean = avg


def first(e, ignore_nulls=False):
    from spark_rapids_tpu.expressions.aggregates import First
    return First(_expr(e), ignore_nulls)


def last(e, ignore_nulls=False):
    from spark_rapids_tpu.expressions.aggregates import Last
    return Last(_expr(e), ignore_nulls)


def var_samp(e):
    from spark_rapids_tpu.expressions.aggregates import VarianceSamp
    return VarianceSamp(_expr(e))


def var_pop(e):
    from spark_rapids_tpu.expressions.aggregates import VariancePop
    return VariancePop(_expr(e))


def stddev(e):
    from spark_rapids_tpu.expressions.aggregates import StddevSamp
    return StddevSamp(_expr(e))


stddev_samp = stddev


def stddev_pop(e):
    from spark_rapids_tpu.expressions.aggregates import StddevPop
    return StddevPop(_expr(e))


# -- hints -------------------------------------------------------------------

def broadcast(df):
    """Marks a DataFrame as broadcastable for joins (pyspark
    functions.broadcast analog; reference: GpuBroadcastHashJoinExec)."""
    import copy
    out = copy.copy(df)
    out._broadcast_hint = True
    return out
