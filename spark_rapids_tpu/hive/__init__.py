"""Hive integration: text-table scan/write + row-based Hive UDF
passthrough (reference: org/apache/spark/sql/hive/rapids/ — 9 files,
GpuHiveTableScanExec.scala, GpuHiveTextFileFormat.scala,
rowBasedHiveUDFs.scala)."""

from spark_rapids_tpu.hive.table import (CpuHiveTextScanExec,  # noqa: F401
                                         write_hive_text)
