"""Hive text tables (LazySimpleSerDe subset).

Reference: ``GpuHiveTableScanExec.scala`` + ``GpuHiveTextFileFormat.scala``
— Hive's default text serde: '\\x01' field delimiter, ``\\N`` null
sentinel, no header, no quoting (an escape char protects delimiters), and
the schema comes from the metastore (here: passed by the caller, like the
reference receives it from the catalog relation).  Supported serde
properties: ``field.delim``, ``serialization.null.format``,
``escape.delim`` — the same subset the reference checks before accepting a
table (GpuHiveTextFileFormat.checkIfEnabled tagging).
"""

from __future__ import annotations

import os
from typing import Iterator, List, Optional, Sequence

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.batch import (HostColumnarBatch,
                                             batch_from_arrow)
from spark_rapids_tpu.io.multifile import (AUTO, MultiFileScanBase,
                                           tpu_scan_of)

DEFAULT_FIELD_DELIM = "\x01"
DEFAULT_NULL_FORMAT = "\\N"


def serde_properties(props: Optional[dict]) -> dict:
    """Normalizes a serde property dict to the supported subset; raises on
    properties the serde cannot honor (the reference falls back to CPU for
    these — here the host IS the text tier, so unknown props are errors)."""
    props = dict(props or {})
    out = {
        "field.delim": props.pop("field.delim", DEFAULT_FIELD_DELIM),
        "serialization.null.format": props.pop(
            "serialization.null.format", DEFAULT_NULL_FORMAT),
        # hive's LazySimpleSerDe has NO escaping unless escape.delim is
        # set explicitly (and an explicit backslash escape would consume
        # the \N null sentinel's backslash)
        "escape.delim": props.pop("escape.delim", None),
    }
    props.pop("serialization.format", None)   # same as field.delim in hive
    if props:
        raise NotImplementedError(
            f"unsupported Hive serde properties: {sorted(props)} "
            "(supported: field.delim, serialization.null.format, "
            "escape.delim)")
    return out


class CpuHiveTextScanExec(MultiFileScanBase):
    """Hive text table scan: schema-required delimited text without
    header/quoting (reference: GpuHiveTableScanExec)."""

    format_name = "hivetext"
    file_ext = ""          # hive table dirs contain bare part files

    def __init__(self, paths: Sequence[str], table_schema: T.StructType,
                 serde: Optional[dict] = None,
                 columns: Optional[List[str]] = None,
                 reader_type: str = AUTO, batch_rows: int = 1 << 20,
                 num_threads: int = 8):
        super().__init__(paths, reader_type=reader_type,
                         batch_rows=batch_rows, num_threads=num_threads)
        self.table_schema = table_schema
        self.serde = serde_properties(serde)
        self.columns = columns

    def _scan_cache_extra(self):
        return (self.table_schema.simple_name,
                tuple(sorted((self.serde or {}).items())))

    def infer_schema(self) -> T.StructType:
        sch = self.table_schema
        if self.columns is not None:
            sch = T.StructType([f for f in sch.fields
                                if f.name in self.columns])
        return sch

    def read_file(self, path: str) -> Iterator[HostColumnarBatch]:
        import pyarrow as pa
        import pyarrow.csv as pcsv
        sch = self.table_schema
        read = pcsv.ReadOptions(column_names=sch.names, block_size=1 << 24)
        parse = pcsv.ParseOptions(
            delimiter=self.serde["field.delim"],
            quote_char=False,
            escape_char=self.serde["escape.delim"] or False)
        conv = pcsv.ConvertOptions(
            null_values=[self.serde["serialization.null.format"]],
            strings_can_be_null=True,
            column_types={f.name: T.to_arrow(f.data_type)
                          for f in sch.fields},
            include_columns=self.columns or None)
        if os.path.getsize(path) == 0:
            return
        with pcsv.open_csv(path, read_options=read, parse_options=parse,
                           convert_options=conv) as rdr:
            for rb in rdr:
                if rb.num_rows:
                    yield batch_from_arrow(pa.Table.from_batches([rb]))


TpuHiveTextScanExec, _hive_convert = tpu_scan_of(CpuHiveTextScanExec)

from spark_rapids_tpu.plan.overrides import register_exec  # noqa: E402

register_exec(CpuHiveTextScanExec, convert=_hive_convert,
              desc="Hive text table scan (LazySimpleSerDe subset; host "
                   "decode + device upload)")


def write_hive_text(batches, path: str, schema: T.StructType,
                    serde: Optional[dict] = None) -> None:
    """Hive text writer (reference: GpuHiveTextFileFormat): one part file,
    '\\x01'-delimited, ``\\N`` nulls, booleans as true/false."""
    import pyarrow as pa
    import pyarrow.compute as pc
    props = serde_properties(serde)
    delim = props["field.delim"]
    nullf = props["serialization.null.format"]
    esc = props["escape.delim"]
    from spark_rapids_tpu.columnar.batch import ColumnarBatch
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    with open(path, "w", encoding="utf-8") as f:
        for b in batches:
            if isinstance(b, ColumnarBatch):
                b = b.to_host()
            if b.row_count == 0:
                continue
            tab = pa.Table.from_batches([b.to_arrow()])
            cols = []
            for name in tab.column_names:
                c = tab.column(name)
                is_str = pa.types.is_string(c.type)
                if pa.types.is_boolean(c.type):
                    c = pc.if_else(c, pa.scalar("true"), pa.scalar("false"))
                c = pc.cast(c, pa.string(), safe=False)
                if esc and is_str:
                    # writer mirrors the reader's escaping (LazySimpleSerDe
                    # escapes the escape char and the field delimiter;
                    # without escape.delim delimiter-bearing values corrupt
                    # the row — hive's own raw-text behavior)
                    c = pc.replace_substring(c, esc, esc + esc)
                    c = pc.replace_substring(c, delim, esc + delim)
                cols.append(pc.fill_null(c, nullf).to_pylist())
            for row in zip(*cols):
                f.write(delim.join(row))
                f.write("\n")
