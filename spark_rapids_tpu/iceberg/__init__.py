"""Iceberg table support (SURVEY.md §2.7: the reference ports Iceberg's
parquet reader stack — 29 Java files — wired to its accelerated parquet
scan; here the metadata/manifest layer reads through the engine's own avro
codec and data files through the accelerated parquet scan)."""

from spark_rapids_tpu.iceberg.table import IcebergTable  # noqa: F401
