"""Iceberg v1/v2 read path (+ a writer for tests).

Reference: sql-plugin/src/main/java/com/nvidia/spark/rapids/iceberg/ —
the reference reimplements Iceberg's reader stack so data files decode on
the accelerator.  Same shape here, sized to the protocol's core:

  <table>/metadata/vN.metadata.json     table metadata + snapshot log
  <table>/metadata/snap-*.avro          manifest LIST (one row/manifest)
  <table>/metadata/*-m0.avro            MANIFEST (one row per data file)
  <table>/data/*.parquet                data files

Reading: latest metadata -> current snapshot -> manifest list -> manifests
-> live data files -> the engine's multi-file parquet scan.  v2 delete
files (content=1 positional, content=2 equality) apply on read through a
host-side DeleteFilter before batches reach the plan (see
docs/compatibility.md for the NULL-equality and sequence-number
simplifications)."""

from __future__ import annotations

import json
import os
import uuid
from typing import Dict, List, Optional

from spark_rapids_tpu import types as T
from spark_rapids_tpu.io.avro import read_avro_records, write_avro_records

_MANIFEST_LIST_SCHEMA = {
    "type": "record", "name": "manifest_file", "fields": [
        {"name": "manifest_path", "type": "string"},
        {"name": "manifest_length", "type": "long"},
        {"name": "added_files_count", "type": ["null", "int"]},
        {"name": "content", "type": ["null", "int"]},
    ]}

_MANIFEST_SCHEMA = {
    "type": "record", "name": "manifest_entry", "fields": [
        {"name": "status", "type": "int"},   # 0 existing 1 added 2 deleted
        {"name": "data_file", "type": {
            "type": "record", "name": "data_file", "fields": [
                {"name": "file_path", "type": "string"},
                {"name": "file_format", "type": "string"},
                {"name": "record_count", "type": "long"},
                {"name": "file_size_in_bytes", "type": "long"},
                {"name": "content", "type": ["null", "int"]},
            ]}},
    ]}

_ICE_TO_TYPE = {
    "boolean": T.BOOLEAN, "int": T.INT, "long": T.LONG, "float": T.FLOAT,
    "double": T.DOUBLE, "string": T.STRING, "binary": T.BINARY,
    "date": T.DATE, "timestamptz": T.TIMESTAMP, "timestamp": T.TIMESTAMP,
}


def _type_from_iceberg(t):
    if isinstance(t, str):
        if t in _ICE_TO_TYPE:
            return _ICE_TO_TYPE[t]
        if t.startswith("decimal("):
            p, s = t[8:-1].split(",")
            return T.DecimalType(int(p), int(s.strip()))
        raise ValueError(f"unsupported iceberg type {t!r}")
    if isinstance(t, dict) and t.get("type") == "list":
        return T.ArrayType(_type_from_iceberg(t["element"]))
    raise ValueError(f"unsupported iceberg type {t!r}")


def _type_to_iceberg(dt: T.DataType) -> str:
    if isinstance(dt, T.BooleanType):
        return "boolean"
    if isinstance(dt, T.IntegerType):
        return "int"
    if isinstance(dt, T.LongType):
        return "long"
    if isinstance(dt, T.FloatType):
        return "float"
    if isinstance(dt, T.DoubleType):
        return "double"
    if isinstance(dt, T.StringType):
        return "string"
    if isinstance(dt, T.BinaryType):
        return "binary"
    if isinstance(dt, T.DateType):
        return "date"
    if isinstance(dt, T.TimestampType):
        return "timestamptz"
    if isinstance(dt, T.DecimalType):
        return f"decimal({dt.precision}, {dt.scale})"
    raise ValueError(f"cannot map {dt.simple_name} to iceberg")


class IcebergTable:
    def __init__(self, session, path: str):
        self.session = session
        self.path = path
        self.meta_dir = os.path.join(path, "metadata")

    # -- metadata ------------------------------------------------------------
    def _latest_metadata(self) -> dict:
        if not os.path.isdir(self.meta_dir):
            raise FileNotFoundError(f"no iceberg metadata in "
                                    f"{self.meta_dir}")
        versions = []
        for f in os.listdir(self.meta_dir):
            if f.endswith(".metadata.json") and f.startswith("v"):
                versions.append((int(f[1:].split(".")[0]), f))
        if not versions:
            raise FileNotFoundError(f"no iceberg metadata in "
                                    f"{self.meta_dir}")
        _, latest = max(versions)
        with open(os.path.join(self.meta_dir, latest)) as fh:
            return json.load(fh)

    @property
    def schema(self) -> T.StructType:
        md = self._latest_metadata()
        schemas = md.get("schemas") or [md["schema"]]
        sid = md.get("current-schema-id", 0)
        sch = next((s for s in schemas if s.get("schema-id", 0) == sid),
                   schemas[-1])
        return T.StructType([
            T.StructField(f["name"], _type_from_iceberg(f["type"]),
                          not f.get("required", False))
            for f in sch["fields"]])

    def current_snapshot(self) -> Optional[dict]:
        md = self._latest_metadata()
        sid = md.get("current-snapshot-id")
        if sid is None or sid == -1:
            return None
        return next(s for s in md["snapshots"] if s["snapshot-id"] == sid)

    def _classified_files(self):
        """(data_files, positional_delete_files, equality_delete_files) —
        v2 manifests carry delete files with content=1 (positional) and
        content=2 (equality); reference: the iceberg reader stack's
        DeleteFilter (sql-plugin/.../iceberg/, GpuDeleteFilter shape)."""
        snap = self.current_snapshot()
        if snap is None:
            return [], [], []
        mlist = snap["manifest-list"]
        if not os.path.isabs(mlist):
            mlist = os.path.join(self.path, mlist)
        data: List[dict] = []
        pos_del: List[dict] = []
        eq_del: List[dict] = []
        for m in read_avro_records(mlist):
            mpath = m["manifest_path"]
            if not os.path.isabs(mpath):
                mpath = os.path.join(self.path, mpath)
            # v2 sequence-number inheritance: ADDED entries written with a
            # null sequence_number inherit the MANIFEST-LIST entry's number
            # (the layout standard writers produce; iceberg spec "Sequence
            # Number Inheritance")
            m_seq = m.get("sequence_number")
            for entry in read_avro_records(mpath):
                if entry["status"] == 2:      # deleted
                    continue
                df = entry["data_file"]
                # entry-level data sequence number (v2 foreign writers);
                # None for our own commits and v1 tables
                seq = entry.get("sequence_number")
                if seq is None:
                    seq = entry.get("data_sequence_number")
                if seq is None and entry.get("status") == 1:
                    seq = m_seq
                df = dict(df)
                df["_seq"] = seq
                content = df.get("content") or 0
                if content == 0:
                    data.append(df)
                elif content == 1:
                    pos_del.append(df)
                elif content == 2:
                    eq_del.append(df)
                else:
                    raise NotImplementedError(
                        f"iceberg file content {content} not supported")
        return data, pos_del, eq_del

    def data_files(self) -> List[dict]:
        return self._classified_files()[0]

    # -- read ----------------------------------------------------------------
    def _abs(self, p: str) -> str:
        if p.startswith("file:"):
            p = p[5:]
        if not os.path.isabs(p):
            p = os.path.join(self.path, p)
        return p

    def to_df(self):
        data, pos_del, eq_del = self._classified_files()
        schema = self.schema
        paths = [self._abs(df["file_path"]) for df in data]
        if not paths:
            from spark_rapids_tpu.columnar.batch import batch_from_pydict
            return self.session.create_dataframe(
                batch_from_pydict({f.name: [] for f in schema.fields},
                                  schema))
        if not pos_del and not eq_del:
            return self.session.read.parquet(*paths)
        return self._read_with_deletes(data, pos_del, eq_del)

    def _read_with_deletes(self, data, pos_del, eq_del):
        """v2 read: positional delete files hold (file_path, pos) rows;
        equality delete files hold rows whose column set defines the
        equality — a data row matching any delete row on those columns
        drops.  Host-applied per data file, then handed to the engine
        (the reference applies the same DeleteFilter before the decoded
        batch reaches the plan).

        Sequence-number scoping (iceberg v2 spec): a positional delete
        applies to data files with data_seq <= delete_seq; an equality
        delete applies strictly to OLDER data files (data_seq <
        delete_seq).  Entries without sequence numbers (this engine's own
        commits, v1 tables) keep the legacy rule — deletes apply to every
        live data file (our writer commits deletes strictly after the
        data they target) — via data_seq=0 / delete_seq=+inf defaults, so
        a foreign table where data was appended AFTER a delete commit no
        longer silently drops the newer rows (ADVICE r4)."""
        import numpy as np
        import pyarrow as pa
        import pyarrow.parquet as pq
        INF = float("inf")

        def dseq(df):          # data files: unknown -> oldest
            return 0 if df.get("_seq") is None else df["_seq"]

        def xseq(df):          # delete files: unknown -> newest
            return INF if df.get("_seq") is None else df["_seq"]

        # positional: normalized data path -> [(delete_seq, positions)]
        pos_map: Dict[str, list] = {}
        for df in pos_del:
            t = pq.read_table(self._abs(df["file_path"]))
            fps = t.column("file_path").to_pylist()
            ps = t.column("pos").to_pylist()
            by_path: Dict[str, list] = {}
            for fp, p in zip(fps, ps):
                by_path.setdefault(self._abs(fp), []).append(int(p))
            for fp, plist in by_path.items():
                pos_map.setdefault(fp, []).append(
                    (xseq(df), np.asarray(plist, dtype=np.int64)))
        eq_tables = [(xseq(df), pq.read_table(self._abs(df["file_path"])))
                     for df in eq_del]
        out = []
        for df in data:
            p = self._abs(df["file_path"])
            sq = dseq(df)
            tbl = pq.read_table(p)
            hits = [ps for (s, ps) in pos_map.get(p, []) if sq <= s]
            if hits:
                drop = np.unique(np.concatenate(hits))
                keep = np.ones(tbl.num_rows, dtype=bool)
                keep[drop[drop < tbl.num_rows]] = False
                tbl = tbl.take(pa.array(np.flatnonzero(keep)))
            for s, et in eq_tables:
                if not (sq < s):
                    continue
                keys = et.column_names    # the file's columns ARE the
                et_u = et.combine_chunks()  # equality column set
                tbl = tbl.join(et_u.group_by(keys).aggregate([]),
                               keys=keys, join_type="left anti")
            out.append(tbl)
        combined = pa.concat_tables(out, promote_options="default")
        from spark_rapids_tpu.columnar.batch import batch_from_arrow
        # restore declared column order (anti-join can reorder columns)
        names = [f.name for f in self.schema.fields]
        combined = combined.select(names)
        return self.session.create_dataframe(batch_from_arrow(combined))

    def record_count(self) -> int:
        """Metadata-only count (no data read) when no deletes exist —
        the manifest stats path; with v2 deletes the count requires
        applying them."""
        data, pos_del, eq_del = self._classified_files()
        if not pos_del and not eq_del:
            return sum(df["record_count"] for df in data)
        return self.to_df().count()

    # -- v2 delete commits (test harness / DML) ------------------------------
    def add_positional_deletes(self, pairs) -> None:
        """Commits a positional delete file: ``pairs`` =
        [(data_file_path_as_written, position), ...]."""
        import pyarrow as pa
        tbl = pa.table({
            "file_path": pa.array([p for p, _ in pairs], type=pa.string()),
            "pos": pa.array([int(x) for _, x in pairs], type=pa.int64())})
        self._append_delete_file(tbl, content=1)

    def add_equality_deletes(self, rows: dict) -> None:
        """Commits an equality delete file; the dict's columns define the
        equality column set."""
        import pyarrow as pa
        self._append_delete_file(pa.table(rows), content=2)

    def _append_delete_file(self, arrow_table, content: int) -> None:
        import pyarrow.parquet as pq
        previous = self._latest_metadata()
        version = self._next_version()
        kind = "pos" if content == 1 else "eq"
        name = f"data/{uuid.uuid4().hex[:12]}-{kind}-deletes.parquet"
        fpath = os.path.join(self.path, name)
        pq.write_table(arrow_table, fpath)
        entries = [{"status": 1, "data_file": {
            "file_path": name, "file_format": "PARQUET",
            "record_count": int(arrow_table.num_rows),
            "file_size_in_bytes": os.path.getsize(fpath),
            "content": content}}]
        fields = (previous.get("schemas") or [previous["schema"]])[0][
            "fields"]
        self._commit_raw(entries, version, previous, fields,
                         operation="delete", format_version=2)

    # -- write (test harness / CTAS) -----------------------------------------
    @classmethod
    def create(cls, session, path: str, df) -> "IcebergTable":
        t = cls(session, path)
        os.makedirs(t.meta_dir, exist_ok=True)
        os.makedirs(os.path.join(path, "data"), exist_ok=True)
        t._commit(df, version=1)
        return t

    def _next_version(self) -> int:
        versions = [int(f[1:].split(".")[0])
                    for f in os.listdir(self.meta_dir)
                    if f.endswith(".metadata.json")]
        return max(versions) + 1

    def append(self, df) -> None:
        md = self._latest_metadata()
        self._commit(df, version=self._next_version(), previous=md)

    def _commit(self, df, version: int, previous: Optional[dict] = None):
        import pyarrow as pa
        import pyarrow.parquet as pq
        from spark_rapids_tpu.columnar.batch import (ColumnarBatch,
                                                     concat_host_batches)
        schema = df.schema
        batches = []
        for b in df._executed_plan().execute_all():
            batches.append(b.to_host() if isinstance(b, ColumnarBatch)
                           else b)
        entries = []
        if batches:
            hb = concat_host_batches(batches) if len(batches) > 1 \
                else batches[0]
            name = f"data/{uuid.uuid4().hex[:12]}.parquet"
            fpath = os.path.join(self.path, name)
            pq.write_table(pa.Table.from_batches([hb.to_arrow()]), fpath)
            entries.append({"status": 1, "data_file": {
                "file_path": name, "file_format": "PARQUET",
                "record_count": int(hb.row_count),
                "file_size_in_bytes": os.path.getsize(fpath),
                "content": 0}})
        fields = [{"id": i + 1, "name": f.name,
                   "required": not f.nullable,
                   "type": _type_to_iceberg(f.data_type)}
                  for i, f in enumerate(schema.fields)]
        self._commit_raw(entries, version, previous, fields,
                         operation="append", format_version=1)

    def _commit_raw(self, entries, version: int, previous: Optional[dict],
                    fields, operation: str, format_version: int) -> None:
        """Shared snapshot commit: manifest + manifest list (carrying the
        previous snapshot's manifests forward) + vN.metadata.json."""
        snap_id = version
        manifest = f"metadata/{uuid.uuid4().hex[:8]}-m0.avro"
        write_avro_records(os.path.join(self.path, manifest),
                           _MANIFEST_SCHEMA, entries)
        manifests = [{"manifest_path": manifest,
                      "manifest_length": os.path.getsize(
                          os.path.join(self.path, manifest)),
                      "added_files_count": len(entries), "content": 0}]
        if previous is not None:
            prev_snap = next((s for s in previous.get("snapshots", [])
                              if s["snapshot-id"] ==
                              previous.get("current-snapshot-id")), None)
            if prev_snap is not None:
                ml = prev_snap["manifest-list"]
                if not os.path.isabs(ml):
                    ml = os.path.join(self.path, ml)
                manifests = read_avro_records(ml) + manifests
        mlist = f"metadata/snap-{snap_id}.avro"
        write_avro_records(os.path.join(self.path, mlist),
                           _MANIFEST_LIST_SCHEMA, manifests)
        snapshots = list((previous or {}).get("snapshots", []))
        snapshots.append({"snapshot-id": snap_id,
                          "manifest-list": mlist,
                          "summary": {"operation": operation}})
        prev_fv = (previous or {}).get("format-version", 1)
        md = {"format-version": max(format_version, prev_fv),
              "table-uuid": (previous or {}).get("table-uuid",
                                                 str(uuid.uuid4())),
              "location": self.path,
              "current-schema-id": 0,
              "schemas": [{"schema-id": 0, "type": "struct",
                           "fields": fields}],
              "schema": {"type": "struct", "fields": fields},
              "current-snapshot-id": snap_id,
              "snapshots": snapshots}
        with open(os.path.join(self.meta_dir,
                               f"v{version}.metadata.json"), "w") as fh:
            json.dump(md, fh)
