"""IO formats: scans and writers (reference: SURVEY.md §2.7).

TPU-first stance on file decode: the reference decodes parquet/orc ON the
GPU (cuDF readers) after host-side footer filtering.  Byte-wrangling decode
is TPU-hostile, so here decode happens on host (arrow readers play the role
of the reference's host-side footer/chunk stage) and decoded columns upload
to the device as padded batches — the admission point mirrors
GpuParquetScan's semaphore acquisition before device work
(GpuParquetScan.scala:1282 readToTable -> GpuSemaphore.acquireIfNecessary).
"""

from spark_rapids_tpu.io.parquet import (  # noqa: F401
    CpuParquetScanExec, write_parquet)
from spark_rapids_tpu.io.text import (  # noqa: F401
    CpuCsvScanExec, CpuJsonScanExec, write_csv, write_json)
from spark_rapids_tpu.io.orc import CpuOrcScanExec, write_orc  # noqa: F401
from spark_rapids_tpu.io.writer import DataFrameWriter  # noqa: F401
