"""Avro object-container-file scan + writer.

Reference: GpuAvroScan.scala (1101) + AvroDataFileReader.scala — the
reference parses the Avro container format in pure Scala (header, codec,
sync-marker-delimited blocks) and feeds the decoded blocks to the device.
Same plan here in pure Python: container parsing + a binary decoder for the
record schema, producing arrow-backed host batches (the host tier of every
scan; device upload happens in the Tpu* variant).

Supported schema surface (mirrors the reference's primitive matrix):
null/boolean/int/long/float/double/bytes/string fields, nullable unions
(["null", T] in either order), enums (decoded to their symbol strings), and
the date / timestamp-millis / timestamp-micros logical types.  Codecs:
null and deflate.
"""

from __future__ import annotations

import io
import json
import os
import struct
import zlib
from typing import Iterator, List, Optional, Sequence, Tuple

import numpy as np

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.batch import HostColumnarBatch, batch_from_arrow
from spark_rapids_tpu.io.multifile import (AUTO, MultiFileScanBase,
                                           chunked_write, tpu_scan_of)

_MAGIC = b"Obj\x01"


# ---------------------------------------------------------------------------
# varint / zigzag primitives
# ---------------------------------------------------------------------------

def _read_long(buf: memoryview, pos: int) -> Tuple[int, int]:
    shift = 0
    acc = 0
    while True:
        b = buf[pos]
        pos += 1
        acc |= (b & 0x7F) << shift
        if not (b & 0x80):
            break
        shift += 7
    return (acc >> 1) ^ -(acc & 1), pos


def _write_long(out: bytearray, v: int) -> None:
    v = (v << 1) ^ (v >> 63) if v < 0 else v << 1
    while True:
        b = v & 0x7F
        v >>= 7
        if v:
            out.append(b | 0x80)
        else:
            out.append(b)
            return


# ---------------------------------------------------------------------------
# schema mapping
# ---------------------------------------------------------------------------

class _Field:
    __slots__ = ("name", "kind", "nullable", "null_first", "logical",
                 "symbols")

    def __init__(self, name, kind, nullable, null_first=True, logical=None,
                 symbols=None):
        self.name = name
        self.kind = kind           # avro primitive name or "enum"
        self.nullable = nullable
        self.null_first = null_first
        self.logical = logical     # date | timestamp-millis | timestamp-micros
        self.symbols = symbols


_KIND_TO_TYPE = {
    "boolean": T.BOOLEAN, "int": T.INT, "long": T.LONG, "float": T.FLOAT,
    "double": T.DOUBLE, "bytes": T.BINARY, "string": T.STRING,
    "enum": T.STRING, "null": T.NULL,
}


def _parse_schema(schema_json: str) -> List[_Field]:
    sch = json.loads(schema_json)
    if sch.get("type") != "record":
        raise ValueError("only record top-level avro schemas are supported")
    fields = []
    for f in sch["fields"]:
        ft = f["type"]
        nullable = False
        null_first = True
        if isinstance(ft, list):
            branches = [b for b in ft if b != "null"]
            if len(ft) != 2 or len(branches) != 1:
                raise ValueError(
                    f"unsupported union for field {f['name']!r}: {ft}")
            nullable = True
            null_first = ft[0] == "null"
            ft = branches[0]
        logical = None
        symbols = None
        if isinstance(ft, dict):
            logical = ft.get("logicalType")
            if ft.get("type") == "enum":
                symbols = list(ft["symbols"])
                kind = "enum"
            else:
                kind = ft.get("type")
        else:
            kind = ft
        if kind not in _KIND_TO_TYPE:
            raise ValueError(f"unsupported avro type {kind!r} for field "
                             f"{f['name']!r}")
        fields.append(_Field(f["name"], kind, nullable, null_first,
                             logical, symbols))
    return fields


def _field_type(f: _Field) -> T.DataType:
    if f.logical == "date":
        return T.DATE
    if f.logical in ("timestamp-millis", "timestamp-micros"):
        return T.TIMESTAMP
    return _KIND_TO_TYPE[f.kind]


# ---------------------------------------------------------------------------
# container + block decode
# ---------------------------------------------------------------------------

def _read_header(f) -> Tuple[List[_Field], str, bytes, str]:
    if f.read(4) != _MAGIC:
        raise ValueError("not an avro object container file")
    meta = {}
    data = f.read()
    buf = memoryview(data)
    pos = 0
    while True:
        n, pos = _read_long(buf, pos)
        if n == 0:
            break
        for _ in range(abs(n)):
            klen, pos = _read_long(buf, pos)
            key = bytes(buf[pos:pos + klen]).decode()
            pos += klen
            vlen, pos = _read_long(buf, pos)
            meta[key] = bytes(buf[pos:pos + vlen])
            pos += vlen
        if n < 0:          # block with byte size prefix
            _, pos = _read_long(buf, pos)
    sync = bytes(buf[pos:pos + 16])
    pos += 16
    schema_json = meta["avro.schema"].decode()
    codec = meta.get("avro.codec", b"null").decode()
    return _parse_schema(schema_json), codec, sync, data[pos:]


def _decode_block(buf: bytes, count: int, fields: List[_Field]):
    """Decodes ``count`` records; returns per-field python value lists."""
    mv = memoryview(buf)
    pos = 0
    cols = [[None] * count for _ in fields]
    for r in range(count):
        for ci, fld in enumerate(fields):
            if fld.nullable:
                branch, pos = _read_long(mv, pos)
                is_null = (branch == 0) == fld.null_first
                if is_null:
                    continue
            v, pos = _decode_value(mv, pos, fld)
            cols[ci][r] = v
    return cols


def _decode_value(mv: memoryview, pos: int, fld: _Field):
    k = fld.kind
    if k == "boolean":
        return mv[pos] != 0, pos + 1
    if k in ("int", "long"):
        return _read_long(mv, pos)
    if k == "float":
        return struct.unpack_from("<f", mv, pos)[0], pos + 4
    if k == "double":
        return struct.unpack_from("<d", mv, pos)[0], pos + 8
    if k in ("bytes", "string"):
        n, pos = _read_long(mv, pos)
        raw = bytes(mv[pos:pos + n])
        return (raw.decode() if k == "string" else raw), pos + n
    if k == "enum":
        i, pos = _read_long(mv, pos)
        return fld.symbols[i], pos
    if k == "null":
        return None, pos
    raise ValueError(f"unsupported avro kind {k}")


def _to_arrow(cols, fields: List[_Field]):
    import pyarrow as pa
    arrays = {}
    for fld, vals in zip(fields, cols):
        dt = _field_type(fld)
        if fld.logical == "date":
            arr = pa.array(vals, type=pa.int32()).cast(pa.date32())
        elif fld.logical == "timestamp-millis":
            vals = [None if v is None else v * 1000 for v in vals]
            arr = pa.array(vals, type=pa.int64()).cast(
                pa.timestamp("us", tz="UTC"))
        elif fld.logical == "timestamp-micros":
            arr = pa.array(vals, type=pa.int64()).cast(
                pa.timestamp("us", tz="UTC"))
        else:
            arr = pa.array(vals, type=T.to_arrow(dt))
        arrays[fld.name] = arr
    return pa.table(arrays)


class CpuAvroScanExec(MultiFileScanBase):
    """Avro scan through the shared multi-file machinery (PERFILE /
    COALESCING / MULTITHREADED strategies come from the base, like the
    reference's GpuAvroScan rides GpuMultiFileReader)."""

    format_name = "avro"
    file_ext = ".avro"

    def __init__(self, paths: Sequence[str],
                 columns: Optional[Sequence[str]] = None, **kw):
        super().__init__(paths, **kw)
        self.columns = list(columns) if columns else None

    def infer_schema(self) -> T.StructType:
        with open(self.paths[0], "rb") as f:
            fields, _, _, _ = _read_header(f)
        out = [T.StructField(fld.name, _field_type(fld),
                             fld.nullable) for fld in fields]
        if self.columns:
            by_name = {f.name: f for f in out}
            out = [by_name[c] for c in self.columns]
        return T.StructType(out)

    def read_file(self, path: str) -> Iterator[HostColumnarBatch]:
        with open(path, "rb") as f:
            fields, codec, sync, body = _read_header(f)
        if codec not in ("null", "deflate"):
            raise ValueError(f"unsupported avro codec {codec!r}")
        mv = memoryview(body)
        pos = 0
        rows = 0
        pending = []
        while pos < len(mv):
            count, pos = _read_long(mv, pos)
            size, pos = _read_long(mv, pos)
            block = bytes(mv[pos:pos + size])
            pos += size
            if bytes(mv[pos:pos + 16]) != sync:
                raise ValueError(f"corrupt avro block in {path} "
                                 "(bad sync marker)")
            pos += 16
            if codec == "deflate":
                block = zlib.decompress(block, -15)
            cols = _decode_block(block, count, fields)
            tab = _to_arrow(cols, fields)
            if self.columns:
                tab = tab.select(self.columns)
            pending.append(tab)
            rows += count
            if rows >= self.batch_rows:
                yield _emit(pending)
                pending, rows = [], 0
        if pending:
            yield _emit(pending)


def _emit(tables) -> HostColumnarBatch:
    import pyarrow as pa
    return batch_from_arrow(pa.concat_tables(tables))


TpuAvroScanExec, _avro_convert = tpu_scan_of(CpuAvroScanExec)

from spark_rapids_tpu.plan.overrides import register_exec  # noqa: E402

register_exec(CpuAvroScanExec, convert=_avro_convert,
              desc="avro scan (pure host block parser, like the "
                   "reference's AvroDataFileReader)")


# ---------------------------------------------------------------------------
# writer (roundtrip + test oracle)
# ---------------------------------------------------------------------------

def _avro_schema_of(schema: T.StructType) -> str:
    fields = []
    for f in schema.fields:
        dt = f.data_type
        if isinstance(dt, T.DateType):
            ft = {"type": "int", "logicalType": "date"}
        elif isinstance(dt, T.TimestampType):
            ft = {"type": "long", "logicalType": "timestamp-micros"}
        elif isinstance(dt, T.BooleanType):
            ft = "boolean"
        elif isinstance(dt, (T.ByteType, T.ShortType, T.IntegerType)):
            ft = "int"
        elif isinstance(dt, T.LongType):
            ft = "long"
        elif isinstance(dt, T.FloatType):
            ft = "float"
        elif isinstance(dt, T.DoubleType):
            ft = "double"
        elif isinstance(dt, T.StringType):
            ft = "string"
        elif isinstance(dt, T.BinaryType):
            ft = "bytes"
        else:
            raise ValueError(f"cannot write {dt.simple_name} to avro")
        fields.append({"name": f.name,
                       "type": ["null", ft] if f.nullable else ft})
    return json.dumps({"type": "record", "name": "row", "fields": fields})


class _AvroWriter:
    def __init__(self, path: str, schema: T.StructType, codec: str):
        import secrets
        self.schema = schema
        self.codec = codec
        self.sync = secrets.token_bytes(16)
        self.f = open(path, "wb")
        self.f.write(_MAGIC)
        meta = {b"avro.schema": _avro_schema_of(schema).encode(),
                b"avro.codec": codec.encode()}
        out = bytearray()
        _write_long(out, len(meta))
        for k, v in meta.items():
            _write_long(out, len(k))
            out += k
            _write_long(out, len(v))
            out += v
        _write_long(out, 0)
        self.f.write(bytes(out))
        self.f.write(self.sync)

    def write(self, rb) -> None:
        import pyarrow as pa
        n = rb.num_rows
        if n == 0:
            return
        rows = rb.to_pydict()
        out = bytearray()
        for r in range(n):
            for fld in self.schema.fields:
                _encode_value(out, rows[fld.name][r], fld)
        block = bytes(out)
        if self.codec == "deflate":
            comp = zlib.compressobj(6, zlib.DEFLATED, -15)
            block = comp.compress(block) + comp.flush()
        head = bytearray()
        _write_long(head, n)
        _write_long(head, len(block))
        self.f.write(bytes(head))
        self.f.write(block)
        self.f.write(self.sync)

    def close(self) -> None:
        self.f.close()


def write_avro(batches, path: str, schema: Optional[T.StructType] = None,
               codec: str = "deflate") -> None:
    def _struct_of(arrow_sch) -> T.StructType:
        return T.StructType([
            T.StructField(f.name, T.from_arrow(f.type), f.nullable)
            for f in arrow_sch])

    chunked_write(
        batches, path, schema,
        open_writer=lambda p, arrow_sch: _AvroWriter(
            p, _struct_of(arrow_sch), codec),
        write_batch=lambda w, rb: w.write(rb))


def _encode_value(out: bytearray, v, fld: T.StructField) -> None:
    import datetime
    dt = fld.data_type
    if fld.nullable:
        if v is None:
            _write_long(out, 0)
            return
        _write_long(out, 1)
    elif v is None:
        raise ValueError(f"null in non-nullable field {fld.name}")
    if isinstance(dt, T.BooleanType):
        out.append(1 if v else 0)
    elif isinstance(dt, T.DateType):
        days = (v - datetime.date(1970, 1, 1)).days \
            if isinstance(v, datetime.date) else int(v)
        _write_long(out, days)
    elif isinstance(dt, T.TimestampType):
        if isinstance(v, datetime.datetime):
            import calendar
            us = int(calendar.timegm(v.utctimetuple())) * 1_000_000 \
                + v.microsecond
        else:
            us = int(v)
        _write_long(out, us)
    elif isinstance(dt, (T.ByteType, T.ShortType, T.IntegerType, T.LongType)):
        _write_long(out, int(v))
    elif isinstance(dt, T.FloatType):
        out += struct.pack("<f", float(v))
    elif isinstance(dt, T.DoubleType):
        out += struct.pack("<d", float(v))
    elif isinstance(dt, T.StringType):
        raw = v.encode()
        _write_long(out, len(raw))
        out += raw
    elif isinstance(dt, T.BinaryType):
        _write_long(out, len(v))
        out += v
    else:
        raise ValueError(f"cannot encode {dt.simple_name}")


# ---------------------------------------------------------------------------
# generic (nested) decode — used by the Iceberg manifest reader; the flat
# columnar fast path above stays for plain tabular files
# ---------------------------------------------------------------------------

class _TypeDesc:
    __slots__ = ("kind", "fields", "items", "values", "symbols", "logical",
                 "nullable", "null_first", "size")

    def __init__(self, kind, **kw):
        self.kind = kind
        self.fields = kw.get("fields")      # record: [(name, desc)]
        self.items = kw.get("items")        # array
        self.values = kw.get("values")      # map
        self.symbols = kw.get("symbols")    # enum
        self.logical = kw.get("logical")
        self.nullable = kw.get("nullable", False)
        self.null_first = kw.get("null_first", True)
        self.size = kw.get("size")          # fixed


def _parse_type(t) -> _TypeDesc:
    if isinstance(t, list):
        branches = [b for b in t if b != "null"]
        if len(t) == 2 and len(branches) == 1:
            d = _parse_type(branches[0])
            d.nullable = True
            d.null_first = t[0] == "null"
            return d
        raise ValueError(f"unsupported avro union {t}")
    if isinstance(t, dict):
        kind = t.get("type")
        logical = t.get("logicalType")
        if kind == "record":
            return _TypeDesc("record", fields=[
                (f["name"], _parse_type(f["type"])) for f in t["fields"]])
        if kind == "array":
            return _TypeDesc("array", items=_parse_type(t["items"]))
        if kind == "map":
            return _TypeDesc("map", values=_parse_type(t["values"]))
        if kind == "enum":
            return _TypeDesc("enum", symbols=list(t["symbols"]))
        if kind == "fixed":
            return _TypeDesc("fixed", size=int(t["size"]))
        d = _parse_type(kind)
        d.logical = logical
        return d
    if t in ("null", "boolean", "int", "long", "float", "double", "bytes",
             "string"):
        return _TypeDesc(t)
    raise ValueError(f"unsupported avro type {t!r}")


def _decode_generic(mv, pos, d: _TypeDesc):
    if d.nullable:
        branch, pos = _read_long(mv, pos)
        if (branch == 0) == d.null_first:
            return None, pos
    k = d.kind
    if k == "record":
        out = {}
        for name, fd in d.fields:
            out[name], pos = _decode_generic(mv, pos, fd)
        return out, pos
    if k == "array":
        items = []
        while True:
            n, pos = _read_long(mv, pos)
            if n == 0:
                break
            if n < 0:
                _, pos = _read_long(mv, pos)   # block byte size
                n = -n
            for _ in range(n):
                v, pos = _decode_generic(mv, pos, d.items)
                items.append(v)
        return items, pos
    if k == "map":
        out = {}
        while True:
            n, pos = _read_long(mv, pos)
            if n == 0:
                break
            if n < 0:
                _, pos = _read_long(mv, pos)
                n = -n
            for _ in range(n):
                klen, pos = _read_long(mv, pos)
                key = bytes(mv[pos:pos + klen]).decode()
                pos += klen
                out[key], pos = _decode_generic(mv, pos, d.values)
        return out, pos
    if k == "fixed":
        raw = bytes(mv[pos:pos + d.size])
        return raw, pos + d.size
    if k == "enum":
        i, pos = _read_long(mv, pos)
        return d.symbols[i], pos
    if k == "boolean":
        return mv[pos] != 0, pos + 1
    if k in ("int", "long"):
        return _read_long(mv, pos)
    if k == "float":
        return struct.unpack_from("<f", mv, pos)[0], pos + 4
    if k == "double":
        return struct.unpack_from("<d", mv, pos)[0], pos + 8
    if k in ("bytes", "string"):
        n, pos = _read_long(mv, pos)
        raw = bytes(mv[pos:pos + n])
        return (raw.decode() if k == "string" else raw), pos + n
    if k == "null":
        return None, pos
    raise ValueError(f"unsupported avro kind {k}")


def read_avro_records(path: str):
    """Reads an avro container file with an ARBITRARILY NESTED record
    schema into python dicts (the Iceberg manifest path)."""
    with open(path, "rb") as f:
        if f.read(4) != _MAGIC:
            raise ValueError("not an avro object container file")
        data = f.read()
    buf = memoryview(data)
    pos = 0
    meta = {}
    while True:
        n, pos = _read_long(buf, pos)
        if n == 0:
            break
        for _ in range(abs(n)):
            klen, pos = _read_long(buf, pos)
            key = bytes(buf[pos:pos + klen]).decode()
            pos += klen
            vlen, pos = _read_long(buf, pos)
            meta[key] = bytes(buf[pos:pos + vlen])
            pos += vlen
        if n < 0:
            _, pos = _read_long(buf, pos)
    sync = bytes(buf[pos:pos + 16])
    pos += 16
    import json as _json
    schema = _json.loads(meta["avro.schema"].decode())
    codec = meta.get("avro.codec", b"null").decode()
    desc = _parse_type(schema)
    if desc.kind != "record":
        raise ValueError("top-level avro schema must be a record")
    out = []
    while pos < len(buf):
        count, pos = _read_long(buf, pos)
        size, pos = _read_long(buf, pos)
        block = bytes(buf[pos:pos + size])
        pos += size
        if bytes(buf[pos:pos + 16]) != sync:
            raise ValueError(f"corrupt avro block in {path}")
        pos += 16
        if codec == "deflate":
            block = zlib.decompress(block, -15)
        elif codec != "null":
            raise ValueError(f"unsupported avro codec {codec!r}")
        bmv = memoryview(block)
        bpos = 0
        for _ in range(count):
            rec, bpos = _decode_generic(bmv, bpos, desc)
            out.append(rec)
    return out


def write_avro_records(path: str, schema_json: dict, records,
                       codec: str = "null") -> None:
    """Writes nested python dicts as an avro container (the test/writer
    counterpart of read_avro_records)."""
    import json as _json
    import secrets
    desc = _parse_type(schema_json)
    sync = secrets.token_bytes(16)
    with open(path, "wb") as f:
        f.write(_MAGIC)
        meta = {b"avro.schema": _json.dumps(schema_json).encode(),
                b"avro.codec": codec.encode()}
        out = bytearray()
        _write_long(out, len(meta))
        for k, v in meta.items():
            _write_long(out, len(k))
            out += k
            _write_long(out, len(v))
            out += v
        _write_long(out, 0)
        f.write(bytes(out))
        f.write(sync)
        body = bytearray()
        for rec in records:
            _encode_generic(body, rec, desc)
        block = bytes(body)
        if codec == "deflate":
            comp = zlib.compressobj(6, zlib.DEFLATED, -15)
            block = comp.compress(block) + comp.flush()
        head = bytearray()
        _write_long(head, len(records))
        _write_long(head, len(block))
        f.write(bytes(head))
        f.write(block)
        f.write(sync)


def _encode_generic(out: bytearray, v, d: _TypeDesc) -> None:
    if d.nullable:
        if v is None:
            _write_long(out, 0 if d.null_first else 1)
            return
        _write_long(out, 1 if d.null_first else 0)
    k = d.kind
    if k == "record":
        for name, fd in d.fields:
            _encode_generic(out, v.get(name), fd)
    elif k == "array":
        if v:
            _write_long(out, len(v))
            for item in v:
                _encode_generic(out, item, d.items)
        _write_long(out, 0)
    elif k == "map":
        if v:
            _write_long(out, len(v))
            for key, val in v.items():
                raw = key.encode()
                _write_long(out, len(raw))
                out += raw
                _encode_generic(out, val, d.values)
        _write_long(out, 0)
    elif k == "fixed":
        out += v
    elif k == "enum":
        _write_long(out, d.symbols.index(v))
    elif k == "boolean":
        out.append(1 if v else 0)
    elif k in ("int", "long"):
        _write_long(out, int(v))
    elif k == "float":
        out += struct.pack("<f", float(v))
    elif k == "double":
        out += struct.pack("<d", float(v))
    elif k == "string":
        raw = v.encode()
        _write_long(out, len(raw))
        out += raw
    elif k == "bytes":
        _write_long(out, len(v))
        out += v
    elif k == "null":
        pass
    else:
        raise ValueError(f"cannot encode avro kind {k}")
