"""Avro object-container-file scan + writer.

Reference: GpuAvroScan.scala (1101) + AvroDataFileReader.scala — the
reference parses the Avro container format in pure Scala (header, codec,
sync-marker-delimited blocks) and feeds the decoded blocks to the device.
Same plan here in pure Python: container parsing + a binary decoder for the
record schema, producing arrow-backed host batches (the host tier of every
scan; device upload happens in the Tpu* variant).

Supported schema surface (mirrors the reference's primitive matrix):
null/boolean/int/long/float/double/bytes/string fields, nullable unions
(["null", T] in either order), enums (decoded to their symbol strings), and
the date / timestamp-millis / timestamp-micros logical types.  Codecs:
null and deflate.
"""

from __future__ import annotations

import io
import json
import os
import struct
import zlib
from typing import Iterator, List, Optional, Sequence, Tuple

import numpy as np

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.batch import HostColumnarBatch, batch_from_arrow
from spark_rapids_tpu.io.multifile import (AUTO, MultiFileScanBase,
                                           chunked_write, tpu_scan_of)

_MAGIC = b"Obj\x01"


# ---------------------------------------------------------------------------
# varint / zigzag primitives
# ---------------------------------------------------------------------------

def _read_long(buf: memoryview, pos: int) -> Tuple[int, int]:
    shift = 0
    acc = 0
    while True:
        b = buf[pos]
        pos += 1
        acc |= (b & 0x7F) << shift
        if not (b & 0x80):
            break
        shift += 7
    return (acc >> 1) ^ -(acc & 1), pos


def _write_long(out: bytearray, v: int) -> None:
    v = (v << 1) ^ (v >> 63) if v < 0 else v << 1
    while True:
        b = v & 0x7F
        v >>= 7
        if v:
            out.append(b | 0x80)
        else:
            out.append(b)
            return


# ---------------------------------------------------------------------------
# schema mapping
# ---------------------------------------------------------------------------

class _Field:
    __slots__ = ("name", "kind", "nullable", "null_first", "logical",
                 "symbols")

    def __init__(self, name, kind, nullable, null_first=True, logical=None,
                 symbols=None):
        self.name = name
        self.kind = kind           # avro primitive name or "enum"
        self.nullable = nullable
        self.null_first = null_first
        self.logical = logical     # date | timestamp-millis | timestamp-micros
        self.symbols = symbols


_KIND_TO_TYPE = {
    "boolean": T.BOOLEAN, "int": T.INT, "long": T.LONG, "float": T.FLOAT,
    "double": T.DOUBLE, "bytes": T.BINARY, "string": T.STRING,
    "enum": T.STRING, "null": T.NULL,
}


def _parse_schema(schema_json: str) -> List[_Field]:
    sch = json.loads(schema_json)
    if sch.get("type") != "record":
        raise ValueError("only record top-level avro schemas are supported")
    fields = []
    for f in sch["fields"]:
        ft = f["type"]
        nullable = False
        null_first = True
        if isinstance(ft, list):
            branches = [b for b in ft if b != "null"]
            if len(ft) != 2 or len(branches) != 1:
                raise ValueError(
                    f"unsupported union for field {f['name']!r}: {ft}")
            nullable = True
            null_first = ft[0] == "null"
            ft = branches[0]
        logical = None
        symbols = None
        if isinstance(ft, dict):
            logical = ft.get("logicalType")
            if ft.get("type") == "enum":
                symbols = list(ft["symbols"])
                kind = "enum"
            else:
                kind = ft.get("type")
        else:
            kind = ft
        if kind not in _KIND_TO_TYPE:
            raise ValueError(f"unsupported avro type {kind!r} for field "
                             f"{f['name']!r}")
        fields.append(_Field(f["name"], kind, nullable, null_first,
                             logical, symbols))
    return fields


def _field_type(f: _Field) -> T.DataType:
    if f.logical == "date":
        return T.DATE
    if f.logical in ("timestamp-millis", "timestamp-micros"):
        return T.TIMESTAMP
    return _KIND_TO_TYPE[f.kind]


# ---------------------------------------------------------------------------
# container + block decode
# ---------------------------------------------------------------------------

def _read_header(f) -> Tuple[List[_Field], str, bytes, str]:
    if f.read(4) != _MAGIC:
        raise ValueError("not an avro object container file")
    meta = {}
    data = f.read()
    buf = memoryview(data)
    pos = 0
    while True:
        n, pos = _read_long(buf, pos)
        if n == 0:
            break
        for _ in range(abs(n)):
            klen, pos = _read_long(buf, pos)
            key = bytes(buf[pos:pos + klen]).decode()
            pos += klen
            vlen, pos = _read_long(buf, pos)
            meta[key] = bytes(buf[pos:pos + vlen])
            pos += vlen
        if n < 0:          # block with byte size prefix
            _, pos = _read_long(buf, pos)
    sync = bytes(buf[pos:pos + 16])
    pos += 16
    schema_json = meta["avro.schema"].decode()
    codec = meta.get("avro.codec", b"null").decode()
    return _parse_schema(schema_json), codec, sync, data[pos:]


def _decode_block(buf: bytes, count: int, fields: List[_Field]):
    """Decodes ``count`` records; returns per-field python value lists."""
    mv = memoryview(buf)
    pos = 0
    cols = [[None] * count for _ in fields]
    for r in range(count):
        for ci, fld in enumerate(fields):
            if fld.nullable:
                branch, pos = _read_long(mv, pos)
                is_null = (branch == 0) == fld.null_first
                if is_null:
                    continue
            v, pos = _decode_value(mv, pos, fld)
            cols[ci][r] = v
    return cols


def _decode_value(mv: memoryview, pos: int, fld: _Field):
    k = fld.kind
    if k == "boolean":
        return mv[pos] != 0, pos + 1
    if k in ("int", "long"):
        return _read_long(mv, pos)
    if k == "float":
        return struct.unpack_from("<f", mv, pos)[0], pos + 4
    if k == "double":
        return struct.unpack_from("<d", mv, pos)[0], pos + 8
    if k in ("bytes", "string"):
        n, pos = _read_long(mv, pos)
        raw = bytes(mv[pos:pos + n])
        return (raw.decode() if k == "string" else raw), pos + n
    if k == "enum":
        i, pos = _read_long(mv, pos)
        return fld.symbols[i], pos
    if k == "null":
        return None, pos
    raise ValueError(f"unsupported avro kind {k}")


def _to_arrow(cols, fields: List[_Field]):
    import pyarrow as pa
    arrays = {}
    for fld, vals in zip(fields, cols):
        dt = _field_type(fld)
        if fld.logical == "date":
            arr = pa.array(vals, type=pa.int32()).cast(pa.date32())
        elif fld.logical == "timestamp-millis":
            vals = [None if v is None else v * 1000 for v in vals]
            arr = pa.array(vals, type=pa.int64()).cast(
                pa.timestamp("us", tz="UTC"))
        elif fld.logical == "timestamp-micros":
            arr = pa.array(vals, type=pa.int64()).cast(
                pa.timestamp("us", tz="UTC"))
        else:
            arr = pa.array(vals, type=T.to_arrow(dt))
        arrays[fld.name] = arr
    return pa.table(arrays)


class CpuAvroScanExec(MultiFileScanBase):
    """Avro scan through the shared multi-file machinery (PERFILE /
    COALESCING / MULTITHREADED strategies come from the base, like the
    reference's GpuAvroScan rides GpuMultiFileReader)."""

    format_name = "avro"
    file_ext = ".avro"

    def __init__(self, paths: Sequence[str],
                 columns: Optional[Sequence[str]] = None, **kw):
        super().__init__(paths, **kw)
        self.columns = list(columns) if columns else None

    def infer_schema(self) -> T.StructType:
        with open(self.paths[0], "rb") as f:
            fields, _, _, _ = _read_header(f)
        out = [T.StructField(fld.name, _field_type(fld),
                             fld.nullable) for fld in fields]
        if self.columns:
            by_name = {f.name: f for f in out}
            out = [by_name[c] for c in self.columns]
        return T.StructType(out)

    def read_file(self, path: str) -> Iterator[HostColumnarBatch]:
        with open(path, "rb") as f:
            fields, codec, sync, body = _read_header(f)
        if codec not in ("null", "deflate"):
            raise ValueError(f"unsupported avro codec {codec!r}")
        mv = memoryview(body)
        pos = 0
        rows = 0
        pending = []
        while pos < len(mv):
            count, pos = _read_long(mv, pos)
            size, pos = _read_long(mv, pos)
            block = bytes(mv[pos:pos + size])
            pos += size
            if bytes(mv[pos:pos + 16]) != sync:
                raise ValueError(f"corrupt avro block in {path} "
                                 "(bad sync marker)")
            pos += 16
            if codec == "deflate":
                block = zlib.decompress(block, -15)
            cols = _decode_block(block, count, fields)
            tab = _to_arrow(cols, fields)
            if self.columns:
                tab = tab.select(self.columns)
            pending.append(tab)
            rows += count
            if rows >= self.batch_rows:
                yield _emit(pending)
                pending, rows = [], 0
        if pending:
            yield _emit(pending)


def _emit(tables) -> HostColumnarBatch:
    import pyarrow as pa
    return batch_from_arrow(pa.concat_tables(tables))


TpuAvroScanExec, _avro_convert = tpu_scan_of(CpuAvroScanExec)

from spark_rapids_tpu.plan.overrides import register_exec  # noqa: E402

register_exec(CpuAvroScanExec, convert=_avro_convert,
              desc="avro scan (pure host block parser, like the "
                   "reference's AvroDataFileReader)")


# ---------------------------------------------------------------------------
# writer (roundtrip + test oracle)
# ---------------------------------------------------------------------------

def _avro_schema_of(schema: T.StructType) -> str:
    fields = []
    for f in schema.fields:
        dt = f.data_type
        if isinstance(dt, T.DateType):
            ft = {"type": "int", "logicalType": "date"}
        elif isinstance(dt, T.TimestampType):
            ft = {"type": "long", "logicalType": "timestamp-micros"}
        elif isinstance(dt, T.BooleanType):
            ft = "boolean"
        elif isinstance(dt, (T.ByteType, T.ShortType, T.IntegerType)):
            ft = "int"
        elif isinstance(dt, T.LongType):
            ft = "long"
        elif isinstance(dt, T.FloatType):
            ft = "float"
        elif isinstance(dt, T.DoubleType):
            ft = "double"
        elif isinstance(dt, T.StringType):
            ft = "string"
        elif isinstance(dt, T.BinaryType):
            ft = "bytes"
        else:
            raise ValueError(f"cannot write {dt.simple_name} to avro")
        fields.append({"name": f.name,
                       "type": ["null", ft] if f.nullable else ft})
    return json.dumps({"type": "record", "name": "row", "fields": fields})


class _AvroWriter:
    def __init__(self, path: str, schema: T.StructType, codec: str):
        import secrets
        self.schema = schema
        self.codec = codec
        self.sync = secrets.token_bytes(16)
        self.f = open(path, "wb")
        self.f.write(_MAGIC)
        meta = {b"avro.schema": _avro_schema_of(schema).encode(),
                b"avro.codec": codec.encode()}
        out = bytearray()
        _write_long(out, len(meta))
        for k, v in meta.items():
            _write_long(out, len(k))
            out += k
            _write_long(out, len(v))
            out += v
        _write_long(out, 0)
        self.f.write(bytes(out))
        self.f.write(self.sync)

    def write(self, rb) -> None:
        import pyarrow as pa
        n = rb.num_rows
        if n == 0:
            return
        rows = rb.to_pydict()
        out = bytearray()
        for r in range(n):
            for fld in self.schema.fields:
                _encode_value(out, rows[fld.name][r], fld)
        block = bytes(out)
        if self.codec == "deflate":
            comp = zlib.compressobj(6, zlib.DEFLATED, -15)
            block = comp.compress(block) + comp.flush()
        head = bytearray()
        _write_long(head, n)
        _write_long(head, len(block))
        self.f.write(bytes(head))
        self.f.write(block)
        self.f.write(self.sync)

    def close(self) -> None:
        self.f.close()


def write_avro(batches, path: str, schema: Optional[T.StructType] = None,
               codec: str = "deflate") -> None:
    def _struct_of(arrow_sch) -> T.StructType:
        return T.StructType([
            T.StructField(f.name, T.from_arrow(f.type), f.nullable)
            for f in arrow_sch])

    chunked_write(
        batches, path, schema,
        open_writer=lambda p, arrow_sch: _AvroWriter(
            p, _struct_of(arrow_sch), codec),
        write_batch=lambda w, rb: w.write(rb))


def _encode_value(out: bytearray, v, fld: T.StructField) -> None:
    import datetime
    dt = fld.data_type
    if fld.nullable:
        if v is None:
            _write_long(out, 0)
            return
        _write_long(out, 1)
    elif v is None:
        raise ValueError(f"null in non-nullable field {fld.name}")
    if isinstance(dt, T.BooleanType):
        out.append(1 if v else 0)
    elif isinstance(dt, T.DateType):
        days = (v - datetime.date(1970, 1, 1)).days \
            if isinstance(v, datetime.date) else int(v)
        _write_long(out, days)
    elif isinstance(dt, T.TimestampType):
        if isinstance(v, datetime.datetime):
            import calendar
            us = int(calendar.timegm(v.utctimetuple())) * 1_000_000 \
                + v.microsecond
        else:
            us = int(v)
        _write_long(out, us)
    elif isinstance(dt, (T.ByteType, T.ShortType, T.IntegerType, T.LongType)):
        _write_long(out, int(v))
    elif isinstance(dt, T.FloatType):
        out += struct.pack("<f", float(v))
    elif isinstance(dt, T.DoubleType):
        out += struct.pack("<d", float(v))
    elif isinstance(dt, T.StringType):
        raw = v.encode()
        _write_long(out, len(raw))
        out += raw
    elif isinstance(dt, T.BinaryType):
        _write_long(out, len(v))
        out += v
    else:
        raise ValueError(f"cannot encode {dt.simple_name}")
