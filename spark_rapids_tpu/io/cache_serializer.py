"""Cached-batch serializer: df.cache() storage.

Reference: ParquetCachedBatchSerializer.scala (1407) — spark.sql.cache
stores columnar batches as compressed parquet-encoded bytes on the host,
encoded/decoded on the accelerator when possible.  Same design: each cached
batch is an in-memory parquet file (schema + encodings + compression for
free), decoded back through the normal scan machinery.
"""

from __future__ import annotations

import io
from typing import List, Optional, Sequence

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.batch import HostColumnarBatch, batch_from_arrow
from spark_rapids_tpu.plan.base import Exec, LeafExec, UnaryExec


def serialize_cached(hb: HostColumnarBatch, compression: str = "zstd"
                     ) -> bytes:
    import pyarrow as pa
    import pyarrow.parquet as pq
    sink = io.BytesIO()
    tab = pa.Table.from_batches([hb.to_arrow()])
    pq.write_table(tab, sink, compression=compression)
    return sink.getvalue()


def deserialize_cached(data: bytes) -> HostColumnarBatch:
    import pyarrow.parquet as pq
    tab = pq.read_table(io.BytesIO(data))
    return batch_from_arrow(tab)


class CpuCachedScanExec(LeafExec):
    """Scan over a materialized cache (reference: the InMemoryTableScan
    path through the parquet cached-batch serializer).

    ``materialize(child)`` runs the child plan ONCE and keeps each
    partition as parquet-encoded bytes; re-executions decode from the
    cache."""

    def __init__(self, schema: T.StructType, num_partitions: int):
        super().__init__()
        self._schema = schema
        self._parts = num_partitions
        self._cache: Optional[List[List[bytes]]] = None
        self.compression = "zstd"

    @property
    def schema(self):
        return self._schema

    @property
    def num_partitions(self):
        return self._parts

    @property
    def is_materialized(self) -> bool:
        return self._cache is not None

    def materialize(self, child: Exec) -> "CpuCachedScanExec":
        from spark_rapids_tpu.columnar.batch import ColumnarBatch
        cache: List[List[bytes]] = []
        for p in range(child.num_partitions):
            frames = []
            for b in child.execute_partition(p):
                if isinstance(b, ColumnarBatch):
                    b = b.to_host()
                frames.append(serialize_cached(b, self.compression))
            cache.append(frames)
        self._cache = cache
        return self

    def cached_bytes(self) -> int:
        if self._cache is None:
            return 0
        return sum(len(f) for part in self._cache for f in part)

    def execute_partition(self, pidx: int):
        if self._cache is None:
            raise RuntimeError("cache not materialized")
        for frame in self._cache[pidx]:
            yield deserialize_cached(frame)

    def node_desc(self):
        state = "materialized" if self.is_materialized else "pending"
        return f"CachedScan[{self._parts}p, {state}]"


class TpuCachedScanExec(CpuCachedScanExec):
    is_device = True

    def __init__(self, cpu: CpuCachedScanExec):
        super().__init__(cpu.schema, cpu.num_partitions)
        self._cache = cpu._cache
        self.compression = cpu.compression

    def execute_partition(self, pidx):
        from spark_rapids_tpu.exec.basic import upload_batches
        yield from upload_batches(super().execute_partition(pidx))

    def node_desc(self):
        return "Tpu" + super().node_desc()


from spark_rapids_tpu.plan import typechecks as TS  # noqa: E402
from spark_rapids_tpu.plan.overrides import register_exec  # noqa: E402

register_exec(CpuCachedScanExec,
              convert=lambda p, m: TpuCachedScanExec(p),
              sig=TS.BASIC_WITH_ARRAYS,
              desc="parquet-encoded in-memory cache scan")
