"""Local file-range cache.

Reference: the closed-source FileCache (SURVEY.md §2.7 — caches remote file
ranges, footers and data chunks, on local disk; hooks in GpuParquetScan/
GpuOrcScan, locality manager on the driver).  Reimplemented open: an
LRU-bounded local store keyed by (path, mtime, offset, length), so repeated
scans of remote files hit local disk.

Scans call ``get_range(path, offset, length, loader)`` — loader reads from
the source on miss.  Local files bypass the cache (no benefit)."""

from __future__ import annotations

import collections
import hashlib
import os
import tempfile
import threading
from typing import Callable, Optional

from spark_rapids_tpu.io.multifile import is_cloud_path


class FileCache:
    def __init__(self, directory: Optional[str] = None,
                 max_bytes: int = 1 << 30):
        self.dir = directory or tempfile.mkdtemp(prefix="tpu_filecache_")
        os.makedirs(self.dir, exist_ok=True)
        self.max_bytes = max_bytes
        self._lock = threading.Lock()
        # key -> size, in LRU order (move_to_end on hit)
        self._entries: "collections.OrderedDict[str, int]" = \
            collections.OrderedDict()
        self._bytes = 0
        self.hits = 0
        self.misses = 0

    def _key(self, path: str, mtime: float, offset: int, length: int) -> str:
        h = hashlib.sha256(
            f"{path}|{mtime}|{offset}|{length}".encode()).hexdigest()[:32]
        return h

    def _local_path(self, key: str) -> str:
        return os.path.join(self.dir, key)

    def get_range(self, path: str, offset: int, length: int,
                  loader: Callable[[], bytes],
                  mtime: Optional[float] = None) -> bytes:
        """Cached read of ``path[offset:offset+length]``; loader supplies
        the bytes on miss.  mtime participates in the key so stale entries
        die with the source file's modification."""
        if mtime is None:
            try:
                mtime = os.path.getmtime(path)
            except OSError:
                mtime = 0.0
        key = self._key(path, mtime, offset, length)
        lp = self._local_path(key)
        with self._lock:
            if key in self._entries:
                self._entries.move_to_end(key)
                self.hits += 1
                hit = True
            else:
                hit = False
        if hit:
            try:
                with open(lp, "rb") as f:
                    return f.read()
            except OSError:
                pass   # evicted underneath us; fall through to load
        data = loader()
        with self._lock:
            self.misses += 1
            if key not in self._entries:
                tmp = lp + ".tmp"
                with open(tmp, "wb") as f:
                    f.write(data)
                os.replace(tmp, lp)
                self._entries[key] = len(data)
                self._bytes += len(data)
                self._evict_locked()
        return data

    def _evict_locked(self) -> None:
        while self._bytes > self.max_bytes and self._entries:
            key, size = self._entries.popitem(last=False)
            self._bytes -= size
            try:
                os.unlink(self._local_path(key))
            except OSError:
                pass

    @property
    def cached_bytes(self) -> int:
        with self._lock:
            return self._bytes

    def clear(self) -> None:
        with self._lock:
            for key in list(self._entries):
                try:
                    os.unlink(self._local_path(key))
                except OSError:
                    pass
            self._entries.clear()
            self._bytes = 0


_ACTIVE: Optional[FileCache] = None
_LOCK = threading.Lock()


def get_file_cache(conf=None) -> Optional[FileCache]:
    """The process-wide cache, created on first use when enabled
    (reference: FileCache.init from the executor plugin)."""
    global _ACTIVE
    from spark_rapids_tpu import config as C
    with _LOCK:
        if _ACTIVE is None and conf is not None and \
                str(conf.get(C.FILECACHE_ENABLED.key)).lower() == "true":
            _ACTIVE = FileCache(
                max_bytes=C.parse_bytes(conf.get(C.FILECACHE_MAX_BYTES.key)))
        return _ACTIVE


def cached_read(path: str, conf=None) -> bytes:
    """Whole-file cached read for remote paths; local paths read directly
    (the integration point the scans use)."""
    cache = get_file_cache(conf)
    if cache is None or not is_cloud_path(path):
        with open(path, "rb") as f:
            return f.read()
    size = os.path.getsize(path)
    return cache.get_range(path, 0, size,
                           lambda: open(path, "rb").read())
