"""Multi-file reader strategies shared by every format scan.

Reference: ``GpuMultiFileReader.scala`` (1271 LoC) — three strategies chosen
by ``spark.rapids.sql.format.<fmt>.reader.type`` (RapidsConf.scala:314
RapidsReaderType AUTO/COALESCING/MULTITHREADED/PERFILE):

- PERFILE: one partition per file, read lazily
  (reference: FilePartitionReaderFactory default path).
- COALESCING: bin-pack small files into partitions and stitch their batches
  into target-sized output batches
  (reference: MultiFileCoalescingPartitionReaderBase, GpuMultiFileReader.scala:827).
- MULTITHREADED: pipelined background reads on a shared thread pool, yielded
  in order (reference: MultiFileCloudPartitionReaderBase, :342).
- AUTO: MULTITHREADED for cloud-scheme paths (s3://...), COALESCING locally
  (reference: AUTO picks by cloud-vs-local path).

TPU note: everything here is host-side IO staging; the device never sees a
file byte.  Scans subclass ``MultiFileScanBase`` and provide ``read_file``.
"""

from __future__ import annotations

import concurrent.futures
import glob as _glob
import os
import threading
from typing import Iterator, List, Optional, Sequence

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.batch import (HostColumnarBatch,
                                             concat_host_batches)
from spark_rapids_tpu.plan.base import LeafExec

PERFILE = "PERFILE"
COALESCING = "COALESCING"
MULTITHREADED = "MULTITHREADED"
AUTO = "AUTO"

_CLOUD_SCHEMES = ("s3://", "s3a://", "gs://", "abfs://", "abfss://",
                  "wasb://", "http://", "https://")

#: steady-state scan cache for repeated queries over static files: host
#:  tier keeps decoded batches, device tier keeps uploaded batches.  Off by
#: default (unbounded residency is only right for benchmark/repeat-query
#: harnesses — the reference's analog is the file cache + device-resident
#: shuffle catalog, filecache.scala / ShuffleBufferCatalog).  Keyed by
#: (paths+mtimes, columns, predicate, pidx, tier), so file changes miss.
SCAN_CACHE_ENABLED = False
_SCAN_CACHE: dict = {}
_SCAN_CACHE_LOCK = threading.Lock()


def enable_scan_cache(on: bool = True) -> None:
    global SCAN_CACHE_ENABLED
    SCAN_CACHE_ENABLED = on
    if not on:
        with _SCAN_CACHE_LOCK:
            _SCAN_CACHE.clear()


def _shallow_copy_batch(b):
    """Cache hits hand out fresh batch shells: downstream execs may set
    ``names``/rewrap columns, which must never write through to the
    cached object (the column planes themselves are immutable arrays)."""
    from spark_rapids_tpu.columnar.batch import (ColumnarBatch,
                                                 HostColumnarBatch)
    if isinstance(b, ColumnarBatch):
        return ColumnarBatch(list(b.columns), b.row_count,
                             list(b.names) if b.names else b.names)
    return HostColumnarBatch(list(b.columns), b.row_count,
                             list(b.names) if b.names else b.names)


# shared background-read pool (reference: MultiFileReaderThreadPool)
_POOL: Optional[concurrent.futures.ThreadPoolExecutor] = None
_POOL_SIZE = 0
_RETIRED_POOLS: List[concurrent.futures.ThreadPoolExecutor] = []
_POOL_LOCK = threading.Lock()


def reader_pool(num_threads: int) -> concurrent.futures.ThreadPoolExecutor:
    global _POOL, _POOL_SIZE
    with _POOL_LOCK:
        if _POOL is None or _POOL_SIZE < num_threads:
            if _POOL is not None:
                # in-flight scans still hold the old pool; retiring (not
                # shutting down) keeps their submits valid until they drain
                _RETIRED_POOLS.append(_POOL)
            _POOL = concurrent.futures.ThreadPoolExecutor(
                max_workers=num_threads,
                thread_name_prefix="tpu-multifile-read")
            _POOL_SIZE = num_threads
        return _POOL


def expand_paths(paths: Sequence[str], ext: str) -> List[str]:
    """Expands dirs/globs into a sorted file list (FilePartition planning)."""
    expanded: List[str] = []
    for p in paths:
        if any(p.startswith(s) for s in _CLOUD_SCHEMES):
            expanded.append(p)  # remote paths pass through unexpanded
        elif os.path.isdir(p):
            hits = sorted(
                _glob.glob(os.path.join(p, "**", f"*{ext}"), recursive=True))
            expanded.extend(h for h in hits
                            if not os.path.basename(h).startswith((".", "_")))
        elif any(ch in p for ch in "*?["):
            expanded.extend(sorted(_glob.glob(p)))
        else:
            if not os.path.exists(p):
                raise FileNotFoundError(f"input path does not exist: {p}")
            expanded.append(p)
    if not expanded:
        raise FileNotFoundError(f"no input files in {list(paths)}")
    return expanded


def is_cloud_path(path: str) -> bool:
    return any(path.startswith(s) for s in _CLOUD_SCHEMES)


class MultiFileScanBase(LeafExec):
    """Base for file-format scans: owns path expansion, the reader-strategy
    partition planning, and batch stitching.  Subclasses implement
    ``read_file(path)`` (host decode) and ``infer_schema()``."""

    format_name = "file"
    file_ext = ""

    def __init__(self, paths: Sequence[str],
                 reader_type: str = AUTO,
                 batch_rows: int = 1 << 20,
                 batch_bytes: int = 512 << 20,
                 coalesce_target_bytes: int = 128 << 20,
                 num_threads: int = 8):
        super().__init__()
        self.paths = expand_paths(paths, self.file_ext)
        self.reader_type = reader_type.upper()
        if self.reader_type not in (PERFILE, COALESCING, MULTITHREADED, AUTO):
            raise ValueError(f"unknown reader type {reader_type!r}")
        self.batch_rows = batch_rows
        self.batch_bytes = batch_bytes
        self.coalesce_target_bytes = coalesce_target_bytes
        self.num_threads = num_threads
        self._schema: Optional[T.StructType] = None
        self._partitions: Optional[List[List[str]]] = None

    # -- subclass surface ---------------------------------------------------
    def read_file(self, path: str) -> Iterator[HostColumnarBatch]:
        raise NotImplementedError

    def infer_schema(self) -> T.StructType:
        raise NotImplementedError

    # -- planning -----------------------------------------------------------
    @property
    def schema(self) -> T.StructType:
        if self._schema is None:
            self._schema = self.infer_schema()
        return self._schema

    def _effective_type(self) -> str:
        if self.reader_type != AUTO:
            return self.reader_type
        if any(is_cloud_path(p) for p in self.paths):
            return MULTITHREADED
        return COALESCING if len(self.paths) > 1 else PERFILE

    def _file_size(self, path: str) -> int:
        try:
            return os.path.getsize(path)
        except OSError:
            return self.coalesce_target_bytes  # unknown: assume large

    def _plan_partitions(self) -> List[List[str]]:
        if self._partitions is not None:
            return self._partitions
        eff = self._effective_type()
        if eff == PERFILE:
            parts = [[p] for p in self.paths]
        else:
            # bin-pack consecutive files up to the coalesce target
            # (reference coalescing reader groups by total chunk bytes)
            parts, cur, cur_bytes = [], [], 0
            for p in self.paths:
                sz = self._file_size(p)
                if cur and cur_bytes + sz > self.coalesce_target_bytes:
                    parts.append(cur)
                    cur, cur_bytes = [], 0
                cur.append(p)
                cur_bytes += sz
            if cur:
                parts.append(cur)
        self._partitions = parts
        return parts

    @property
    def num_partitions(self) -> int:
        return len(self._plan_partitions())

    # -- execution ----------------------------------------------------------
    def _scan_cache_extra(self):
        """Format-specific decode options that must key the scan cache
        (schema/serde/parse options) — formats with such options override
        (hive/csv); default formats decode from file metadata alone."""
        return ()

    def _scan_cache_key(self, pidx: int, tier: str):
        files = tuple((p, os.path.getmtime(p) if os.path.exists(p) else 0)
                      for p in self.paths)
        pred = getattr(self, "predicate", None)
        # key by the partition's ACTUAL file group, not the bare index:
        # two scans over the same files under different reader conf
        # (reader_type, coalesce target) map pidx to different groups and
        # must not alias each other's cache entries (ADVICE r4)
        group = tuple(self._plan_partitions()[pidx])
        # encoded vs plain batches must not alias: a cached dictionary
        # batch served to an encoding-disabled session would change plans
        from spark_rapids_tpu.columnar import encoding as _ENC
        return (self.format_name, files,
                tuple(self.columns or ()) if hasattr(self, "columns")
                else (),
                None if pred is None else pred.sql(),
                self._scan_cache_extra(), group, tier,
                ("enc", _ENC.ENCODING_ENABLED, _ENC.RLE_ENABLED,
                 _ENC.MAX_DICTIONARY_SIZE))

    def execute_partition(self, pidx: int):
        if SCAN_CACHE_ENABLED:
            key = self._scan_cache_key(pidx, "host")
            with _SCAN_CACHE_LOCK:
                cached = _SCAN_CACHE.get(key)
            if cached is None:
                cached = list(self._scan_partition(pidx))
                with _SCAN_CACHE_LOCK:
                    _SCAN_CACHE[key] = cached
            yield from (_shallow_copy_batch(b) for b in cached)
            return
        yield from self._scan_partition(pidx)

    def _scan_partition(self, pidx: int):
        files = self._plan_partitions()[pidx]
        eff = self._effective_type()
        if eff == MULTITHREADED:
            it = self._read_pipelined(files)
        else:
            it = self._read_sequential(files)
        if eff in (COALESCING, MULTITHREADED) and len(files) > 1:
            yield from self._stitch(it)
        else:
            yield from it

    def _read_sequential(self, files):
        for p in files:
            yield from self.read_file(p)

    def _read_pipelined(self, files):
        """Background reads with bounded lookahead, yielded in file order
        (reference: MultiFileCloudPartitionReaderBase pipelining)."""
        pool = reader_pool(self.num_threads)
        lookahead = max(1, min(self.num_threads, len(files)))
        futures = {}
        nxt = 0
        for i in range(min(lookahead, len(files))):
            futures[i] = pool.submit(lambda p=files[i]: list(self.read_file(p)))
        for i in range(len(files)):
            batches = futures.pop(i).result()
            j = i + lookahead
            if j < len(files):
                futures[j] = pool.submit(
                    lambda p=files[j]: list(self.read_file(p)))
            yield from batches

    def _stitch(self, batches):
        """Concats small batches up to the row/byte targets so downstream
        device kernels see large batches (COALESCING semantics)."""
        pending: List[HostColumnarBatch] = []
        rows = 0
        nbytes = 0
        for b in batches:
            if b.row_count == 0:
                continue
            pending.append(b)
            rows += b.row_count
            nbytes += b.nbytes()
            if rows >= self.batch_rows or nbytes >= self.batch_bytes:
                yield concat_host_batches(pending) if len(pending) > 1 \
                    else pending[0]
                pending, rows, nbytes = [], 0, 0
        if pending:
            yield concat_host_batches(pending) if len(pending) > 1 \
                else pending[0]

    def node_desc(self):
        base = os.path.basename(self.paths[0])
        extra = f"+{len(self.paths) - 1}" if len(self.paths) > 1 else ""
        return (f"{self.format_name.capitalize()}Scan[{base}{extra}]"
                f"({self._effective_type().lower()})")


# -- device-feeding variants (host decode -> semaphore -> upload) -----------

class _TpuFileScanMixin:
    is_device = True

    def execute_partition(self, pidx):
        from spark_rapids_tpu.exec.basic import upload_batches
        if SCAN_CACHE_ENABLED:
            key = self._scan_cache_key(pidx, "device")
            with _SCAN_CACHE_LOCK:
                cached = _SCAN_CACHE.get(key)
            if cached is None:
                cached = list(upload_batches(super().execute_partition(pidx)))
                with _SCAN_CACHE_LOCK:
                    _SCAN_CACHE[key] = cached
            else:
                from spark_rapids_tpu.memory.device_manager import get_runtime
                rt = get_runtime()
                if rt is not None:        # device admission still applies
                    rt.semaphore.acquire_if_necessary()
            yield from (_shallow_copy_batch(b) for b in cached)
            return
        yield from upload_batches(super().execute_partition(pidx))

    def node_desc(self):
        return "Tpu" + super().node_desc()


def tpu_scan_of(cls):
    """Builds the Tpu* scan class + plan-rewrite convert fn for a Cpu* scan
    (shares all fields; the device variant only adds the upload stage)."""
    tpu = type("Tpu" + cls.__name__[3:], (_TpuFileScanMixin, cls), {})

    def convert(cpu, meta):
        import copy
        dev = copy.copy(cpu)
        dev.__class__ = tpu
        return dev

    return tpu, convert


def chunked_write(batches, path: str, schema, open_writer, write_batch):
    """Shared writer loop: lazy writer creation from the first batch, host
    download of device batches, empty-dataset schema fallback, close on
    every path (reference: ColumnarOutputWriter chunked TableWriter)."""
    import pyarrow as pa
    from spark_rapids_tpu.columnar.batch import ColumnarBatch
    writer = None
    try:
        for b in batches:
            if isinstance(b, ColumnarBatch):
                b = b.to_host()
            rb = b.to_arrow()
            if writer is None:
                writer = open_writer(path, rb.schema)
            write_batch(writer, rb)
        if writer is None:
            if schema is None:
                raise ValueError("cannot write empty dataset without schema")
            from spark_rapids_tpu import types as _T
            arrays = [pa.array([], type=_T.to_arrow(f.data_type))
                      for f in schema]
            names = [f.name for f in schema]
            writer = open_writer(path, pa.schema(
                [(n, a.type) for n, a in zip(names, arrays)]))
            # one explicit 0-row batch: some writers (ORC) emit no footer
            # metadata at all unless at least one write happens
            write_batch(writer, pa.RecordBatch.from_arrays(arrays,
                                                           names=names))
    finally:
        if writer is not None:
            writer.close()
