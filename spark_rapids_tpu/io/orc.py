"""ORC scan & write.

Reference: ``GpuOrcScan.scala`` (2918 LoC) — the same host-filter +
device-decode pattern as parquet (stripe-level predicate filtering on host,
cuDF ORC decode on device) and ``GpuOrcFileFormat.scala`` for writes.
TPU-first: host decode via arrow's ORC reader (the stripe stage), padded
device upload through the common transitions.
"""

from __future__ import annotations

from typing import Iterator, List, Optional, Sequence

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.batch import HostColumnarBatch, batch_from_arrow
from spark_rapids_tpu.io.multifile import (AUTO, MultiFileScanBase,
                                           chunked_write, tpu_scan_of)


#: observability: stripes skipped by statistics since process start
#: (tests assert the pushdown actually prunes)
STRIPES_SKIPPED = 0


class CpuOrcScanExec(MultiFileScanBase):
    format_name = "orc"
    file_ext = ".orc"

    def __init__(self, paths: Sequence[str],
                 columns: Optional[List[str]] = None,
                 predicate=None,
                 reader_type: str = AUTO, batch_rows: int = 1 << 20,
                 num_threads: int = 8):
        super().__init__(paths, reader_type=reader_type,
                         batch_rows=batch_rows, num_threads=num_threads)
        self.columns = columns
        #: pushed-down predicate: used for stats-based stripe skipping
        #: (conservative — the planner keeps the exact Filter above)
        self.predicate = predicate

    def infer_schema(self) -> T.StructType:
        import pyarrow.orc as porc
        sch = porc.ORCFile(self.paths[0]).schema
        fields = []
        for f in sch:
            if self.columns is not None and f.name not in self.columns:
                continue
            fields.append(T.StructField(f.name, T.from_arrow(f.type)))
        return T.StructType(fields)

    def read_file(self, path: str) -> Iterator[HostColumnarBatch]:
        import pyarrow.orc as porc
        from spark_rapids_tpu.io.orc_meta import surviving_stripes
        global STRIPES_SKIPPED
        f = porc.ORCFile(path)
        # stripe-at-a-time read (the reference decodes stripe ranges; stripes
        # are the ORC row-group analog and bound host memory per step),
        # filtered against the file-tail stripe statistics first
        # (reference: GpuOrcScan.scala host stripe filter)
        keep = surviving_stripes(path, self.predicate, f.nstripes)
        STRIPES_SKIPPED += f.nstripes - len(keep)
        for i in keep:
            tbl = f.read_stripe(i, columns=self.columns)
            import pyarrow as pa
            if isinstance(tbl, pa.RecordBatch):
                tbl = pa.Table.from_batches([tbl])
            for off in range(0, tbl.num_rows, self.batch_rows):
                chunk = tbl.slice(off, self.batch_rows)
                if chunk.num_rows:
                    yield batch_from_arrow(chunk)


TpuOrcScanExec, _orc_convert = tpu_scan_of(CpuOrcScanExec)

from spark_rapids_tpu.plan.overrides import register_exec  # noqa: E402

register_exec(CpuOrcScanExec, convert=_orc_convert,
              exprs_of=lambda p: [p.predicate]
              if p.predicate is not None else [],
              desc="ORC scan (stripe-stats pruning + host stripe decode "
                   "+ device upload)")


def write_orc(batches, path: str, schema: Optional[T.StructType] = None):
    """ORC writer (reference: GpuOrcFileFormat chunked TableWriter)."""
    import pyarrow as pa
    import pyarrow.orc as porc
    chunked_write(
        batches, path, schema,
        open_writer=lambda p, sch: porc.ORCWriter(p),
        write_batch=lambda w, rb: w.write(pa.Table.from_batches([rb])))
