"""ORC file-tail metadata: postscript/footer/stripe-statistics parsing and
stats-based stripe predicate filtering.

Reference: ``GpuOrcScan.scala`` (the host side reads the ORC tail, filters
stripes against the pushed-down predicate, and only decodes surviving
stripe ranges — GpuOrcScan.scala:2918 host stripe filter).  pyarrow's ORC
reader exposes no stripe statistics, so the tail is parsed here directly:
a minimal protobuf TLV walk over the ORC spec's Postscript / Footer /
Metadata messages (https://orc.apache.org/specification/ — public format),
handling UNCOMPRESSED and ZLIB tails (pyarrow's writer emits these).

Conservative contract: any stripe whose statistics cannot PROVE the
predicate unsatisfiable is kept; unknown codecs/types keep every stripe.
"""

from __future__ import annotations

import struct
import zlib
from typing import Dict, List, Optional, Tuple

from spark_rapids_tpu import types as T
from spark_rapids_tpu.expressions.base import Expression


# -- minimal protobuf wire-format walk --------------------------------------

def _varint(buf: bytes, i: int) -> Tuple[int, int]:
    shift = 0
    out = 0
    while True:
        b = buf[i]
        i += 1
        out |= (b & 0x7F) << shift
        if not b & 0x80:
            return out, i
        shift += 7


def _zigzag(v: int) -> int:
    return (v >> 1) ^ -(v & 1)


def _fields(buf: bytes):
    """Yields (field_no, wire_type, value) over one protobuf message."""
    i = 0
    n = len(buf)
    while i < n:
        tag, i = _varint(buf, i)
        fno, wt = tag >> 3, tag & 7
        if wt == 0:
            v, i = _varint(buf, i)
        elif wt == 2:
            ln, i = _varint(buf, i)
            v = buf[i:i + ln]
            i += ln
        elif wt == 5:
            v = buf[i:i + 4]
            i += 4
        elif wt == 1:
            v = buf[i:i + 8]
            i += 8
        else:
            raise ValueError(f"unsupported wire type {wt}")
        yield fno, wt, v


def _decompress(buf: bytes, codec: int, block: int) -> bytes:
    """ORC compressed streams: 3-byte chunk headers (len << 1 | original)."""
    if codec == 0:           # NONE
        return buf
    out = bytearray()
    i = 0
    while i + 3 <= len(buf):
        hdr = buf[i] | (buf[i + 1] << 8) | (buf[i + 2] << 16)
        i += 3
        ln = hdr >> 1
        chunk = buf[i:i + ln]
        i += ln
        if hdr & 1:          # original (stored uncompressed)
            out += chunk
        elif codec == 1:     # ZLIB (raw deflate)
            out += zlib.decompress(chunk, wbits=-15)
        else:                # SNAPPY/LZO/LZ4/ZSTD: not parsed here
            raise NotImplementedError(f"ORC codec {codec}")
    return bytes(out)


# -- column statistics -------------------------------------------------------

class ColumnStats:
    """min/max/has_null for one column of one stripe (None = unknown)."""

    __slots__ = ("num_values", "minimum", "maximum", "has_null")

    def __init__(self):
        self.num_values: Optional[int] = None
        self.minimum = None
        self.maximum = None
        self.has_null: Optional[bool] = None

    def __repr__(self):
        return (f"ColumnStats(n={self.num_values}, min={self.minimum!r}, "
                f"max={self.maximum!r}, nulls={self.has_null})")


def _parse_col_stats(buf: bytes) -> ColumnStats:
    cs = ColumnStats()
    for fno, wt, v in _fields(buf):
        if fno == 1 and wt == 0:          # numberOfValues
            cs.num_values = v
        elif fno == 2 and wt == 2:        # IntegerStatistics
            for f2, w2, v2 in _fields(v):
                if f2 == 1 and w2 == 0:
                    cs.minimum = _zigzag(v2)
                elif f2 == 2 and w2 == 0:
                    cs.maximum = _zigzag(v2)
        elif fno == 3 and wt == 2:        # DoubleStatistics
            for f2, w2, v2 in _fields(v):
                if f2 == 1 and w2 == 1:
                    cs.minimum = struct.unpack("<d", v2)[0]
                elif f2 == 2 and w2 == 1:
                    cs.maximum = struct.unpack("<d", v2)[0]
        elif fno == 4 and wt == 2:        # StringStatistics
            for f2, w2, v2 in _fields(v):
                if f2 == 1 and w2 == 2:
                    cs.minimum = v2.decode("utf-8", "replace")
                elif f2 == 2 and w2 == 2:
                    cs.maximum = v2.decode("utf-8", "replace")
        elif fno == 10 and wt == 0:       # hasNull
            cs.has_null = bool(v)
    return cs


class OrcTail:
    """Parsed ORC tail: stripe count + per-stripe per-column statistics.

    ``stripe_stats[s][c]`` is the ColumnStats of flattened-schema column
    ``c`` in stripe ``s`` (column 0 = root struct; top-level field i of a
    flat schema maps to column i+1)."""

    def __init__(self, nstripes: int,
                 stripe_stats: List[List[ColumnStats]],
                 field_names: List[str]):
        self.nstripes = nstripes
        self.stripe_stats = stripe_stats
        self.field_names = field_names

    def col_index(self, name: str) -> Optional[int]:
        """Flattened column index of a TOP-LEVEL field (flat schemas)."""
        try:
            return self.field_names.index(name) + 1
        except ValueError:
            return None


def read_orc_tail(path: str) -> Optional[OrcTail]:
    """Parses the ORC tail; None when the tail cannot be parsed (unknown
    codec, nested schema quirks) — callers then keep every stripe."""
    try:
        with open(path, "rb") as f:
            f.seek(0, 2)
            size = f.tell()
            take = min(size, 16 << 10)
            f.seek(size - take)
            tail = f.read(take)
        ps_len = tail[-1]
        ps = tail[-1 - ps_len:-1]
        footer_len = meta_len = 0
        codec = 0
        block = 256 << 10
        for fno, wt, v in _fields(ps):
            if fno == 1 and wt == 0:
                footer_len = v
            elif fno == 2 and wt == 0:
                codec = v
            elif fno == 3 and wt == 0:
                block = v
            elif fno == 5 and wt == 0:
                meta_len = v
        need = 1 + ps_len + footer_len + meta_len
        if need > len(tail):
            with open(path, "rb") as f:
                f.seek(size - need)
                tail = f.read(need)
        footer_buf = tail[-1 - ps_len - footer_len:-1 - ps_len]
        meta_buf = tail[-1 - ps_len - footer_len - meta_len:
                        -1 - ps_len - footer_len]
        footer = _decompress(footer_buf, codec, block)
        meta = _decompress(meta_buf, codec, block) if meta_len else b""
        # Footer: field 3 = StripeInformation (repeated), field 4 = Type
        nstripes = 0
        field_names: List[str] = []
        for fno, wt, v in _fields(footer):
            if fno == 3 and wt == 2:
                nstripes += 1
            elif fno == 4 and wt == 2 and not field_names:
                # first Type message = root struct; field 3 = fieldNames
                for f2, w2, v2 in _fields(v):
                    if f2 == 3 and w2 == 2:
                        field_names.append(v2.decode("utf-8", "replace"))
        # Metadata: field 1 = StripeStatistics { repeated colStats = 1 }
        stripe_stats: List[List[ColumnStats]] = []
        for fno, wt, v in _fields(meta):
            if fno == 1 and wt == 2:
                cols = [_parse_col_stats(v2)
                        for f2, w2, v2 in _fields(v) if f2 == 1 and w2 == 2]
                stripe_stats.append(cols)
        return OrcTail(nstripes, stripe_stats, field_names)
    except Exception:
        return None


# -- predicate vs statistics -------------------------------------------------

def _stat_range(tail: OrcTail, stripe: int, name: str):
    """(min, max, has_null) of a column in a stripe, or None if unknown."""
    if stripe >= len(tail.stripe_stats):
        return None
    ci = tail.col_index(name)
    if ci is None or ci >= len(tail.stripe_stats[stripe]):
        return None
    cs = tail.stripe_stats[stripe][ci]
    if cs.minimum is None or cs.maximum is None:
        return None
    return cs.minimum, cs.maximum, cs.has_null


def stripe_may_match(tail: OrcTail, stripe: int,
                     predicate: Expression) -> bool:
    """False only when the statistics PROVE no row of the stripe can pass
    (reference: the SearchArgument evaluation in the ORC host filter)."""
    from spark_rapids_tpu.expressions import predicates as P
    from spark_rapids_tpu.expressions.base import (AttributeReference,
                                                   BoundReference, Literal)

    def col_name(e):
        if isinstance(e, (AttributeReference, BoundReference)):
            return getattr(e, "ref_name", None)
        return None

    def lit_value(e):
        return e.value if isinstance(e, Literal) else None

    e = predicate
    if isinstance(e, P.And):
        return all(stripe_may_match(tail, stripe, c) for c in e.children)
    if isinstance(e, P.Or):
        return any(stripe_may_match(tail, stripe, c) for c in e.children)
    binops = (P.EqualTo, P.LessThan, P.GreaterThan, P.LessThanOrEqual,
              P.GreaterThanOrEqual)
    if isinstance(e, binops):
        left, right = e.children
        name, val = col_name(left), lit_value(right)
        flipped = False
        if name is None:
            name, val = col_name(right), lit_value(left)
            flipped = True
        if name is None or val is None:
            return True
        rng = _stat_range(tail, stripe, name)
        if rng is None:
            return True
        lo, hi, _nulls = rng
        try:
            if isinstance(e, P.EqualTo):
                return lo <= val <= hi
            if (isinstance(e, P.LessThan) and not flipped) or \
                    (isinstance(e, P.GreaterThan) and flipped):
                return lo < val          # some row < val possible
            if (isinstance(e, P.GreaterThan) and not flipped) or \
                    (isinstance(e, P.LessThan) and flipped):
                return hi > val
            if (isinstance(e, P.LessThanOrEqual) and not flipped) or \
                    (isinstance(e, P.GreaterThanOrEqual) and flipped):
                return lo <= val
            return hi >= val
        except TypeError:
            return True                  # incomparable types: keep
    if isinstance(e, P.IsNotNull):
        name = col_name(e.children[0])
        if name is None:
            return True
        rng = _stat_range(tail, stripe, name)
        if rng is None:
            return True
        _lo, _hi, _nulls = rng
        # min/max known => at least one non-null value exists
        return True
    return True


def surviving_stripes(path: str, predicate: Optional[Expression],
                      nstripes: int) -> List[int]:
    """Stripe indices that may contain matching rows (all when stats are
    unavailable or the predicate is None)."""
    if predicate is None:
        return list(range(nstripes))
    tail = read_orc_tail(path)
    if tail is None or not tail.stripe_stats:
        return list(range(nstripes))
    return [s for s in range(nstripes)
            if stripe_may_match(tail, s, predicate)]
