"""Parquet scan & write.

Reference: ``GpuParquetScan.scala`` (2897 LoC) — host footer parse +
row-group predicate pushdown + device decode; writer via ColumnarOutputWriter.
Here: pyarrow handles footer/row-group plumbing (the host stage), decode is
host-side (see io/__init__ docstring for why that is the TPU-first choice),
and predicate pushdown maps our Expressions to arrow dataset filters.

Multi-file strategies (PERFILE/COALESCING/MULTITHREADED/AUTO) come from
``io.multifile`` (reference: GpuMultiFileReader.scala, RapidsConf READER_TYPE).
"""

from __future__ import annotations

import os
from typing import List, Optional, Sequence

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.batch import batch_from_arrow
from spark_rapids_tpu.expressions.base import Expression
from spark_rapids_tpu.io.multifile import (AUTO, MultiFileScanBase,
                                           chunked_write, tpu_scan_of)


def _expr_to_arrow_filter(expr: Expression):
    """Best-effort translation of a predicate to a pyarrow dataset filter
    (predicate pushdown; unsupported shapes return None and filter later)."""
    import pyarrow.compute as pc
    from spark_rapids_tpu.expressions import predicates as P
    from spark_rapids_tpu.expressions.base import (AttributeReference,
                                                   BoundReference, Literal)

    def leaf(e):
        if isinstance(e, AttributeReference):
            return pc.field(e.ref_name)
        if isinstance(e, BoundReference):
            return pc.field(e.ref_name)
        if isinstance(e, Literal):
            return e.value
        return None

    if isinstance(expr, P.And):
        l, r = (_expr_to_arrow_filter(c) for c in expr.children)
        return None if l is None or r is None else l & r
    if isinstance(expr, P.Or):
        l, r = (_expr_to_arrow_filter(c) for c in expr.children)
        return None if l is None or r is None else l | r
    ops = {P.EqualTo: lambda a, b: a == b, P.LessThan: lambda a, b: a < b,
           P.GreaterThan: lambda a, b: a > b,
           P.LessThanOrEqual: lambda a, b: a <= b,
           P.GreaterThanOrEqual: lambda a, b: a >= b}
    for cls, fn in ops.items():
        if type(expr) is cls:
            a, b = (leaf(c) for c in expr.children)
            if a is not None and b is not None:
                return fn(a, b)
    if isinstance(expr, P.IsNotNull):
        a = leaf(expr.children[0])
        return None if a is None else a.is_valid()
    return None


#: Spark writer metadata tags marking LEGACY (julian hybrid) datetime
#: rebase (reference: datetimeRebaseUtils.scala reads the same tags)
_LEGACY_DATETIME_TAG = b"org.apache.spark.legacyDateTime"
_LEGACY_INT96_TAG = b"org.apache.spark.legacyINT96"


def _widen(a, b):
    """Least common arrow type for cross-file schema evolution (the safe
    widenings Spark's vectorized reader performs: int upcasts, float ->
    double, ts unit alignment); None = incompatible."""
    import pyarrow as pa
    if a.equals(b):
        return a
    ints = [pa.int8(), pa.int16(), pa.int32(), pa.int64()]
    if a in ints and b in ints:
        return ints[max(ints.index(a), ints.index(b))]
    floats = [pa.float32(), pa.float64()]
    if a in floats and b in floats:
        return pa.float64()
    if (a in ints and b in floats) or (a in floats and b in ints):
        return pa.float64()
    if pa.types.is_timestamp(a) and pa.types.is_timestamp(b):
        return pa.timestamp("us")
    return None


class CpuParquetScanExec(MultiFileScanBase):
    format_name = "parquet"
    file_ext = ".parquet"

    def __init__(self, paths: Sequence[str],
                 columns: Optional[List[str]] = None,
                 predicate: Optional[Expression] = None,
                 batch_rows: int = 1 << 20,
                 reader_type: str = AUTO, num_threads: int = 8):
        super().__init__(paths, reader_type=reader_type,
                         batch_rows=batch_rows, num_threads=num_threads)
        self.columns = columns
        self.predicate = predicate
        self._unified: Optional[object] = None  # arrow schema across files

    # -- planning-time metadata (host footer stage) -------------------------
    def _unified_schema(self):
        """Cross-file schema evolution (reference: the multi-file readers
        resolve each file's footer schema against the read schema —
        GpuParquetScan evolution handling): union of columns across every
        footer with safe type widening; later files may add columns
        (nulls elsewhere) or widen numeric types."""
        if self._unified is not None:
            return self._unified
        import pyarrow as pa
        import pyarrow.parquet as pq
        fields: dict = {}
        order: List[str] = []
        for p in self.paths:
            sch = pq.read_schema(p)
            for f in sch:
                # a writer that received encoded batches embeds a
                # dictionary arrow type in the footer metadata; the
                # LOGICAL read schema is the value type (the scan
                # re-encodes via read_dictionary regardless)
                ftype = f.type
                if pa.types.is_dictionary(ftype):
                    ftype = ftype.value_type
                if f.name not in fields:
                    fields[f.name] = ftype
                    order.append(f.name)
                else:
                    w = _widen(fields[f.name], ftype)
                    if w is None:
                        raise TypeError(
                            f"parquet schema evolution cannot reconcile "
                            f"column {f.name!r}: {fields[f.name]} vs "
                            f"{f.type} ({p})")
                    fields[f.name] = w
        self._unified = pa.schema([pa.field(n, fields[n], nullable=True)
                                   for n in order])
        return self._unified

    def infer_schema(self) -> T.StructType:
        out = []
        for f in self._unified_schema():
            if self.columns is not None and f.name not in self.columns:
                continue
            out.append(T.StructField(f.name, T.from_arrow(f.type)))
        return T.StructType(out)

    def _rebase_flags(self, pqfile):
        """(legacy_datetime, legacy_int96, int96_columns) from the footer."""
        md = pqfile.metadata.metadata or {}
        legacy_dt = _LEGACY_DATETIME_TAG in md
        legacy_96 = _LEGACY_INT96_TAG in md
        int96_cols = set()
        psch = pqfile.metadata.schema
        for i in range(len(psch)):
            col = psch.column(i)
            if col.physical_type == "INT96":
                int96_cols.add(col.name)
        return legacy_dt, legacy_96, int96_cols

    def _adapt(self, tbl, legacy_dt: bool, legacy_96: bool, int96_cols):
        """Rebase + evolve one decoded table to the unified read schema."""
        import numpy as np
        import pyarrow as pa
        import pyarrow.compute as pc
        from spark_rapids_tpu.expressions.timezone_db import (
            rebase_julian_to_gregorian_days,
            rebase_julian_to_gregorian_micros)
        unified = self._unified_schema()
        canon_ts = T.to_arrow(T.TIMESTAMP)   # engine unit/tz convention
        cols = {}
        n = tbl.num_rows
        for f in unified:
            if self.columns is not None and f.name not in self.columns:
                continue
            if f.name in tbl.column_names:
                c = tbl.column(f.name).combine_chunks()
                want = canon_ts if pa.types.is_timestamp(f.type) \
                    else f.type
                if not c.type.equals(want):
                    c = pc.cast(c, want, safe=False)
                rebase_this = (legacy_dt or
                               (legacy_96 and f.name in int96_cols))
                if rebase_this and (pa.types.is_date(c.type) or
                                    pa.types.is_timestamp(c.type)):
                    mask = c.is_null().to_numpy(zero_copy_only=False)
                    if pa.types.is_date(c.type):
                        raw = c.cast(pa.int32()).fill_null(0) \
                            .to_numpy(zero_copy_only=False)
                        fixed = rebase_julian_to_gregorian_days(
                            raw.astype(np.int64)).astype(np.int32)
                        c = pa.array(fixed, type=pa.int32(),
                                     mask=mask).cast(c.type)
                    else:
                        raw = c.cast(pa.int64()).fill_null(0) \
                            .to_numpy(zero_copy_only=False)
                        fixed = rebase_julian_to_gregorian_micros(raw)
                        c = pa.array(fixed, type=pa.int64(),
                                     mask=mask).cast(c.type)
                cols[f.name] = c
            else:
                want = canon_ts if pa.types.is_timestamp(f.type) \
                    else f.type
                cols[f.name] = pa.nulls(n, type=want)
        return pa.table(cols)

    def _dictionary_columns(self, f, file_cols):
        """Columns whose parquet dictionary pages stay ENCODED through
        the scan (reference: the plugin executes over cuDF's encoded
        columns; here pyarrow hands back DictionaryArrays and the upload
        ships codes + a once-per-fingerprint dictionary).  String/binary
        columns only — the types whose decode the engine defers."""
        from spark_rapids_tpu.columnar import encoding as ENC
        import pyarrow as pa
        if not ENC.ENCODING_ENABLED:
            return None
        want = file_cols if file_cols is not None else \
            list(f.schema_arrow.names)
        out = [fld.name for fld in f.schema_arrow
               if fld.name in want and
               (pa.types.is_string(fld.type) or
                pa.types.is_large_string(fld.type) or
                pa.types.is_binary(fld.type))]
        return out or None

    def read_file(self, path: str):
        import pyarrow as pa
        import pyarrow.parquet as pq
        f = pq.ParquetFile(path)
        legacy_dt, legacy_96, int96_cols = self._rebase_flags(f)
        # int96 (and any arrow ns-unit writer) decodes as timestamp[ns];
        # the engine's timestamp unit is us, so those files adapt too
        non_us_ts = any(pa.types.is_timestamp(fld.type) and
                        str(fld.type.unit) != "us"
                        for fld in f.schema_arrow)
        # per-FILE evolution check: identically-schemaed part files (the
        # common multi-file case) keep the arrow filter-pushdown fast path
        evolved = len(self.paths) > 1 and \
            not f.schema_arrow.equals(self._unified_schema())
        needs_adapt = legacy_dt or bool(int96_cols) or non_us_ts or evolved
        flt = None if self.predicate is None else \
            _expr_to_arrow_filter(self.predicate)
        file_cols = None
        if self.columns is not None:
            present = set(f.schema_arrow.names)
            file_cols = [c for c in self.columns if c in present]
        # the rebase/evolution adapter casts through plain arrays, so
        # only adapt-free files keep their dictionary pages encoded
        dict_cols = None if needs_adapt else \
            self._dictionary_columns(f, file_cols)
        if flt is not None and not needs_adapt:
            import pyarrow.dataset as ds
            fmt = "parquet"
            if dict_cols:
                try:
                    fmt = ds.ParquetFileFormat(
                        read_options=ds.ParquetReadOptions(
                            dictionary_columns=set(dict_cols)))
                except Exception:  # noqa: BLE001 — dataset API drift:
                    fmt = "parquet"  # plain decode, never a scan failure
            dataset = ds.dataset(path, format=fmt)
            scanner = dataset.scanner(columns=file_cols, filter=flt,
                                      batch_size=self.batch_rows)
            for rb in scanner.to_batches():
                if rb.num_rows:
                    yield batch_from_arrow(pa.Table.from_batches([rb]))
            return
        if dict_cols:
            # the read_dictionary option only exists at open time; close
            # the metadata handle before reopening (fd pressure on wide
            # multi-file scans otherwise waits on GC)
            try:
                f.close()
            except AttributeError:  # older pyarrow: no explicit close
                pass
            f = pq.ParquetFile(path, read_dictionary=dict_cols)
        for rb in f.iter_batches(batch_size=self.batch_rows,
                                 columns=file_cols):
            if not rb.num_rows:
                continue
            tbl = pa.Table.from_batches([rb])
            if needs_adapt:
                tbl = self._adapt(tbl, legacy_dt, legacy_96, int96_cols)
            yield batch_from_arrow(tbl)


TpuParquetScanExec, _pq_convert = tpu_scan_of(CpuParquetScanExec)

# plan-rewrite registration (reference: ScanRule registry GpuOverrides.scala:3864)
from spark_rapids_tpu.plan.overrides import register_exec  # noqa: E402

register_exec(CpuParquetScanExec,
              convert=_pq_convert,
              exprs_of=lambda p: [p.predicate] if p.predicate is not None else [],
              desc="parquet scan (host decode + device upload)")


def write_parquet(batches, path: str, schema: Optional[T.StructType] = None):
    """Writer (reference: GpuParquetFileFormat + ColumnarOutputWriter chunked
    TableWriter; host-side arrow writer here)."""
    import pyarrow.parquet as pq
    chunked_write(
        batches, path, schema,
        open_writer=lambda p, sch: pq.ParquetWriter(p, sch),
        write_batch=lambda w, rb: w.write_batch(rb))
