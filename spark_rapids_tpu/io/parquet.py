"""Parquet scan & write.

Reference: ``GpuParquetScan.scala`` (2897 LoC) — host footer parse +
row-group predicate pushdown + device decode; writer via ColumnarOutputWriter.
Here: pyarrow handles footer/row-group plumbing (the host stage), decode is
host-side (see io/__init__ docstring for why that is the TPU-first choice),
and predicate pushdown maps our Expressions to arrow dataset filters.

Multi-file strategies (PERFILE/COALESCING/MULTITHREADED/AUTO) come from
``io.multifile`` (reference: GpuMultiFileReader.scala, RapidsConf READER_TYPE).
"""

from __future__ import annotations

import os
from typing import List, Optional, Sequence

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.batch import batch_from_arrow
from spark_rapids_tpu.expressions.base import Expression
from spark_rapids_tpu.io.multifile import (AUTO, MultiFileScanBase,
                                           chunked_write, tpu_scan_of)


def _expr_to_arrow_filter(expr: Expression):
    """Best-effort translation of a predicate to a pyarrow dataset filter
    (predicate pushdown; unsupported shapes return None and filter later)."""
    import pyarrow.compute as pc
    from spark_rapids_tpu.expressions import predicates as P
    from spark_rapids_tpu.expressions.base import (AttributeReference,
                                                   BoundReference, Literal)

    def leaf(e):
        if isinstance(e, AttributeReference):
            return pc.field(e.ref_name)
        if isinstance(e, BoundReference):
            return pc.field(e.ref_name)
        if isinstance(e, Literal):
            return e.value
        return None

    if isinstance(expr, P.And):
        l, r = (_expr_to_arrow_filter(c) for c in expr.children)
        return None if l is None or r is None else l & r
    if isinstance(expr, P.Or):
        l, r = (_expr_to_arrow_filter(c) for c in expr.children)
        return None if l is None or r is None else l | r
    ops = {P.EqualTo: lambda a, b: a == b, P.LessThan: lambda a, b: a < b,
           P.GreaterThan: lambda a, b: a > b,
           P.LessThanOrEqual: lambda a, b: a <= b,
           P.GreaterThanOrEqual: lambda a, b: a >= b}
    for cls, fn in ops.items():
        if type(expr) is cls:
            a, b = (leaf(c) for c in expr.children)
            if a is not None and b is not None:
                return fn(a, b)
    if isinstance(expr, P.IsNotNull):
        a = leaf(expr.children[0])
        return None if a is None else a.is_valid()
    return None


class CpuParquetScanExec(MultiFileScanBase):
    format_name = "parquet"
    file_ext = ".parquet"

    def __init__(self, paths: Sequence[str],
                 columns: Optional[List[str]] = None,
                 predicate: Optional[Expression] = None,
                 batch_rows: int = 1 << 20,
                 reader_type: str = AUTO, num_threads: int = 8):
        super().__init__(paths, reader_type=reader_type,
                         batch_rows=batch_rows, num_threads=num_threads)
        self.columns = columns
        self.predicate = predicate

    # -- planning-time metadata (host footer stage) -------------------------
    def infer_schema(self) -> T.StructType:
        import pyarrow.parquet as pq
        arrow_schema = pq.read_schema(self.paths[0])
        fields = []
        for f in arrow_schema:
            if self.columns is not None and f.name not in self.columns:
                continue
            fields.append(T.StructField(f.name, T.from_arrow(f.type)))
        return T.StructType(fields)

    def read_file(self, path: str):
        import pyarrow as pa
        import pyarrow.parquet as pq
        flt = None if self.predicate is None else \
            _expr_to_arrow_filter(self.predicate)
        cols = self.columns
        if flt is not None:
            import pyarrow.dataset as ds
            dataset = ds.dataset(path, format="parquet")
            scanner = dataset.scanner(columns=cols, filter=flt,
                                      batch_size=self.batch_rows)
            for rb in scanner.to_batches():
                if rb.num_rows:
                    yield batch_from_arrow(pa.Table.from_batches([rb]))
            return
        f = pq.ParquetFile(path)
        for rb in f.iter_batches(batch_size=self.batch_rows, columns=cols):
            if rb.num_rows:
                yield batch_from_arrow(pa.Table.from_batches([rb]))


TpuParquetScanExec, _pq_convert = tpu_scan_of(CpuParquetScanExec)

# plan-rewrite registration (reference: ScanRule registry GpuOverrides.scala:3864)
from spark_rapids_tpu.plan.overrides import register_exec  # noqa: E402

register_exec(CpuParquetScanExec,
              convert=_pq_convert,
              exprs_of=lambda p: [p.predicate] if p.predicate is not None else [],
              desc="parquet scan (host decode + device upload)")


def write_parquet(batches, path: str, schema: Optional[T.StructType] = None):
    """Writer (reference: GpuParquetFileFormat + ColumnarOutputWriter chunked
    TableWriter; host-side arrow writer here)."""
    import pyarrow.parquet as pq
    chunked_write(
        batches, path, schema,
        open_writer=lambda p, sch: pq.ParquetWriter(p, sch),
        write_batch=lambda w, rb: w.write_batch(rb))
