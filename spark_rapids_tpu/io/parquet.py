"""Parquet scan & write.

Reference: ``GpuParquetScan.scala`` (2897 LoC) — host footer parse +
row-group predicate pushdown + device decode; writer via ColumnarOutputWriter.
Here: pyarrow handles footer/row-group plumbing (the host stage), decode is
host-side (see io/__init__ docstring for why that is the TPU-first choice),
and predicate pushdown maps our Expressions to arrow dataset filters.

Partitioning: one partition per row-group span (reference coalesces small
files/row-groups; the COALESCING/MULTITHREADED strategies land with the
multi-file reader milestone, RapidsConf READER_TYPE).
"""

from __future__ import annotations

import glob as _glob
import os
from typing import List, Optional, Sequence

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.batch import (HostColumnarBatch,
                                             batch_from_arrow)
from spark_rapids_tpu.expressions.base import Expression
from spark_rapids_tpu.plan.base import LeafExec


def _expr_to_arrow_filter(expr: Expression):
    """Best-effort translation of a predicate to a pyarrow dataset filter
    (predicate pushdown; unsupported shapes return None and filter later)."""
    import pyarrow.compute as pc
    from spark_rapids_tpu.expressions import predicates as P
    from spark_rapids_tpu.expressions.base import (AttributeReference,
                                                   BoundReference, Literal)

    def leaf(e):
        if isinstance(e, AttributeReference):
            return pc.field(e.ref_name)
        if isinstance(e, BoundReference):
            return pc.field(e.ref_name)
        if isinstance(e, Literal):
            return e.value
        return None

    if isinstance(expr, P.And):
        l, r = (_expr_to_arrow_filter(c) for c in expr.children)
        return None if l is None or r is None else l & r
    if isinstance(expr, P.Or):
        l, r = (_expr_to_arrow_filter(c) for c in expr.children)
        return None if l is None or r is None else l | r
    ops = {P.EqualTo: lambda a, b: a == b, P.LessThan: lambda a, b: a < b,
           P.GreaterThan: lambda a, b: a > b,
           P.LessThanOrEqual: lambda a, b: a <= b,
           P.GreaterThanOrEqual: lambda a, b: a >= b}
    for cls, fn in ops.items():
        if type(expr) is cls:
            a, b = (leaf(c) for c in expr.children)
            if a is not None and b is not None:
                return fn(a, b)
    if isinstance(expr, P.IsNotNull):
        a = leaf(expr.children[0])
        return None if a is None else a.is_valid()
    return None


class CpuParquetScanExec(LeafExec):
    def __init__(self, paths: Sequence[str],
                 columns: Optional[List[str]] = None,
                 predicate: Optional[Expression] = None,
                 batch_rows: int = 1 << 20):
        super().__init__()
        expanded = []
        for p in paths:
            if os.path.isdir(p):
                expanded.extend(sorted(
                    _glob.glob(os.path.join(p, "**", "*.parquet"),
                               recursive=True)))
            elif any(ch in p for ch in "*?["):
                expanded.extend(sorted(_glob.glob(p)))
            else:
                if not os.path.exists(p):
                    raise FileNotFoundError(f"parquet path does not exist: {p}")
                expanded.append(p)
        if not expanded:
            raise FileNotFoundError(f"no parquet files in {list(paths)}")
        self.paths = expanded
        self.columns = columns
        self.predicate = predicate
        self.batch_rows = batch_rows
        self._schema = None
        self._fragments = None

    # -- planning-time metadata (host footer stage) -------------------------
    @property
    def schema(self) -> T.StructType:
        if self._schema is None:
            import pyarrow.parquet as pq
            arrow_schema = pq.read_schema(self.paths[0])
            fields = []
            for f in arrow_schema:
                if self.columns is not None and f.name not in self.columns:
                    continue
                fields.append(T.StructField(f.name, T.from_arrow(f.type)))
            self._schema = T.StructType(fields)
        return self._schema

    def _plan_fragments(self):
        """One partition per file (row-group spans within a file stream as
        batches).  reference: FilePartition planning in GpuFileSourceScanExec."""
        if self._fragments is None:
            self._fragments = list(self.paths)
        return self._fragments

    @property
    def num_partitions(self):
        return len(self._plan_fragments())

    def execute_partition(self, pidx):
        import pyarrow as pa
        import pyarrow.parquet as pq
        path = self._plan_fragments()[pidx]
        f = pq.ParquetFile(path)
        flt = None if self.predicate is None else \
            _expr_to_arrow_filter(self.predicate)
        cols = self.columns
        if flt is not None:
            import pyarrow.dataset as ds
            dataset = ds.dataset(path, format="parquet")
            scanner = dataset.scanner(columns=cols, filter=flt,
                                      batch_size=self.batch_rows)
            for rb in scanner.to_batches():
                if rb.num_rows:
                    yield batch_from_arrow(pa.Table.from_batches([rb]))
            return
        for rb in f.iter_batches(batch_size=self.batch_rows, columns=cols):
            if rb.num_rows:
                yield batch_from_arrow(pa.Table.from_batches([rb]))

    def node_desc(self):
        base = os.path.basename(self.paths[0])
        extra = f"+{len(self.paths)-1}" if len(self.paths) > 1 else ""
        cols = "*" if self.columns is None else ",".join(self.columns)
        return f"ParquetScan[{base}{extra}]({cols})"


class TpuParquetScanExec(CpuParquetScanExec):
    """Device-feeding parquet scan: host decode -> semaphore -> upload
    (reference call stack SURVEY.md §3.2)."""

    is_device = True

    def __init__(self, cpu: CpuParquetScanExec):
        LeafExec.__init__(self)
        self.paths = cpu.paths
        self.columns = cpu.columns
        self.predicate = cpu.predicate
        self.batch_rows = cpu.batch_rows
        self._schema = cpu._schema
        self._fragments = cpu._fragments

    def execute_partition(self, pidx):
        from spark_rapids_tpu.exec.basic import upload_batches
        yield from upload_batches(super().execute_partition(pidx))

    def node_desc(self):
        return "Tpu" + super().node_desc()


# plan-rewrite registration (reference: ScanRule registry GpuOverrides.scala:3864)
from spark_rapids_tpu.plan.overrides import register_exec  # noqa: E402

register_exec(CpuParquetScanExec,
              convert=lambda p, m: TpuParquetScanExec(p),
              exprs_of=lambda p: [p.predicate] if p.predicate is not None else [],
              desc="parquet scan (host decode + device upload)")


def write_parquet(batches, path: str, schema: Optional[T.StructType] = None):
    """Writer (reference: GpuParquetFileFormat + ColumnarOutputWriter chunked
    TableWriter; host-side arrow writer here)."""
    import pyarrow as pa
    import pyarrow.parquet as pq
    from spark_rapids_tpu.columnar.batch import ColumnarBatch
    writer = None
    try:
        for b in batches:
            if isinstance(b, ColumnarBatch):
                b = b.to_host()
            rb = b.to_arrow()
            if writer is None:
                writer = pq.ParquetWriter(path, rb.schema)
            writer.write_batch(rb)
        if writer is None:
            if schema is None:
                raise ValueError("cannot write empty dataset without schema")
            empty = pa.table({f.name: pa.array([], type=T.to_arrow(f.data_type))
                              for f in schema})
            pq.write_table(empty, path)
    finally:
        if writer is not None:
            writer.close()
