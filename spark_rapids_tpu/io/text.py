"""Text-based formats: CSV and JSON(lines) scans + writers.

Reference: ``GpuCSVScan.scala`` (439 LoC) and
``catalyst/json/rapids/GpuJsonScan.scala`` (455 LoC), both built on
``GpuTextBasedPartitionReader.scala`` — line-based host read feeding the
cuDF CSV/JSON device parsers.  TPU-first: byte-level parsing is TPU-hostile,
so the parse is host-side (pyarrow csv/json readers are the parser stage);
decoded columns upload as padded device batches through the common
transition machinery.
"""

from __future__ import annotations

import io
import os
from typing import Dict, Iterator, List, Optional, Sequence

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.batch import HostColumnarBatch, batch_from_arrow
from spark_rapids_tpu.io.multifile import AUTO, MultiFileScanBase


def _cast_to_schema(table, schema: T.StructType):
    """Casts an inferred arrow table to the user schema (CSV schema
    enforcement; reference: GpuTextBasedPartitionReader castsToSchema)."""
    import pyarrow as pa
    import pyarrow.compute as pc
    cols = []
    for f in schema.fields:
        if f.name in table.column_names:
            arr = table.column(f.name)
            want = T.to_arrow(f.data_type)
            if arr.type != want:
                arr = arr.cast(want)
            cols.append(arr)
        else:
            cols.append(pa.nulls(len(table), type=T.to_arrow(f.data_type)))
    return pa.table(dict(zip([f.name for f in schema.fields], cols)))


class CpuCsvScanExec(MultiFileScanBase):
    """CSV scan (reference: GpuCSVScan.scala)."""

    format_name = "csv"
    file_ext = ".csv"

    def __init__(self, paths: Sequence[str],
                 user_schema: Optional[T.StructType] = None,
                 header: bool = True, sep: str = ",",
                 quote: str = '"', escape: str = "\\",
                 comment: str = "", null_value: str = "",
                 columns: Optional[List[str]] = None,
                 reader_type: str = AUTO, batch_rows: int = 1 << 20,
                 num_threads: int = 8):
        super().__init__(paths, reader_type=reader_type,
                         batch_rows=batch_rows, num_threads=num_threads)
        self.user_schema = user_schema
        self.header = header
        self.sep = sep
        self.quote = quote
        self.escape = escape
        self.comment = comment
        self.null_value = null_value
        self.columns = columns

    def _scan_cache_extra(self):
        return (self.user_schema.simple_name if self.user_schema else None,
                self.header, self.sep, self.quote, self.escape,
                self.comment, self.null_value)

    def _options(self):
        import pyarrow.csv as pcsv
        col_names = None
        if not self.header:
            if self.user_schema is not None:
                col_names = self.user_schema.names
            else:
                raise ValueError("headerless CSV requires an explicit schema")
        read = pcsv.ReadOptions(column_names=col_names,
                                block_size=1 << 24)
        parse = pcsv.ParseOptions(delimiter=self.sep, quote_char=self.quote,
                                  escape_char=self.escape or False)
        null_values = [self.null_value] if self.null_value else [""]
        conv_kw = dict(null_values=null_values, strings_can_be_null=True)
        if self.user_schema is not None:
            conv_kw["column_types"] = {
                f.name: T.to_arrow(f.data_type) for f in self.user_schema.fields
                if not isinstance(f.data_type,
                                  (T.TimestampType, T.DateType))}
        conv = pcsv.ConvertOptions(**conv_kw)
        return read, parse, conv

    def infer_schema(self) -> T.StructType:
        if self.user_schema is not None:
            sch = self.user_schema
        else:
            import pyarrow.csv as pcsv
            read, parse, conv = self._options()
            # infer from the first block only (streaming reader), not a full
            # file parse — planning-time schema access must stay cheap
            with pcsv.open_csv(self.paths[0], read_options=read,
                               parse_options=parse,
                               convert_options=conv) as rdr:
                arrow_schema = rdr.schema
            sch = T.StructType([T.StructField(f.name, T.from_arrow(f.type))
                                for f in arrow_schema])
        if self.columns is not None:
            sch = T.StructType([f for f in sch.fields
                                if f.name in self.columns])
        return sch

    @staticmethod
    def _strip_comments(data: bytes, comment: bytes, quote: bytes,
                        escape: bytes) -> bytes:
        """Drops comment lines, but never a physical line inside an open
        quoted field (multi-line values).  Quote parity counts only
        unescaped quotes: doubled quotes ("") contribute 2 (parity
        unchanged, RFC-4180), and escape-char-prefixed quotes are skipped."""
        q = quote[0] if quote else None
        e = escape[0] if escape and escape != quote else None
        out = []
        in_quote = False
        for ln in data.split(b"\n"):
            if not in_quote and ln.lstrip().startswith(comment):
                continue
            out.append(ln)
            cnt = 0
            skip = False
            for b in ln:
                if skip:
                    skip = False
                elif e is not None and b == e:
                    skip = True
                elif b == q:
                    cnt += 1
            if cnt % 2 == 1:
                in_quote = not in_quote
        return b"\n".join(out)

    def read_file(self, path: str) -> Iterator[HostColumnarBatch]:
        import pyarrow.csv as pcsv
        read, parse, conv = self._options()
        stripped = None
        if self.comment:
            # arrow csv has no comment support: pre-strip comment lines
            # (full in-memory read — the comment option trades streaming for
            # correctness; omit it for large files)
            with open(path, "rb") as f:
                data = self._strip_comments(
                    f.read(), self.comment.encode(), self.quote.encode(),
                    self.escape.encode() if self.escape else b"")
            stripped = io.BytesIO(data)
        with pcsv.open_csv(stripped or path, read_options=read,
                           parse_options=parse, convert_options=conv) as rdr:
            for rb in rdr:
                if rb.num_rows == 0:
                    continue
                import pyarrow as pa
                tbl = pa.Table.from_batches([rb])
                if self.user_schema is not None:
                    tbl = _cast_to_schema(tbl, self.user_schema)
                if self.columns is not None:
                    tbl = tbl.select([c for c in tbl.column_names
                                      if c in self.columns])
                yield batch_from_arrow(tbl)


class CpuJsonScanExec(MultiFileScanBase):
    """JSON-lines scan (reference: GpuJsonScan.scala)."""

    format_name = "json"
    file_ext = ".json"

    def __init__(self, paths: Sequence[str],
                 user_schema: Optional[T.StructType] = None,
                 columns: Optional[List[str]] = None,
                 reader_type: str = AUTO, batch_rows: int = 1 << 20,
                 num_threads: int = 8):
        super().__init__(paths, reader_type=reader_type,
                         batch_rows=batch_rows, num_threads=num_threads)
        self.user_schema = user_schema
        self.columns = columns

    def infer_schema(self) -> T.StructType:
        if self.user_schema is not None:
            sch = self.user_schema
        else:
            import io as _io
            import pyarrow.json as pjson
            # infer from the leading block only (cut at the last complete
            # line) — planning-time schema access must stay cheap
            with open(self.paths[0], "rb") as f:
                head = f.read(1 << 20)
                if len(head) == (1 << 20):
                    cut = head.rfind(b"\n")
                    if cut > 0:
                        head = head[:cut]
            if not head.strip():
                sch = T.StructType([])  # empty file: zero-column schema
            else:
                tbl = pjson.read_json(_io.BytesIO(head))
                sch = T.StructType([
                    T.StructField(f.name, T.from_arrow(f.type))
                    for f in tbl.schema])
        if self.columns is not None:
            sch = T.StructType([f for f in sch.fields
                                if f.name in self.columns])
        return sch

    def read_file(self, path: str) -> Iterator[HostColumnarBatch]:
        import os as _os
        import pyarrow.json as pjson
        if _os.path.getsize(path) == 0:
            return  # empty part file
        opts = None
        if self.user_schema is not None:
            import pyarrow as pa
            opts = pjson.ParseOptions(explicit_schema=pa.schema(
                [(f.name, T.to_arrow(f.data_type))
                 for f in self.user_schema.fields]),
                unexpected_field_behavior="ignore")
        tbl = pjson.read_json(path, parse_options=opts)
        if self.columns is not None:
            tbl = tbl.select([c for c in tbl.column_names
                              if c in self.columns])
        # chunk to batch_rows
        for off in range(0, max(tbl.num_rows, 1), self.batch_rows):
            chunk = tbl.slice(off, self.batch_rows)
            if chunk.num_rows:
                yield batch_from_arrow(chunk)


from spark_rapids_tpu.io.multifile import tpu_scan_of  # noqa: E402

TpuCsvScanExec, _csv_convert = tpu_scan_of(CpuCsvScanExec)
TpuJsonScanExec, _json_convert = tpu_scan_of(CpuJsonScanExec)

from spark_rapids_tpu.plan.overrides import register_exec  # noqa: E402

register_exec(CpuCsvScanExec, convert=_csv_convert,
              desc="CSV scan (host parse + device upload)")
register_exec(CpuJsonScanExec, convert=_json_convert,
              desc="JSON scan (host parse + device upload)")


# ---------------------------------------------------------------------------
# Writers
# ---------------------------------------------------------------------------

def write_csv(batches, path: str, schema: Optional[T.StructType] = None,
              header: bool = True, sep: str = ","):
    """CSV writer (reference: Spark CSV write falls back to CPU in the
    reference; here it is a first-class host writer)."""
    import pyarrow.csv as pcsv
    from spark_rapids_tpu.io.multifile import chunked_write
    opts = pcsv.WriteOptions(include_header=header, delimiter=sep)
    chunked_write(
        batches, path, schema,
        open_writer=lambda p, sch: pcsv.CSVWriter(p, sch, write_options=opts),
        write_batch=lambda w, rb: w.write(rb))


def write_json(batches, path: str, schema: Optional[T.StructType] = None):
    """JSON-lines writer."""
    import datetime
    import decimal
    import json
    import math
    from spark_rapids_tpu.columnar.batch import ColumnarBatch

    def enc(v):
        if isinstance(v, float) and (math.isnan(v) or math.isinf(v)):
            return str(v)
        if isinstance(v, decimal.Decimal):
            return str(v)
        if isinstance(v, (datetime.datetime, datetime.date)):
            return v.isoformat()
        if isinstance(v, bytes):
            return v.decode("utf-8", "replace")
        return v

    with open(path, "w") as f:
        for b in batches:
            if isinstance(b, ColumnarBatch):
                b = b.to_host()
            d = b.to_pydict()
            names = list(d.keys())
            for row in zip(*d.values()):
                obj = {k: enc(v) for k, v in zip(names, row) if v is not None}
                f.write(json.dumps(obj) + "\n")


class CpuTextScanExec(MultiFileScanBase):
    """Line-oriented text scan: each line is one row in a single ``value``
    string column (reference: GpuHiveTableScanExec's delimited-text path /
    Spark's text format)."""

    format_name = "text"
    file_ext = ".txt"

    def __init__(self, paths: Sequence[str], reader_type: str = AUTO,
                 batch_rows: int = 1 << 20, num_threads: int = 8, **_kw):
        super().__init__(paths, reader_type=reader_type,
                         batch_rows=batch_rows, num_threads=num_threads)

    def infer_schema(self) -> T.StructType:
        return T.StructType([T.StructField("value", T.STRING, False)])

    def read_file(self, path: str):
        import pyarrow as pa
        from spark_rapids_tpu.columnar.batch import batch_from_arrow
        # stream line by line; ONLY \n / \r\n terminate rows (Spark's
        # text format — str.splitlines would also split on \v, \f,
        # U+2028...), and the file is never slurped whole
        chunk = []
        with open(path, "r", encoding="utf-8", errors="replace",
                  newline="\n") as f:
            for line in f:
                if line.endswith("\n"):
                    line = line[:-1]
                if line.endswith("\r"):
                    line = line[:-1]
                chunk.append(line)
                if len(chunk) >= self.batch_rows:
                    yield batch_from_arrow(pa.table(
                        {"value": pa.array(chunk, type=pa.string())}))
                    chunk = []
        if chunk:
            yield batch_from_arrow(pa.table(
                {"value": pa.array(chunk, type=pa.string())}))


TpuTextScanExec, _text_convert = tpu_scan_of(CpuTextScanExec)
register_exec(CpuTextScanExec, convert=_text_convert,
              desc="line-oriented text scan")


def write_text(batches, path: str, schema: Optional[T.StructType] = None):
    """One line per row of the single string column."""

    class _W:
        def __init__(self, p):
            self.f = open(p, "w")

        def write(self, rb):
            if rb.num_columns != 1:
                raise ValueError("text format writes exactly one column")
            for v in rb.column(0).to_pylist():
                self.f.write(("" if v is None else str(v)) + "\n")

        def close(self):
            self.f.close()

    from spark_rapids_tpu.io.multifile import chunked_write
    chunked_write(batches, path, schema,
                  open_writer=lambda p, sch: _W(p),
                  write_batch=lambda w, rb: w.write(rb))
