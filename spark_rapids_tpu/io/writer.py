"""DataFrame writer: Spark-style directory output with part files.

Reference: the write path through ``GpuDataWritingCommandExec`` +
``ColumnarOutputWriter.scala`` — one output file per task/partition under the
target directory, a ``_SUCCESS`` marker on commit, and SaveMode semantics
(error/overwrite/append/ignore).
"""

from __future__ import annotations

import os
import shutil
import uuid
from typing import Optional

from spark_rapids_tpu import types as T

_FORMATS = {}


def _register(fmt):
    def deco(fn):
        _FORMATS[fmt] = fn
        return fn
    return deco


@_register("parquet")
def _write_parquet(batches, path, schema):
    from spark_rapids_tpu.io.parquet import write_parquet
    write_parquet(batches, path, schema)


@_register("csv")
def _write_csv(batches, path, schema, **opts):
    from spark_rapids_tpu.io.text import write_csv
    write_csv(batches, path, schema, **opts)


@_register("json")
def _write_json(batches, path, schema):
    from spark_rapids_tpu.io.text import write_json
    write_json(batches, path, schema)


@_register("avro")
def _write_avro(batches, path, schema, **opts):
    from spark_rapids_tpu.io.avro import write_avro
    write_avro(batches, path, schema, **opts)


@_register("text")
def _write_text(batches, path, schema, **opts):
    from spark_rapids_tpu.io.text import write_text
    write_text(batches, path, schema)


@_register("orc")
def _write_orc(batches, path, schema):
    from spark_rapids_tpu.io.orc import write_orc
    write_orc(batches, path, schema)


_EXT = {"parquet": ".parquet", "csv": ".csv", "json": ".json",
        "orc": ".orc", "avro": ".avro", "text": ".txt"}


class DataFrameWriter:
    """``df.write.mode("overwrite").parquet(path)`` — directory output."""

    def __init__(self, df):
        self._df = df
        self._mode = "error"
        self._options = {}

    def mode(self, m: str) -> "DataFrameWriter":
        m = m.lower()
        if m not in ("error", "errorifexists", "overwrite", "append",
                     "ignore"):
            raise ValueError(f"unknown save mode {m!r}")
        self._mode = "error" if m == "errorifexists" else m
        return self

    def option(self, key: str, value) -> "DataFrameWriter":
        self._options[key] = value
        return self

    # -- format entry points -------------------------------------------------
    def parquet(self, path: str):
        self._save(path, "parquet")

    def csv(self, path: str):
        self._save(path, "csv")

    def json(self, path: str):
        self._save(path, "json")

    def text(self, path: str):
        return self._save(path, "text")

    def avro(self, path: str):
        return self._save(path, "avro")

    def orc(self, path: str):
        self._save(path, "orc")

    # -- machinery ----------------------------------------------------------
    def _save(self, path: str, fmt: str):
        write_one = _FORMATS[fmt]
        exists = os.path.exists(path)
        if exists and self._mode == "error":
            raise FileExistsError(
                f"path {path} already exists (mode=error)")
        if exists and self._mode == "ignore":
            return
        if exists and self._mode == "overwrite":
            if os.path.isdir(path):
                shutil.rmtree(path)
            else:
                os.remove(path)
        os.makedirs(path, exist_ok=True)
        plan = self._df._executed_plan()
        schema = self._df.schema
        job_id = uuid.uuid4().hex[:8]
        from spark_rapids_tpu.plan.base import run_task
        kw = {}
        if fmt == "csv":
            kw = {k: v for k, v in self._options.items()
                  if k in ("header", "sep")}
        wrote = 0
        for pidx in range(plan.num_partitions):
            batches = list(run_task(plan, pidx))
            if not batches and plan.num_partitions > 1:
                continue  # empty partition: no part file (Spark behavior)
            part = os.path.join(
                path, f"part-{pidx:05d}-{job_id}{_EXT[fmt]}")
            write_one(iter(batches), part, schema, **kw)
            wrote += 1
        if wrote == 0:
            # all-empty dataset still gets one (empty) part file
            part = os.path.join(path, f"part-00000-{job_id}{_EXT[fmt]}")
            write_one(iter(()), part, schema, **kw)
        with open(os.path.join(path, "_SUCCESS"), "w"):
            pass
