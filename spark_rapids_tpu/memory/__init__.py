"""Memory & device runtime: the framework's hardest-won layer.

Reference counterparts (SURVEY.md §2.4):
- ``GpuDeviceManager.scala`` — device acquisition + RMM pool init  → ``device_manager``
- ``RapidsBufferCatalog.scala`` + stores — tiered buffer registry  → ``catalog``
- ``DeviceMemoryEventHandler.scala`` — spill-on-OOM callback       → ``pool`` event hook
- ``RmmRapidsRetryIterator.scala`` — retry/split-retry discipline  → ``retry``
- ``SpillableColumnarBatch.scala``                                 → ``spillable``
- ``GpuSemaphore.scala`` — device admission control                → ``semaphore``
- ``GpuTaskMetrics.scala``                                         → ``metrics``

TPU-first note: XLA/PJRT owns the physical HBM allocator, so the pool here is
an *accounting & admission* layer over tracked buffers (the same role RMM's
limiting/tracking adapters play): every catalog-registered device buffer
counts against a budget; exceeding it triggers synchronous spill of the
lowest-priority spillable buffers, then deterministic Retry/SplitAndRetry
signaling to the task that asked.
"""

from spark_rapids_tpu.memory.retry import (  # noqa: F401
    RetryOOM, SplitAndRetryOOM, task_context, with_retry, with_retry_no_split,
    force_retry_oom, force_split_and_retry_oom)
from spark_rapids_tpu.memory.catalog import (  # noqa: F401
    BufferCatalog, StorageTier, SpillPriority)
from spark_rapids_tpu.memory.spillable import SpillableColumnarBatch  # noqa: F401
from spark_rapids_tpu.memory.device_manager import (  # noqa: F401
    DeviceManager, initialize, shutdown, get_runtime)
from spark_rapids_tpu.memory.semaphore import TpuSemaphore  # noqa: F401
