"""Cooperative memory arbitration: the thread-state machine behind OOMs.

Reference: ``RmmSpark`` / ``SparkResourceAdaptor`` (spark-rapids-jni) — the
heart of the plugin's "retryable OOM handling" is not the retry frames but
the per-thread state machine behind them: a task that cannot allocate
*blocks* until concurrent tasks release memory, and only when every active
task is blocked (a true deadlock) is one victim woken with a forced OOM.
Sparkle's analysis of memory partitioning among concurrent Spark workers
(PAPERS.md) identifies exactly this cooperation as the limiter for
shared-memory scale-up.

Three cooperating pieces:

``ResourceArbiter``
    A process-wide registry of every active task thread's state
    (RUNNING, BLOCKED_ON_ALLOC, BLOCKED_ON_SEMAPHORE, BLOCKED_ON_SPOOL,
    BUFN).  ``BufferCatalog.reserve`` parks a short thread in
    BLOCKED_ON_ALLOC on the arbiter's condition variable — signalled by
    every catalog ``remove``/spill — instead of raising ``RetryOOM`` on
    first shortfall, so concurrent tasks cooperate instead of thrashing
    through rollbacks.

Deadlock detection + forced-split victim selection
    Run inline on every transition-to-blocked (plus the watchdog's
    low-frequency sweep): when every registered *device-holding* task is
    blocked and at least one waits on an allocation, the arbiter picks a
    victim by ``(spill priority, wake count, most recently started)`` and
    wakes it with a forced OOM.  The first wake of a task is a
    ``RetryOOM`` (spill-everything-and-retry may still succeed); a task
    that blocks again without progress is BUFN — "blocked until further
    notice" — and its next forced wake is a ``SplitAndRetryOOM`` (or
    ``RetryOOM`` again when the thread holds no splittable input).  The
    existing ``with_retry`` / ``with_retry_no_split`` frames in
    ``memory/retry.py`` absorb the thrown OOMs unchanged.

``HungQueryWatchdog``
    A conf-armed daemon (``spark.rapids.watchdog.{enabled,timeoutMs,
    pollMs}``) observing per-task last-progress timestamps (fed by
    task-runner heartbeats in ``plan/base.py``, spool progress in
    ``exec/pipeline.py`` and semaphore/alloc wait entries).  On expiry it
    dumps every thread state + holder stacks (``watchdogDump``), then
    escalates: first a forced arbitration round, then cancelling the
    wedged task with ``TaskCancelled`` — a ``TimeoutError`` the task
    runner classifies retryable, so the PR 3 task-retry/circuit-breaker
    machinery re-executes or degrades it.

Lock discipline: callers may hold the catalog lock, a semaphore condition
or a spool condition when calling in (their lock -> arbiter lock); the
arbiter NEVER calls back into the catalog, semaphore or spools, so the
ordering is one-directional and deadlock-free by construction.
"""

from __future__ import annotations

import enum
import sys
import threading
import time
import traceback
from typing import Dict, List, Optional, Tuple

from spark_rapids_tpu.memory.retry import (RetryOOM, SplitAndRetryOOM,
                                           task_context)

#: conf-driven (plan/overrides.apply): spark.rapids.memory.arbitration.*
ARBITRATION_ENABLED = True
#: cap on ONE alloc park before falling back to a plain RetryOOM (the
#: pre-arbiter behavior) — a liveness backstop for waits nothing can
#: break cooperatively (e.g. an unregistered thread pinning the pool)
MAX_BLOCK_MS = 10_000


class TaskState(enum.Enum):
    """reference: RmmSparkThreadState (spark-rapids-jni SparkResourceAdaptor)"""
    RUNNING = "running"
    BLOCKED_ON_ALLOC = "blocked_on_alloc"
    BLOCKED_ON_SEMAPHORE = "blocked_on_semaphore"
    BLOCKED_ON_SPOOL = "blocked_on_spool"
    #: serving-layer admission queue (QueryServer) — deliberately NOT a
    #: deadlock-relevant blocked state: a query waiting for admission is
    #: waiting on OTHER queries finishing, which needs no victim
    BLOCKED_ON_ADMISSION = "blocked_on_admission"
    BUFN = "bufn"


class TaskCancelled(TimeoutError):
    """The watchdog cancelled a wedged task.  A ``TimeoutError`` so the
    task runner's retryable classification (plan/base.py) re-executes or
    degrades it through the existing machinery."""

    def __init__(self, task_id, reason: str):
        super().__init__(f"task {task_id} cancelled: {reason}")
        self.task_id = task_id
        self.reason = reason


class InjectedBlockHold(Exception):
    """Chaos-only (``spark.rapids.chaos.memory.block``): simulates a
    never-releasing allocation hold.  ``BufferCatalog.reserve`` converts
    it into an arbitration-immune park that only watchdog cancellation
    (or a generous expiry backstop) can break."""


_BLOCKED_STATES = frozenset({TaskState.BLOCKED_ON_ALLOC,
                             TaskState.BLOCKED_ON_SEMAPHORE,
                             TaskState.BLOCKED_ON_SPOOL})


class _ThreadSlot:
    __slots__ = ("ident", "name", "state", "since", "nbytes",
                 "split_capable", "hold", "wake_exc", "break_info")

    def __init__(self, ident: int, name: str):
        self.ident = ident
        self.name = name
        self.state = TaskState.RUNNING
        self.since = time.monotonic()
        self.nbytes = 0
        self.split_capable = False
        #: True while parked in an injected memory.block hold: visible to
        #: dumps as blocked, invisible to victim selection (a hang is not
        #: a memory wait — arbitration cannot relieve it)
        self.hold = False
        self.wake_exc = None            # exception CLASS set by the waker
        self.break_info: Optional[dict] = None


class _TaskEntry:
    __slots__ = ("task_id", "seq", "threads", "holds_device",
                 "holds_memory", "spill_priority", "wake_count", "bufn",
                 "last_progress", "cancelled", "cancel_reason",
                 "cancel_reported")

    def __init__(self, task_id: int, seq: int):
        self.task_id = task_id
        self.seq = seq                  # registration order (victim ties)
        self.threads: Dict[int, _ThreadSlot] = {}
        self.holds_device = False
        #: registered catalog device buffers (sticky for the task's life:
        #: a task that held memory stays deadlock-relevant — conservative
        #: toward the MAX_BLOCK_MS fallback, never toward spurious wakes)
        self.holds_memory = False
        #: min priority of registered buffers; None until the task
        #: registers one (a positive-priority buffer must not compare
        #: against a phantom 0 that marks the task most-evictable)
        self.spill_priority: Optional[int] = None
        self.wake_count = 0             # forced wakes received
        self.bufn = False               # blocked-until-further-notice
        self.last_progress = time.monotonic()
        self.cancelled = False
        self.cancel_reason = ""
        self.cancel_reported = False    # counted/emitted once per episode


class ResourceArbiter:
    """The process-wide task thread-state registry + blocking-allocation
    rendezvous (reference: SparkResourceAdaptor's thread registry)."""

    def __init__(self):
        from spark_rapids_tpu.aux.lockorder import tracked_condition
        self._cond = tracked_condition("arbiter")
        self._tasks: Dict[int, _TaskEntry] = {}
        #: task ids currently BUFN, mirrored from the entries so the
        #: catalog's fast path can test membership WITHOUT the arbiter
        #: lock (mutated only under it; a stale read merely defers the
        #: clear to the next allocation)
        self._bufn_tasks: set = set()
        self._seq = 0
        #: bumped by every release-ish transition; alloc parkers wait for
        #: it to move and then re-try admission
        self._release_seq = 0
        # process-lifetime counters (render_prometheus / tests)
        self.blocked_on_alloc_total = 0
        self.deadlock_breaks = 0
        self.forced_splits = 0
        self.forced_retries = 0
        self.tasks_cancelled = 0
        self.watchdog_dumps = 0
        #: serving-layer view: query_id -> (state, reserved_bytes,
        #: since).  Rides the registry so ``stats()``/``dump()`` show
        #: admission-queued queries next to the task threads, but never
        #: participates in deadlock victim selection (its own dict, not
        #: ``_tasks``)
        self._serving: Dict[int, tuple] = {}

    # -- registration --------------------------------------------------------
    def register_task(self, task_id: Optional[int]) -> None:
        """Registers the calling thread as ``task_id``'s primary thread
        (task start in ``plan/base.run_task_iter``)."""
        if task_id is None:
            return
        t = threading.current_thread()
        with self._cond:
            self._seq += 1
            e = self._tasks.get(task_id)
            if e is None:
                e = self._tasks[task_id] = _TaskEntry(task_id, self._seq)
            e.threads[t.ident] = _ThreadSlot(t.ident, t.name)

    def deregister_task(self, task_id: Optional[int]) -> None:
        if task_id is None:
            return
        with self._cond:
            self._bufn_tasks.discard(task_id)
            if self._tasks.pop(task_id, None) is None:
                return
            # the task's buffers / permits free with it: blocked peers
            # wake, re-try admission, and — still short — RE-park, which
            # re-runs the deadlock check against the post-exit registry
            # (checking here instead would victimize a peer that the
            # departing task's releases are about to satisfy)
            self._release_seq += 1
            self._cond.notify_all()

    def adopt_thread(self, task_id: Optional[int]) -> bool:
        """Registers an EXTRA thread under an existing task (the pipeline
        prefetch producer adopting its consumer's identity)."""
        if task_id is None:
            return False
        t = threading.current_thread()
        with self._cond:
            e = self._tasks.get(task_id)
            if e is None:
                return False
            e.threads[t.ident] = _ThreadSlot(t.ident, t.name)
            return True

    def drop_thread(self, task_id: Optional[int]) -> None:
        if task_id is None:
            return
        ident = threading.get_ident()
        with self._cond:
            e = self._tasks.get(task_id)
            if e is None:
                return
            e.threads.pop(ident, None)
            # one fewer thread can change "all blocked": wake parkers so
            # their re-park re-evaluates against the new thread set
            self._release_seq += 1
            self._cond.notify_all()

    # -- cheap notes (hot paths guard on the empty registry) -----------------
    def note_progress(self, task_id: Optional[int] = None) -> None:
        """Heartbeat: the task moved data (a batch yielded, a spool item
        exchanged, an unspill).  Feeds the watchdog and clears BUFN."""
        if not self._tasks:
            return
        if task_id is None:
            task_id = task_context().task_id
        if task_id is None:
            return
        with self._cond:
            e = self._tasks.get(task_id)
            if e is not None:
                e.last_progress = time.monotonic()
                # progress disproves "wedged": a cancellation the task
                # outran must not kill it at its NEXT legitimate wait.
                # (BUFN is NOT cleared here — only a successful
                # allocation disproves "cannot allocate", else a retry's
                # own heartbeats would reset the forced-split escalation)
                e.cancelled = False
                e.cancel_reason = ""
                e.cancel_reported = False

    def is_bufn(self, task_id: Optional[int] = None) -> bool:
        """Lock-free BUFN probe for ``reserve``'s fast path: only a BUFN
        task's success needs the locked clear below."""
        if not self._bufn_tasks:
            return False
        if task_id is None:
            task_id = task_context().task_id
        return task_id in self._bufn_tasks

    def note_alloc_success(self, task_id: Optional[int]) -> None:
        """ANY successful reserve disproves "cannot allocate": the task
        is no longer blocked-until-further-notice."""
        with self._cond:
            self._bufn_tasks.discard(task_id)
            e = self._tasks.get(task_id)
            if e is not None:
                e.bufn = False
                e.last_progress = time.monotonic()
                e.cancelled = False
                e.cancel_reason = ""
                e.cancel_reported = False

    def note_device_held(self, task_id: Optional[int], held: bool) -> None:
        """Semaphore acquire/release keeps the registry's device-holder
        view current (the arbiter never queries the semaphore — lock
        ordering stays one-directional)."""
        if task_id is None or not self._tasks:
            return
        with self._cond:
            e = self._tasks.get(task_id)
            if e is None:
                return
            e.holds_device = held
            if not held:
                # released admission: a blocked peer may now win it
                self._release_seq += 1
                self._cond.notify_all()

    def note_buffer_priority(self, task_id: Optional[int],
                             priority: int) -> None:
        """Victim-selection input: the task's most-evictable registered
        buffer (lower spills first, and its owner loses arbitration
        first)."""
        if task_id is None or not self._tasks:
            return
        with self._cond:
            e = self._tasks.get(task_id)
            if e is not None:
                e.holds_memory = True
                if e.spill_priority is None or priority < e.spill_priority:
                    e.spill_priority = priority

    def notify_release(self) -> None:
        """Catalog hook: device bytes were freed (remove / spill) — every
        alloc parker re-tries admission."""
        if not self._tasks:
            return
        with self._cond:
            self._release_seq += 1
            self._cond.notify_all()

    def release_seq(self) -> int:
        """Sampled by ``BufferCatalog.reserve`` BEFORE its admission
        check and handed back to ``block_on_alloc``: a release landing
        between the failed check and the park moves the seq past the
        sample, so the parker retries immediately instead of waiting for
        a future release that may never come."""
        with self._cond:
            return self._release_seq

    # -- cancellation --------------------------------------------------------
    def cancel_task(self, task_id: int, reason: str) -> bool:
        """Watchdog escalation: every blocking primitive of the task
        raises ``TaskCancelled`` at its next wait check."""
        with self._cond:
            e = self._tasks.get(task_id)
            if e is None or e.cancelled:
                return False
            e.cancelled = True
            e.cancel_reason = reason
            self._cond.notify_all()
            return True

    def check_cancelled(self, task_id: Optional[int] = None) -> None:
        """Raises ``TaskCancelled`` (and emits ``taskCancelled``) when the
        watchdog cancelled the calling task.  Blocking wait loops
        (semaphore, spools) poll this between wait slices."""
        if not self._tasks:
            return
        if task_id is None:
            task_id = task_context().task_id
        if task_id is None:
            return
        with self._cond:
            e = self._tasks.get(task_id)
            if e is None or not e.cancelled:
                return
            reason = e.cancel_reason
        self._raise_cancelled(task_id, reason)

    def _raise_cancelled(self, task_id, reason: str):
        # every blocked thread of the task raises, but the cancellation
        # is ONE event: count/emit only the first reporter per episode
        first = False
        with self._cond:
            e = self._tasks.get(task_id)
            if e is not None and not e.cancel_reported:
                e.cancel_reported = True
                first = True
        if first:
            self.tasks_cancelled += 1
            from spark_rapids_tpu.aux.events import emit
            from spark_rapids_tpu.aux.faults import note_recovery
            note_recovery("tasks_cancelled")
            emit("taskCancelled", task_id=task_id, reason=reason[:160])
        raise TaskCancelled(task_id, reason)

    # -- blocked-state transitions -------------------------------------------
    def enter_blocked(self, state: TaskState) -> Optional[_ThreadSlot]:
        """Marks the calling thread blocked (semaphore/spool waits).  The
        transition runs the inline deadlock check: this thread going
        quiet may complete the all-blocked condition.  Returns the slot
        for ``exit_blocked`` (None when unregistered/disabled)."""
        if not ARBITRATION_ENABLED or not self._tasks:
            return None
        task_id = task_context().task_id
        if task_id is None:
            return None
        ident = threading.get_ident()
        with self._cond:
            e = self._tasks.get(task_id)
            slot = e.threads.get(ident) if e is not None else None
            if slot is None or slot.state is not TaskState.RUNNING:
                return None
            slot.state = state
            slot.since = time.monotonic()
            self._check_deadlock_locked()
            return slot

    def exit_blocked(self, slot: Optional[_ThreadSlot],
                     state: TaskState) -> None:
        if slot is None:
            return
        with self._cond:
            if slot.state is state:
                slot.state = TaskState.RUNNING

    def wait_cancellable(self, cond: threading.Condition, should_wait,
                         state: TaskState, slice_s: float = 0.05,
                         task_id: Optional[int] = None,
                         on_first_wait=None) -> Optional[float]:
        """THE blocking-primitive wait discipline, shared by the
        semaphore and the spool ends: slice-waits on ``cond`` (which the
        caller already holds) while ``should_wait()`` is true, tracked
        in the registry as ``state`` and polling watchdog cancellation
        between slices.  ``on_first_wait`` runs once, before the first
        wait slice.  Returns the monotonic time of the first wait (for
        the caller's stall accounting), or None when it never waited."""
        t0 = None
        slot = None
        try:
            while should_wait():
                if t0 is None:
                    t0 = time.monotonic()
                    # lock order: caller's cond -> arbiter lock; the
                    # arbiter never calls back into the caller
                    slot = self.enter_blocked(state)
                    if on_first_wait is not None:
                        on_first_wait()
                self.check_cancelled(task_id)
                cond.wait(slice_s)
        finally:
            self.exit_blocked(slot, state)
        return t0

    # -- the blocking allocation rendezvous ----------------------------------
    def can_block(self) -> bool:
        """True when the calling thread belongs to a registered task and
        arbitration is on — the gate ``BufferCatalog.reserve`` consults
        before parking instead of raising."""
        if not ARBITRATION_ENABLED or not self._tasks:
            return False
        task_id = task_context().task_id
        if task_id is None:
            return False
        with self._cond:
            e = self._tasks.get(task_id)
            return e is not None and threading.get_ident() in e.threads

    def block_on_alloc(self, nbytes: int,
                       seen_seq: Optional[int] = None) -> str:
        """Parks the calling thread in BLOCKED_ON_ALLOC until memory is
        released ("retry": the caller re-tries admission), the deadlock
        detector picks it as victim (raises the forced OOM), the watchdog
        cancels it (raises ``TaskCancelled``), or ``MAX_BLOCK_MS``
        expires ("timeout": the caller falls back to plain RetryOOM).

        ``seen_seq`` is the ``release_seq()`` sample the caller took
        before its failed admission check: a release in the gap bumps
        past it and the park degenerates to an immediate "retry"."""
        ctx = task_context()
        task_id = ctx.task_id
        ident = threading.get_ident()
        t0 = time.monotonic()
        deadline = t0 + max(1, MAX_BLOCK_MS) / 1000.0
        exc_cls = None
        break_info = None
        cancel_reason = None
        with self._cond:
            e = self._tasks.get(task_id)
            slot = e.threads.get(ident) if e is not None else None
            if slot is None:
                return "unregistered"
            slot.state = TaskState.BLOCKED_ON_ALLOC
            slot.since = t0
            slot.nbytes = int(nbytes)
            # only a top-level with_retry frame can absorb a split
            slot.split_capable = ctx.split_frames > 0
            self.blocked_on_alloc_total += 1
            if seen_seq is None:
                seen_seq = self._release_seq
            self._check_deadlock_locked()
            outcome = None
            while outcome is None:
                if slot.wake_exc is not None:
                    exc_cls, slot.wake_exc = slot.wake_exc, None
                    break_info, slot.break_info = slot.break_info, None
                    outcome = "forced"
                elif e.cancelled:
                    cancel_reason = e.cancel_reason
                    outcome = "cancelled"
                elif self._release_seq != seen_seq:
                    outcome = "retry"
                else:
                    now = time.monotonic()
                    if now >= deadline:
                        outcome = "timeout"
                    else:
                        self._cond.wait(min(0.25, deadline - now))
            slot.state = TaskState.RUNNING
            slot.nbytes = 0
        wait_s = time.monotonic() - t0
        if ctx.metrics is not None:
            ctx.metrics.alloc_wait_seconds += wait_s
        from spark_rapids_tpu.aux.events import emit
        emit("threadBlocked", task_id=task_id, nbytes=int(nbytes),
             wait_s=round(wait_s, 6), outcome=outcome)
        if outcome == "forced":
            from spark_rapids_tpu.aux.faults import note_recovery
            note_recovery("deadlock_breaks")
            emit("deadlockBreak", task_id=task_id,
                 exc=exc_cls.__name__, **(break_info or {}))
            raise exc_cls(
                f"forced {exc_cls.__name__} by arbitration: task {task_id} "
                f"lost the deadlock break (needed {nbytes} bytes)")
        if outcome == "cancelled":
            self._raise_cancelled(task_id, cancel_reason or "cancelled")
        return outcome

    def hold_until_cancelled(self) -> None:
        """The injected ``memory.block`` hang: parks arbitration-immune
        until the watchdog cancels the task.  A generous expiry backstop
        (10x MAX_BLOCK_MS) keeps watchdog-less runs from hanging a test
        process forever."""
        ctx = task_context()
        task_id = ctx.task_id
        ident = threading.get_ident()
        deadline = time.monotonic() + 10 * max(1, MAX_BLOCK_MS) / 1000.0
        reason = None
        with self._cond:
            e = self._tasks.get(task_id)
            slot = e.threads.get(ident) if e is not None else None
            if slot is not None:
                slot.state = TaskState.BLOCKED_ON_ALLOC
                slot.since = time.monotonic()
                slot.hold = True
            try:
                while True:
                    if e is not None and e.cancelled:
                        reason = e.cancel_reason
                        break
                    now = time.monotonic()
                    if now >= deadline:
                        reason = "injected memory.block hold expired " \
                                 "without watchdog cancellation"
                        if e is not None:
                            e.cancelled = True
                            e.cancel_reason = reason
                        break
                    self._cond.wait(min(0.05, deadline - now))
            finally:
                if slot is not None:
                    slot.state = TaskState.RUNNING
                    slot.hold = False
        self._raise_cancelled(task_id, reason or "cancelled")

    # -- deadlock detection + victim selection -------------------------------
    def _check_deadlock_locked(self, force: bool = False,
                               only_task: Optional[int] = None) -> bool:
        """All registered device-holding tasks blocked and somebody
        waiting on an allocation = a true deadlock: pick ONE victim and
        wake it with a forced OOM.  ``force=True`` (watchdog escalation)
        skips the all-blocked requirement and goes straight to the
        split-capable exception; ``only_task`` confines victim selection
        to the expired task so escalation never force-splits a healthy
        bystander."""
        candidates: List[Tuple[_TaskEntry, _ThreadSlot]] = []
        for e in self._tasks.values():
            if only_task is not None and e.task_id != only_task:
                continue
            slots = list(e.threads.values())
            if not slots:
                continue
            alloc = [s for s in slots
                     if s.state is TaskState.BLOCKED_ON_ALLOC
                     and not s.hold and s.wake_exc is None]
            relevant = e.holds_device or e.holds_memory or any(
                s.state is TaskState.BLOCKED_ON_ALLOC for s in slots)
            if not relevant:
                continue        # cannot free device memory either way
            if not force and any(s.state is TaskState.RUNNING
                                 or s.wake_exc is not None for s in slots):
                return False    # somebody can still release
            candidates.extend((e, s) for s in alloc)
        if not candidates:
            return False
        # buffer-less tasks sort last: they have nothing to spill, so
        # victimizing a task with real evictable buffers frees more
        entry, slot = min(
            candidates,
            key=lambda es: (es[0].spill_priority
                            if es[0].spill_priority is not None
                            else float("inf"),
                            es[0].wake_count, -es[0].seq))
        # first wake: RetryOOM (spill-everything-and-retry may suffice);
        # a BUFN task blocking again escalates to a forced split
        if (force or entry.bufn) and slot.split_capable:
            exc_cls = SplitAndRetryOOM
            self.forced_splits += 1
        else:
            exc_cls = RetryOOM
            self.forced_retries += 1
        entry.bufn = True
        self._bufn_tasks.add(entry.task_id)
        entry.wake_count += 1
        self.deadlock_breaks += 1
        slot.wake_exc = exc_cls
        slot.break_info = {
            "blocked_tasks": sum(
                1 for t in self._tasks.values()
                if t.threads and all(s.state is not TaskState.RUNNING
                                     for s in t.threads.values())),
            "forced": bool(force),
            "split_capable": slot.split_capable,
            "spill_priority": entry.spill_priority,
            "wake_count": entry.wake_count,
        }
        self._cond.notify_all()
        return True

    def force_arbitration(self, task_id: Optional[int] = None) -> bool:
        """Watchdog escalation step 1: break the wait NOW, all-blocked or
        not.  ``task_id`` confines the wake to the expired task — if the
        wedged task is alloc-parked, forcing IT to retry/split is the
        right escalation; waking a healthy bystander would defer the
        wedged task's recovery while costing the bystander its work.
        Returns True when a victim was woken."""
        with self._cond:
            return self._check_deadlock_locked(force=True,
                                               only_task=task_id)

    # -- introspection -------------------------------------------------------
    def task_held(self, task_id: int) -> bool:
        """True when the task sits in an injected ``memory.block`` hold —
        known unrecoverable, so the watchdog cancels it at the first
        detection instead of granting the post-dump grace."""
        with self._cond:
            e = self._tasks.get(task_id)
            return e is not None and any(s.hold
                                         for s in e.threads.values())

    def waiting_on_live_holder(self, task_id: int) -> bool:
        """True when the task's ONLY blockage is the device-admission
        queue while some other registered task holds the device and
        still has a runnable thread: queued behind a live worker, not
        wedged — the watchdog must leave it alone (cancelling it would
        fail a query that was merely waiting its turn)."""
        with self._cond:
            e = self._tasks.get(task_id)
            if e is None or not e.threads:
                return False
            if not all(s.state is TaskState.BLOCKED_ON_SEMAPHORE
                       for s in e.threads.values()):
                return False
            return any(o.task_id != task_id and o.holds_device
                       and any(s.state is TaskState.RUNNING
                               for s in o.threads.values())
                       for o in self._tasks.values())

    def global_progress_age(self) -> float:
        """Seconds since ANY registered task progressed — the watchdog's
        process-liveness test: while something is moving, an idle task
        may just be starved, and cancellation can wait."""
        with self._cond:
            if not self._tasks:
                return 0.0
            return time.monotonic() - max(e.last_progress
                                          for e in self._tasks.values())

    def expired_tasks(self, timeout_s: float) -> List[Tuple[int, float]]:
        """(task_id, idle_s) for tasks with no progress for timeout_s.
        Cancelled tasks stay listed: one that never reaches a
        cancellation checkpoint must keep its watchdog episode alive
        (periodic re-dumps) instead of going silent."""
        now = time.monotonic()
        out = []
        with self._cond:
            for e in self._tasks.values():
                idle = now - e.last_progress
                if idle >= timeout_s:
                    out.append((e.task_id, idle))
        return out

    # -- serving-layer view (QueryServer admission) --------------------------
    def note_serving(self, query_id: int, state: TaskState,
                     reserved_bytes: int = 0) -> None:
        """Registers/updates one served query's admission state (the
        QueryServer calls this around its admission waits)."""
        with self._cond:
            self._serving[query_id] = (state, int(reserved_bytes),
                                       time.monotonic())

    def drop_serving(self, query_id: int) -> None:
        with self._cond:
            self._serving.pop(query_id, None)

    def serving_view(self) -> Dict[int, dict]:
        with self._cond:
            now = time.monotonic()
            return {qid: {"state": st.value, "reserved_bytes": rb,
                          "age_s": now - since}
                    for qid, (st, rb, since) in self._serving.items()}

    def stats(self) -> dict:
        with self._cond:
            blocked = sum(
                1 for e in self._tasks.values()
                for s in e.threads.values() if s.state in _BLOCKED_STATES)
            serving_queued = sum(
                1 for st, _, _ in self._serving.values()
                if st is TaskState.BLOCKED_ON_ADMISSION)
            return {
                "serving_queries": len(self._serving),
                "serving_queued": serving_queued,
                "tasks": len(self._tasks),
                "threads": sum(len(e.threads)
                               for e in self._tasks.values()),
                "blocked_threads": blocked,
                "bufn_tasks": sum(1 for e in self._tasks.values()
                                  if e.bufn),
                "blocked_on_alloc_total": self.blocked_on_alloc_total,
                "deadlock_breaks": self.deadlock_breaks,
                "forced_splits": self.forced_splits,
                "forced_retries": self.forced_retries,
                "tasks_cancelled": self.tasks_cancelled,
                "watchdog_dumps": self.watchdog_dumps,
            }

    def dump(self) -> str:
        """Thread-state + stack dump for the watchdog (extends the
        semaphore's holder dump with every registered task thread's live
        stack via ``sys._current_frames``)."""
        frames = sys._current_frames()
        lines: List[str] = []
        now = time.monotonic()
        with self._cond:
            entries = [(e.task_id, e.holds_device, e.bufn, e.cancelled,
                        now - e.last_progress, list(e.threads.values()))
                       for e in self._tasks.values()]
        lines.append(f"== arbiter: {len(entries)} task(s) ==")
        for qid, info in sorted(self.serving_view().items()):
            lines.append(f"serving query {qid} state={info['state']} "
                         f"reserved={info['reserved_bytes']}B "
                         f"for {info['age_s']:.1f}s")
        for tid, held, bufn, cancelled, idle, slots in entries:
            flags = "".join(f for f, on in
                            (("D", held), ("B", bufn), ("C", cancelled))
                            if on)
            lines.append(f"task {tid} [{flags or '-'}] idle={idle:.1f}s")
            for s in slots:
                age = now - s.since
                lines.append(f"  thread {s.name} state={s.state.value} "
                             f"for {age:.1f}s"
                             + (f" waiting {s.nbytes}B" if s.nbytes else "")
                             + (" (injected hold)" if s.hold else ""))
                f = frames.get(s.ident)
                if f is not None:
                    for fl in traceback.format_stack(f)[-4:]:
                        lines.extend("    " + x
                                     for x in fl.rstrip().splitlines())
        from spark_rapids_tpu.memory.device_manager import get_runtime
        rt = get_runtime()
        if rt is not None:
            lines.append(rt.semaphore.dump_active_holders())
        return "\n".join(lines)

    def _reset_for_tests(self) -> None:
        with self._cond:
            self._tasks.clear()
            self._bufn_tasks.clear()
            self._serving.clear()
            self._cond.notify_all()


_ARBITER = ResourceArbiter()


def get_arbiter() -> ResourceArbiter:
    return _ARBITER


def note_progress_current() -> None:
    """Module-level heartbeat helper for hot paths (spillable unspills,
    spool handoffs): zero-cost when no task is registered."""
    if _ARBITER._tasks:
        _ARBITER.note_progress()


# ---------------------------------------------------------------------------
# hung-query watchdog (conf: spark.rapids.watchdog.*)
# ---------------------------------------------------------------------------

class HungQueryWatchdog:
    """Daemon sweeping the arbiter registry every ``poll_ms``: a task with
    no progress for ``timeout_ms`` gets (1) a full thread-state + holder
    stack dump (``watchdogDump``), (2) a forced arbitration round, and —
    when arbitration had nothing to wake, or the task is still wedged a
    full timeout after the dump — (3) cancellation through
    ``TaskCancelled`` so the task-retry machinery re-executes it."""

    def __init__(self, timeout_ms: int, poll_ms: int):
        self.timeout_ms = int(timeout_ms)
        self.poll_ms = int(poll_ms)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        #: task_id -> monotonic time of its dump (one per episode)
        self._dumped: Dict[int, float] = {}
        self.sweeps = 0
        self.sweep_faults = 0

    @property
    def running(self) -> bool:
        t = self._thread
        return t is not None and t.is_alive()

    def start(self) -> None:
        if self.running:
            return
        self._stop.clear()
        t = threading.Thread(target=self._run, name="tpu-watchdog",
                             daemon=True)
        self._thread = t
        t.start()

    def _run(self) -> None:
        poll_s = max(0.001, self.poll_ms / 1000.0)
        while not self._stop.wait(poll_s):
            try:
                self.sweep()
            except Exception:   # noqa: BLE001 - the daemon must survive
                self.sweep_faults += 1

    def sweep(self) -> None:
        """One detection pass (directly callable in tests)."""
        self.sweeps += 1
        from spark_rapids_tpu.aux.faults import maybe_fire
        try:
            maybe_fire("watchdog.sweep")
        except Exception:   # noqa: BLE001 - injected sweep fault: the
            self.sweep_faults += 1      # daemon skips one pass, survives
            return
        arb = get_arbiter()
        timeout_s = self.timeout_ms / 1000.0
        now = time.monotonic()
        expired = arb.expired_tasks(timeout_s)
        live = {tid for tid, _ in expired}
        for tid in list(self._dumped):
            if tid not in live:
                del self._dumped[tid]   # progressed or finished: episode over
        # while ANY registered task is progressing, an idle one may just
        # be starved: cancellation (never the dump) waits for the stall
        stalled = arb.global_progress_age() >= timeout_s
        for tid, idle in expired:
            if arb.waiting_on_live_holder(tid):
                continue    # queued behind a live worker: not wedged
            dumped_at = self._dumped.get(tid)
            if dumped_at is None:
                self._dumped[tid] = now
                arb.watchdog_dumps += 1
                from spark_rapids_tpu.aux.events import emit
                from spark_rapids_tpu.aux.faults import note_recovery
                note_recovery("watchdog_dumps")
                emit("watchdogDump", task_id=tid, idle_s=round(idle, 3),
                     timeout_ms=self.timeout_ms, dump=arb.dump()[:8000])
                if not arb.force_arbitration(tid) and arb.task_held(tid):
                    # an injected memory.block hold is KNOWN
                    # unrecoverable: skip the grace rung.  Every other
                    # task — even fully blocked — gets a full timeout of
                    # post-dump grace first (one rung per detection)
                    arb.cancel_task(
                        tid, f"watchdog: no progress for {idle:.1f}s "
                             f"(timeout {self.timeout_ms}ms)")
            else:
                if stalled and now - dumped_at >= timeout_s:
                    # dumped + arbitrated a full timeout ago, still no
                    # progress anywhere (cancel_task latches: re-firing
                    # on an already-cancelled task is a no-op)
                    arb.cancel_task(
                        tid, f"watchdog: still wedged {idle:.1f}s "
                             f"after dump")
                if now - dumped_at >= 10 * timeout_s:
                    # a cancelled task that never reaches a cancellation
                    # checkpoint must not go silent: re-dump on a slow
                    # cadence so the operator keeps seeing the hang
                    self._dumped[tid] = now
                    arb.watchdog_dumps += 1
                    from spark_rapids_tpu.aux.events import emit
                    from spark_rapids_tpu.aux.faults import note_recovery
                    note_recovery("watchdog_dumps")
                    emit("watchdogDump", task_id=tid,
                         idle_s=round(idle, 3),
                         timeout_ms=self.timeout_ms,
                         dump=arb.dump()[:8000])

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None and t is not threading.current_thread():
            t.join(timeout=5.0)
        self._thread = None


_WD_LOCK = threading.Lock()
_WATCHDOG: Optional[HungQueryWatchdog] = None


def active_watchdog() -> Optional[HungQueryWatchdog]:
    with _WD_LOCK:
        return _WATCHDOG


def stop_watchdog() -> None:
    global _WATCHDOG
    with _WD_LOCK:
        cur, _WATCHDOG = _WATCHDOG, None
    if cur is not None:
        cur.stop()


def sync_watchdog_from_conf(conf) -> Optional[HungQueryWatchdog]:
    """Reconciles the process-singleton watchdog with
    ``spark.rapids.watchdog.*`` (same lifecycle pattern as the resource
    sampler): enabling starts it, disabling stops it, changed knobs
    restart it.  Idempotent — called from session init and set_conf."""
    global _WATCHDOG
    from spark_rapids_tpu import config as C
    enabled = conf.get(C.WATCHDOG_ENABLED.key, False)
    timeout_ms = conf.get(C.WATCHDOG_TIMEOUT_MS.key, 60_000)
    poll_ms = conf.get(C.WATCHDOG_POLL_MS.key, 100)
    stale = None
    with _WD_LOCK:
        cur = _WATCHDOG
        if not enabled:
            _WATCHDOG, stale = None, cur
        elif cur is not None and cur.running and \
                cur.timeout_ms == timeout_ms and cur.poll_ms == poll_ms:
            return cur
        else:
            stale = cur
            _WATCHDOG = HungQueryWatchdog(timeout_ms, poll_ms)
            _WATCHDOG.start()
        out = _WATCHDOG
    if stale is not None:
        stale.stop()
    return out
