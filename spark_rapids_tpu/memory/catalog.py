"""Tiered buffer catalog: DEVICE(HBM) -> HOST(DRAM) -> DISK with spill.

Reference: ``RapidsBufferCatalog.scala`` (:62 class, :737 object; handle API
:47,126,215), ``RapidsBufferStore.scala`` (:58 spill logic),
``RapidsDeviceMemoryStore.scala`` / ``RapidsHostMemoryStore.scala`` /
``RapidsDiskStore.scala``, ``SpillPriorities.scala`` (:26), and
``DeviceMemoryEventHandler.scala`` (:36-193 spill-on-alloc-failure).

TPU-first: XLA owns physical HBM, so the device store is an accounting layer
over catalog-tracked jax buffers.  ``reserve()`` is the admission point every
operator calls before materializing a large result; on budget exhaustion it
synchronously spills lowest-priority buffers (the reference's event handler
does this inside the RMM callback).  If spilling wasn't enough, a registered
task thread PARKS in the resource arbiter (``memory/arbiter.py`` —
BLOCKED_ON_ALLOC on a condition variable signalled by every ``remove``/
spill) instead of raising ``RetryOOM`` immediately: concurrent tasks
cooperate, and only a detected deadlock (or the MAX_BLOCK_MS backstop)
surfaces a forced Retry/SplitAndRetry OOM toward the task's retry frame.
Unregistered threads (direct-catalog tests, driver code) keep the original
raise-immediately behavior.
"""

from __future__ import annotations

import enum
import itertools
import os
import tempfile
import time
from typing import Dict, List, Optional

from spark_rapids_tpu.columnar.batch import ColumnarBatch, HostColumnarBatch
from spark_rapids_tpu.memory import arbiter as _ARB
from spark_rapids_tpu.memory.retry import RetryOOM, maybe_inject_oom, task_context


#: codec for host->disk spill files (spark.rapids.memory.spill.codec,
#: synced by TpuOverrides.apply): the shuffle serializer's frame format,
#: so the spill tier rides the same lz4/zlib path shuffle payloads do
#: (reference: nvcomp-compressed spill in RapidsDiskStore)
SPILL_CODEC = "lz4"


class StorageTier(enum.IntEnum):
    """reference: RapidsBuffer.scala:59-64 StorageTier"""
    DEVICE = 0
    HOST = 1
    DISK = 2


class SpillPriority:
    """Lower value spills first (reference: SpillPriorities.scala:26)."""
    INPUT_FROM_SHUFFLE = -100
    ACTIVE_BATCHING = 0
    ACTIVE_ON_DECK = 100
    HOST_MEMORY = -50


_handle_ids = itertools.count(1)


class BufferHandle:
    """Opaque handle to a catalog buffer (reference: RapidsBufferHandle)."""

    __slots__ = ("id", "priority", "closed")

    def __init__(self, priority: int):
        self.id = next(_handle_ids)
        self.priority = priority
        self.closed = False

    def __repr__(self):
        return f"BufferHandle(id={self.id}, prio={self.priority})"


class _Buffer:
    __slots__ = ("handle", "tier", "device_batch", "host_batch", "disk_path",
                 "device_nbytes", "host_nbytes", "disk_nbytes",
                 "disk_logical_nbytes", "spillable", "owned",
                 "query_id", "span_id")

    def __init__(self, handle: BufferHandle):
        self.handle = handle
        #: attribution tags stamped at registration from the emitting
        #: thread's query/span context (aux/events.py); -1 outside any
        #: query.  The console /memory endpoint aggregates bytes by
        #: these through ``attribution()``.
        self.query_id = -1
        self.span_id = -1
        self.tier = StorageTier.DEVICE
        self.device_batch: Optional[ColumnarBatch] = None
        self.host_batch: Optional[HostColumnarBatch] = None
        self.disk_path: Optional[str] = None
        self.device_nbytes = 0
        self.host_nbytes = 0
        #: actual on-disk (post-codec) size — the accounting the pool
        #: watermarks and spill events report; re-statting the file
        #: raced with unlink and silently leaked disk_bytes on loss
        self.disk_nbytes = 0
        self.disk_logical_nbytes = 0
        self.spillable = True
        #: True = the catalog exclusively owns the device arrays and may
        #: .delete() them on spill/remove.  False = the arrays may be
        #: shared with other holders (scan device caches, exchange
        #: stores, a consumer using the batch right now): spill/remove
        #: only DROP the catalog's reference — HBM frees when the last
        #: Python reference does.  In-flight pipeline prefetch registers
        #: this way (exec/pipeline.py).
        self.owned = True


def _attribution_tags() -> tuple:
    """(query_id, span_id) of the registering thread's context, -1/-1
    outside any query.  Contextvar + thread-local reads only — no lock,
    negligible cost on the registration path."""
    from spark_rapids_tpu.aux import events as EV
    q = EV.active_query()
    if q is None:
        return -1, -1
    sid = EV.current_span_id()
    return q.query_id, (sid if sid is not None else -1)


def _delete_device_batch(batch: ColumnarBatch) -> None:
    """Releases device buffers eagerly (reference: RapidsBuffer.free /
    cudf close; jax arrays support explicit .delete())."""
    for col in batch.columns:
        # run_ends: RleColumn's extra plane; DICTIONARY value planes are
        # shared process-wide and must never be deleted with a batch
        for arr in (col.data, col.validity, col.lengths,
                    getattr(col, "run_ends", None)):
            if arr is not None and hasattr(arr, "delete"):
                try:
                    arr.delete()
                except Exception:
                    pass  # already donated/deleted


class BufferCatalog:
    """Central registry of spillable buffers across storage tiers."""

    def __init__(self, device_limit_bytes: int, host_limit_bytes: int,
                 disk_dir: Optional[str] = None, debug: bool = False):
        self.device_limit = device_limit_bytes
        self.host_limit = host_limit_bytes
        self._disk_dir = disk_dir
        self._buffers: Dict[int, _Buffer] = {}
        from spark_rapids_tpu.aux.lockorder import tracked_rlock
        self._lock = tracked_rlock("catalog")
        self.device_bytes = 0
        #: high-watermark of device_bytes (resource sampler / Prometheus)
        self.device_peak_bytes = 0
        self.host_bytes = 0
        self.disk_bytes = 0
        #: pre-codec bytes behind disk_bytes (compression ratio =
        #: disk_logical_bytes / disk_bytes)
        self.disk_logical_bytes = 0
        self.spill_count = 0
        self.debug = debug

    # -- admission ----------------------------------------------------------
    def reserve(self, nbytes: int) -> None:
        """Admission check before materializing ``nbytes`` on device.

        Mirrors DeviceMemoryEventHandler: on shortfall, synchronously spill
        spillable device buffers.  Still short, a registered task thread
        blocks in the arbiter until concurrent tasks release memory (a
        detected deadlock wakes one victim with a forced OOM); an
        unregistered thread — or an expired MAX_BLOCK_MS wait — signals
        RetryOOM so the calling retry frame can spill/split as before.
        """
        maybe_inject_oom()
        from spark_rapids_tpu.aux.faults import maybe_fire
        try:
            # chaos point memory.block: an injected never-releasing
            # allocation hold (only watchdog cancellation breaks it)
            maybe_fire("memory.block")
        except _ARB.InjectedBlockHold:
            _ARB.get_arbiter().hold_until_cancelled()
        blocked = False
        arb = _ARB.get_arbiter()
        while True:
            with self._lock:
                if self.device_bytes + nbytes <= self.device_limit:
                    if blocked or arb.is_bufn():
                        break       # cooperation worked: note outside lock
                    return
                needed = self.device_bytes + nbytes - self.device_limit
                freed = self._spill_device_locked(needed)
                if self.device_bytes + nbytes <= self.device_limit:
                    if blocked or arb.is_bufn():
                        break
                    return
                used = self.device_bytes
                # sampled under the catalog lock AFTER the failed
                # re-check: every byte-freeing release serializes behind
                # this lock, so a release the park could miss must bump
                # the seq past this sample and block_on_alloc retries
                # immediately — while our OWN spill above is already
                # reflected, so it cannot self-invalidate the park.
                # (lock order catalog -> arbiter, one-directional.)
                seq0 = arb.release_seq()
            outcome = arb.block_on_alloc(nbytes, seen_seq=seq0) \
                if arb.can_block() else "unregistered"
            if outcome == "retry":
                blocked = True
                continue    # released bytes: re-try admission (re-spill)
            # unregistered thread / MAX_BLOCK_MS expired: the pre-arbiter
            # behavior — signal the retry frame (forced OOMs and
            # cancellation raise out of block_on_alloc directly)
            mt = task_context().metrics
            if mt is not None:
                mt.oom_count += 1
            from spark_rapids_tpu.aux.events import emit
            emit("oom", needed=nbytes, used=used,
                 limit=self.device_limit, freed=freed)
            raise RetryOOM(
                f"device pool exhausted: need {nbytes}, used {used}"
                f"/{self.device_limit}, freed only {freed}")
        arb.note_alloc_success(task_context().task_id)

    # -- registration -------------------------------------------------------
    def add_device_batch(self, batch: ColumnarBatch,
                         priority: int = SpillPriority.ACTIVE_BATCHING,
                         spillable: bool = True,
                         owned: bool = True) -> BufferHandle:
        nbytes = batch.nbytes()
        self.reserve(nbytes)
        qid, sid = _attribution_tags()
        with self._lock:
            handle = BufferHandle(priority)
            buf = _Buffer(handle)
            buf.query_id, buf.span_id = qid, sid
            buf.device_batch = batch
            buf.device_nbytes = nbytes
            buf.spillable = spillable
            buf.owned = owned
            buf.tier = StorageTier.DEVICE
            self._buffers[handle.id] = buf
            self.device_bytes += nbytes
            self.device_peak_bytes = max(self.device_peak_bytes,
                                         self.device_bytes)
        # victim-selection input: the owning task's most-evictable buffer
        _ARB.get_arbiter().note_buffer_priority(task_context().task_id,
                                                priority)
        return handle

    def add_host_batch(self, batch: HostColumnarBatch,
                       priority: int = SpillPriority.HOST_MEMORY) -> BufferHandle:
        qid, sid = _attribution_tags()
        with self._lock:
            handle = BufferHandle(priority)
            buf = _Buffer(handle)
            buf.query_id, buf.span_id = qid, sid
            buf.host_batch = batch
            buf.host_nbytes = batch.nbytes()
            buf.tier = StorageTier.HOST
            self._buffers[handle.id] = buf
            self.host_bytes += buf.host_nbytes
            self._maybe_spill_host_locked()
            return handle

    # -- retrieval (unspill on demand) --------------------------------------
    def get_device_batch(self, handle: BufferHandle) -> ColumnarBatch:
        with self._lock:
            buf = self._require(handle)
            if buf.tier == StorageTier.DEVICE:
                return buf.device_batch
            host = self._host_batch_locked(buf)
        # admission BEFORE materializing on device (the estimate is exact for
        # fixed-width data and a safe upper bound for strings: pow2 bucket
        # padding is < 2x the host payload + validity/length vectors)
        est = 2 * host.nbytes() + 16 * max(host.row_count, 1024)
        self.reserve(est)
        t0 = time.monotonic()
        dev = host.to_device()
        unspill_s = time.monotonic() - t0
        nbytes = dev.nbytes()
        promoted = False
        with self._lock:
            buf = self._buffers.get(handle.id)
            if buf is None:  # removed concurrently
                _delete_device_batch(dev)
                raise KeyError(f"unknown or closed buffer handle {handle}")
            if buf.tier != StorageTier.DEVICE:
                buf.device_batch = dev
                buf.device_nbytes = nbytes
                self.device_bytes += nbytes
                self.device_peak_bytes = max(self.device_peak_bytes,
                                             self.device_bytes)
                # single-tier ownership: promotion drops the host copy and its
                # charge (prevents double-count on the next spill cycle)
                if buf.host_batch is not None:
                    self.host_bytes -= buf.host_nbytes
                    buf.host_batch = None
                    buf.host_nbytes = 0
                buf.tier = StorageTier.DEVICE
                promoted = True
            else:
                _delete_device_batch(dev)  # raced with another unspiller
            out = buf.device_batch
        if promoted:
            # exactly one event per actual promotion (race losers skip);
            # emitted outside the lock
            from spark_rapids_tpu.aux.events import emit
            emit("unspill", bytes=nbytes, rows=host.row_count,
                 buffer_id=handle.id, duration_s=round(unspill_s, 6))
        return out

    def get_host_batch(self, handle: BufferHandle) -> HostColumnarBatch:
        with self._lock:
            buf = self._require(handle)
            if buf.tier == StorageTier.DEVICE:
                return buf.device_batch.to_host()
            return self._host_batch_locked(buf)

    def tier_of(self, handle: BufferHandle) -> StorageTier:
        with self._lock:
            return self._require(handle).tier

    def set_spillable(self, handle: BufferHandle, spillable: bool) -> None:
        with self._lock:
            self._require(handle).spillable = spillable

    def disown(self, handle: BufferHandle) -> None:
        """Transfers device-array ownership back to the caller: any later
        spill/remove of this buffer drops the catalog's reference instead
        of deleting the arrays (SpillableColumnarBatch.release unwrap)."""
        with self._lock:
            buf = self._buffers.get(handle.id)
            if buf is not None:
                buf.owned = False

    def remove(self, handle: BufferHandle) -> None:
        freed_device = False
        with self._lock:
            buf = self._buffers.pop(handle.id, None)
            handle.closed = True
            if buf is None:
                return
            if buf.device_batch is not None:
                self.device_bytes -= buf.device_nbytes
                freed_device = buf.device_nbytes > 0
                if buf.owned:
                    _delete_device_batch(buf.device_batch)
            if buf.host_batch is not None:
                self.host_bytes -= buf.host_nbytes
            if buf.disk_path is not None:
                # recorded size, not a re-stat: the decrement must happen
                # even when the file is already gone
                self.disk_bytes -= buf.disk_nbytes
                self.disk_logical_bytes -= buf.disk_logical_nbytes
                try:
                    os.unlink(buf.disk_path)
                except OSError:
                    pass
        if freed_device:
            # wake BLOCKED_ON_ALLOC parkers: admission may now fit
            _ARB.get_arbiter().notify_release()

    # -- spilling -----------------------------------------------------------
    def synchronous_spill(self, target_free_bytes: Optional[int]) -> int:
        """Spills device buffers until ``target_free_bytes`` are free (None =
        spill everything spillable).  Returns bytes freed."""
        with self._lock:
            if target_free_bytes is None:
                needed = self.device_bytes
            else:
                free = self.device_limit - self.device_bytes
                needed = max(0, target_free_bytes - free)
            return self._spill_device_locked(needed)

    def _spill_device_locked(self, needed: int) -> int:
        candidates = sorted(
            (b for b in self._buffers.values()
             if b.tier == StorageTier.DEVICE and b.spillable),
            key=lambda b: b.handle.priority)
        freed = 0
        mt = task_context().metrics
        for buf in candidates:
            if freed >= needed:
                break
            t0 = time.monotonic()
            host = buf.device_batch.to_host()
            spill_s = time.monotonic() - t0
            if buf.owned:
                _delete_device_batch(buf.device_batch)
            self.device_bytes -= buf.device_nbytes
            freed += buf.device_nbytes
            buf.device_batch = None
            buf.device_nbytes = 0
            buf.host_batch = host
            buf.host_nbytes = host.nbytes()
            self.host_bytes += buf.host_nbytes
            buf.tier = StorageTier.HOST
            self.spill_count += 1
            if mt is not None:
                mt.spill_count += 1
                mt.spill_bytes += buf.host_nbytes
            from spark_rapids_tpu.aux.events import emit
            emit("spill", tier="device->host", bytes=buf.host_nbytes,
                 buffer_id=buf.handle.id, priority=buf.handle.priority,
                 duration_s=round(spill_s, 6))
        self._maybe_spill_host_locked()
        if freed > 0:
            # device bytes moved down a tier: alloc parkers re-try
            _ARB.get_arbiter().notify_release()
        return freed

    def _maybe_spill_host_locked(self) -> None:
        if self.host_bytes <= self.host_limit:
            return
        candidates = sorted(
            (b for b in self._buffers.values()
             if b.tier == StorageTier.HOST and b.spillable),
            key=lambda b: b.handle.priority)
        for buf in candidates:
            if self.host_bytes <= self.host_limit:
                break
            self._spill_host_to_disk_locked(buf)

    def _spill_host_to_disk_locked(self, buf: _Buffer) -> None:
        from spark_rapids_tpu.shuffle.serializer import serialize_batch
        d = self._disk_dir or tempfile.gettempdir()
        os.makedirs(d, exist_ok=True)
        path = os.path.join(d, f"spill-{buf.handle.id}.spill")
        t0 = time.monotonic()
        logical = buf.host_nbytes
        # the shuffle wire format (arrow IPC stream + codec frame): the
        # spill tier compresses through the same lz4/zlib path shuffle
        # payloads use, multiplying effective disk spill capacity
        frame = serialize_batch(buf.host_batch, SPILL_CODEC)
        with open(path, "wb") as fh:
            fh.write(frame)
        self.host_bytes -= buf.host_nbytes
        buf.host_batch = None
        buf.host_nbytes = 0
        buf.disk_path = path
        buf.disk_nbytes = len(frame)
        buf.disk_logical_nbytes = logical
        self.disk_bytes += buf.disk_nbytes
        self.disk_logical_bytes += logical
        buf.tier = StorageTier.DISK
        self.spill_count += 1
        from spark_rapids_tpu.aux.events import emit
        # bytes = ACTUAL on-disk (compressed) size, so profile spill
        # durations and the AutoTuner pressure rule see real I/O volume
        emit("spill", tier="host->disk", bytes=buf.disk_nbytes,
             logical_bytes=logical, codec=SPILL_CODEC,
             buffer_id=buf.handle.id, priority=buf.handle.priority,
             duration_s=round(time.monotonic() - t0, 6))

    def _host_batch_locked(self, buf: _Buffer) -> HostColumnarBatch:
        if buf.host_batch is not None:
            return buf.host_batch
        assert buf.disk_path is not None, "buffer has no backing storage"
        from spark_rapids_tpu.shuffle.serializer import deserialize_batch
        with open(buf.disk_path, "rb") as fh:
            host = deserialize_batch(fh.read())
        # promote back to host tier
        buf.host_batch = host
        buf.host_nbytes = host.nbytes()
        self.host_bytes += buf.host_nbytes
        self.disk_bytes -= buf.disk_nbytes
        self.disk_logical_bytes -= buf.disk_logical_nbytes
        buf.disk_nbytes = 0
        buf.disk_logical_nbytes = 0
        try:
            os.unlink(buf.disk_path)
        except OSError:
            pass
        buf.disk_path = None
        buf.tier = StorageTier.HOST
        return host

    def _require(self, handle: BufferHandle) -> _Buffer:
        buf = self._buffers.get(handle.id)
        if buf is None:
            raise KeyError(f"unknown or closed buffer handle {handle}")
        return buf

    # -- introspection ------------------------------------------------------
    def stats(self) -> dict:
        with self._lock:
            spillable = sum(b.device_nbytes for b in self._buffers.values()
                            if b.tier == StorageTier.DEVICE and b.spillable)
            return {
                "device_bytes": self.device_bytes,
                "device_limit": self.device_limit,
                "device_peak_bytes": self.device_peak_bytes,
                "spillable_bytes": spillable,
                "host_bytes": self.host_bytes,
                "host_limit": self.host_limit,
                "disk_bytes": self.disk_bytes,
                "disk_logical_bytes": self.disk_logical_bytes,
                "buffers": len(self._buffers),
                "spill_count": self.spill_count,
            }

    def attribution(self) -> List[dict]:
        """Per-(query, operator-span) byte attribution of live buffers,
        aggregated from the registration tags (console /memory).  One
        row per (query_id, span_id) with per-tier byte sums; query_id
        -1 collects buffers registered outside any query (caches,
        exchange stores).  Snapshot under the catalog lock only."""
        with self._lock:
            agg: Dict[tuple, dict] = {}
            for b in self._buffers.values():
                row = agg.setdefault((b.query_id, b.span_id), {
                    "query_id": b.query_id, "span_id": b.span_id,
                    "buffers": 0, "device_bytes": 0, "host_bytes": 0,
                    "disk_bytes": 0, "spillable_bytes": 0,
                })
                row["buffers"] += 1
                row["device_bytes"] += b.device_nbytes
                row["host_bytes"] += b.host_nbytes
                row["disk_bytes"] += b.disk_nbytes
                if b.tier == StorageTier.DEVICE and b.spillable:
                    row["spillable_bytes"] += b.device_nbytes
            return [agg[k] for k in sorted(agg)]

    def close(self) -> None:
        with self._lock:
            for buf in list(self._buffers.values()):
                self.remove(buf.handle)
