"""Device/runtime initialization (reference: GpuDeviceManager.scala:37
initializeGpuAndMemory — device acquisition, RMM pool modes, pinned pool,
store wiring; Plugin.scala:502 executor init sequence).

Here: detect the jax device, size the accounting pool from HBM (or conf
override for tests), wire the BufferCatalog tiers and the TpuSemaphore, and
enforce x64 mode.  ``initialize()`` is idempotent; ``shutdown()`` tears down
(reference executor plugin shutdown).
"""

from __future__ import annotations

import logging
import threading
from typing import Optional

from spark_rapids_tpu import config as C
from spark_rapids_tpu.config import TpuConf
from spark_rapids_tpu.memory.catalog import BufferCatalog
from spark_rapids_tpu.memory.semaphore import TpuSemaphore
from spark_rapids_tpu.memory.metrics import MetricsRegistry

log = logging.getLogger(__name__)

_runtime_lock = threading.Lock()
_runtime: Optional["DeviceManager"] = None


class DeviceManager:
    def __init__(self, conf: TpuConf):
        import os
        import jax
        # honor an explicit JAX_PLATFORMS=cpu request even when a site hook
        # pinned a different platform list in-process (hermetic CPU runs);
        # any other value is left to jax/site configuration untouched
        if os.environ.get("JAX_PLATFORMS", "").strip().lower() == "cpu":
            from jax._src import xla_bridge as _xb
            if _xb._backends and "cpu" not in _xb._backends:
                log.warning(
                    "JAX_PLATFORMS=cpu requested but jax backends were "
                    "already initialized (%s); the request cannot take "
                    "effect in this process", list(_xb._backends))
            jax.config.update("jax_platforms", "cpu")
        jax.config.update("jax_enable_x64", True)
        self.conf = conf
        self.device = jax.devices()[0]
        pool_override = conf.get(C.DEVICE_POOL_SIZE.key)
        if pool_override:
            pool_bytes = pool_override
        else:
            pool_bytes = self._detect_hbm_bytes(self.device)
            pool_bytes = int(pool_bytes * conf.get(C.DEVICE_POOL_FRACTION.key))
        spill_dir = conf.get(C.SPILL_TO_DISK_DIR.key) or None
        self.catalog = BufferCatalog(
            device_limit_bytes=pool_bytes,
            host_limit_bytes=conf.get(C.HOST_SPILL_STORAGE_SIZE.key),
            disk_dir=spill_dir,
            debug=conf.get(C.RMM_DEBUG.key))
        self.semaphore = TpuSemaphore(conf.get(C.CONCURRENT_TPU_TASKS.key))
        self.metrics = MetricsRegistry()
        log.info("DeviceManager initialized on %s pool=%dMiB",
                 self.device, pool_bytes >> 20)

    @staticmethod
    def _detect_hbm_bytes(device) -> int:
        """HBM capacity via PJRT memory stats; conservative fallback for CPU
        test platforms (reference: Cuda.memGetInfo in GpuDeviceManager)."""
        try:
            stats = device.memory_stats()
            if stats and "bytes_limit" in stats:
                return int(stats["bytes_limit"])
        except Exception:
            pass
        return 4 << 30  # virtual/CPU devices: pretend 4 GiB

    def shutdown(self) -> None:
        self.catalog.close()


def initialize(conf: Optional[TpuConf] = None) -> DeviceManager:
    """Idempotent runtime init (reference: GpuDeviceManager.initializeGpuAndMemory
    called from RapidsExecutorPlugin.init, Plugin.scala:548)."""
    global _runtime
    with _runtime_lock:
        if _runtime is None:
            _runtime = DeviceManager(conf or C.default_conf())
        elif conf is not None and conf is not _runtime.conf:
            # device/memory settings are startup-scoped (reference: RapidsConf
            # STARTUP level); a second session cannot re-shape the pool
            for key in (C.DEVICE_POOL_SIZE.key, C.DEVICE_POOL_FRACTION.key,
                        C.HOST_SPILL_STORAGE_SIZE.key, C.SPILL_TO_DISK_DIR.key,
                        C.CONCURRENT_TPU_TASKS.key):
                if conf.get(key) != _runtime.conf.get(key):
                    log.warning(
                        "runtime already initialized; startup conf %s=%r is "
                        "ignored (active value %r). Call shutdown() first to "
                        "re-shape the device runtime.", key, conf.get(key),
                        _runtime.conf.get(key))
        return _runtime


def get_runtime() -> Optional[DeviceManager]:
    return _runtime


def free_device_headroom(divisor: int) -> Optional[int]:
    """Free device-pool bytes divided by a safety factor, or None when no
    runtime is initialized (tests driving execs directly).  The single
    policy point for every out-of-core trigger (agg merge, external sort,
    running window, exchange store)."""
    rt = get_runtime()
    if rt is None:
        return None
    free = max(0, rt.catalog.device_limit - rt.catalog.device_bytes)
    return free // divisor


def shutdown() -> None:
    global _runtime
    with _runtime_lock:
        if _runtime is not None:
            _runtime.shutdown()
            _runtime = None
