"""Device/runtime initialization (reference: GpuDeviceManager.scala:37
initializeGpuAndMemory — device acquisition, RMM pool modes, pinned pool,
store wiring; Plugin.scala:502 executor init sequence).

Here: detect the jax device, size the accounting pool from HBM (or conf
override for tests), wire the BufferCatalog tiers and the TpuSemaphore, and
enforce x64 mode.  ``initialize()`` is idempotent; ``shutdown()`` tears down
(reference executor plugin shutdown).
"""

from __future__ import annotations

import logging
import threading
from typing import Optional

from spark_rapids_tpu import config as C
from spark_rapids_tpu.config import TpuConf
from spark_rapids_tpu.memory.catalog import BufferCatalog
from spark_rapids_tpu.memory.semaphore import TpuSemaphore
from spark_rapids_tpu.memory.metrics import MetricsRegistry

log = logging.getLogger(__name__)

_runtime_lock = threading.Lock()
_runtime: Optional["DeviceManager"] = None


class DeviceManager:
    def __init__(self, conf: TpuConf):
        import jax
        jax.config.update("jax_enable_x64", True)
        self.conf = conf
        self.device = jax.devices()[0]
        pool_override = conf.get(C.DEVICE_POOL_SIZE.key)
        if pool_override:
            pool_bytes = pool_override
        else:
            pool_bytes = self._detect_hbm_bytes(self.device)
            pool_bytes = int(pool_bytes * conf.get(C.DEVICE_POOL_FRACTION.key))
        spill_dir = conf.get(C.SPILL_TO_DISK_DIR.key) or None
        self.catalog = BufferCatalog(
            device_limit_bytes=pool_bytes,
            host_limit_bytes=conf.get(C.HOST_SPILL_STORAGE_SIZE.key),
            disk_dir=spill_dir,
            debug=conf.get(C.RMM_DEBUG.key))
        self.semaphore = TpuSemaphore(conf.get(C.CONCURRENT_TPU_TASKS.key))
        self.metrics = MetricsRegistry()
        log.info("DeviceManager initialized on %s pool=%dMiB",
                 self.device, pool_bytes >> 20)

    @staticmethod
    def _detect_hbm_bytes(device) -> int:
        """HBM capacity via PJRT memory stats; conservative fallback for CPU
        test platforms (reference: Cuda.memGetInfo in GpuDeviceManager)."""
        try:
            stats = device.memory_stats()
            if stats and "bytes_limit" in stats:
                return int(stats["bytes_limit"])
        except Exception:
            pass
        return 4 << 30  # virtual/CPU devices: pretend 4 GiB

    def shutdown(self) -> None:
        self.catalog.close()


def initialize(conf: Optional[TpuConf] = None) -> DeviceManager:
    """Idempotent runtime init (reference: GpuDeviceManager.initializeGpuAndMemory
    called from RapidsExecutorPlugin.init, Plugin.scala:548)."""
    global _runtime
    with _runtime_lock:
        if _runtime is None:
            _runtime = DeviceManager(conf or C.default_conf())
        return _runtime


def get_runtime() -> Optional[DeviceManager]:
    return _runtime


def shutdown() -> None:
    global _runtime
    with _runtime_lock:
        if _runtime is not None:
            _runtime.shutdown()
            _runtime = None
