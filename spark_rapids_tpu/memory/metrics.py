"""Per-task accounting (reference: GpuTaskMetrics.scala — semaphore wait,
retry counts, spill sizes/times, max device memory, surfaced as accumulators).
"""

from __future__ import annotations

import contextlib
import dataclasses
import threading
import time
from typing import Dict, Optional


@dataclasses.dataclass
class TaskMetrics:
    task_id: int = -1
    semaphore_wait_seconds: float = 0.0
    #: seconds parked in the arbiter's BLOCKED_ON_ALLOC state waiting for
    #: concurrent tasks to release memory (memory/arbiter.py)
    alloc_wait_seconds: float = 0.0
    retry_count: int = 0
    split_retry_count: int = 0
    oom_count: int = 0
    spill_count: int = 0
    spill_bytes: int = 0
    op_time_seconds: Dict[str, float] = dataclasses.field(default_factory=dict)
    max_device_bytes: int = 0

    def observe_device_bytes(self, n: int) -> None:
        if n > self.max_device_bytes:
            self.max_device_bytes = n

    @contextlib.contextmanager
    def time_op(self, name: str):
        t0 = time.monotonic()
        try:
            yield
        finally:
            self.op_time_seconds[name] = (self.op_time_seconds.get(name, 0.0) +
                                          time.monotonic() - t0)

    def merge(self, other: "TaskMetrics") -> None:
        self.semaphore_wait_seconds += other.semaphore_wait_seconds
        self.alloc_wait_seconds += other.alloc_wait_seconds
        self.retry_count += other.retry_count
        self.split_retry_count += other.split_retry_count
        self.oom_count += other.oom_count
        self.spill_count += other.spill_count
        self.spill_bytes += other.spill_bytes
        for k, v in other.op_time_seconds.items():
            self.op_time_seconds[k] = self.op_time_seconds.get(k, 0.0) + v
        self.max_device_bytes = max(self.max_device_bytes, other.max_device_bytes)


class MetricsRegistry:
    """Aggregates finished tasks' metrics (driver-side accumulator analog)."""

    def __init__(self):
        self._lock = threading.Lock()
        self.total = TaskMetrics()
        self.finished_tasks = 0
        self.started_tasks = 0

    def note_started(self) -> None:
        with self._lock:
            self.started_tasks += 1

    def active_count(self) -> int:
        """Tasks started but not yet reported (the resource sampler's
        active-task gauge)."""
        with self._lock:
            return max(0, self.started_tasks - self.finished_tasks)

    def report(self, m: TaskMetrics) -> None:
        with self._lock:
            self.total.merge(m)
            self.finished_tasks += 1

    def snapshot(self):
        """(totals copy, finished_tasks) under one lock — the delta basis
        for per-query summaries (aux/tracing.QueryExecution)."""
        with self._lock:
            s = TaskMetrics()
            s.merge(self.total)
            return s, self.finished_tasks


@contextlib.contextmanager
def task_scope(task_id: int, registry: Optional[MetricsRegistry] = None):
    """Binds a task id + metrics to the current thread for the duration of a
    task (reference: RmmSpark thread-to-task registration + onTaskCompletion
    listeners in ScalableTaskCompletion)."""
    from spark_rapids_tpu.memory.retry import task_context
    ctx = task_context()
    prev_id, prev_metrics = ctx.task_id, ctx.metrics
    ctx.task_id = task_id
    ctx.metrics = TaskMetrics(task_id=task_id)
    if registry is not None:
        registry.note_started()
    try:
        yield ctx.metrics
    finally:
        if registry is not None:
            registry.report(ctx.metrics)
        m = ctx.metrics
        from spark_rapids_tpu.aux.events import emit
        emit("taskEnd", task_id=task_id, retry_count=m.retry_count,
             split_retry_count=m.split_retry_count, oom_count=m.oom_count,
             spill_count=m.spill_count, spill_bytes=m.spill_bytes,
             semaphore_wait_s=round(m.semaphore_wait_seconds, 6),
             alloc_wait_s=round(m.alloc_wait_seconds, 6),
             max_device_bytes=m.max_device_bytes)
        # release the semaphore if the task still holds it (completion listener)
        from spark_rapids_tpu.memory.device_manager import get_runtime
        rt = get_runtime()
        if rt is not None:
            rt.semaphore.release_if_necessary(task_id)
        ctx.task_id, ctx.metrics = prev_id, prev_metrics
