"""OOM retry / split-and-retry discipline with deterministic fault injection.

Reference: ``RmmRapidsRetryIterator.scala`` (withRetry/withRetryNoSplit/
withSplitAndRetry, :33-757) + the ``RmmSpark`` JNI per-thread state machine
that throws ``GpuRetryOOM`` / ``GpuSplitAndRetryOOM`` and supports
``forceRetryOOM`` / ``forceSplitAndRetryOOM`` test injection
(tests/.../RmmSparkRetrySuiteBase.scala:27-53, GpuSortRetrySuite.scala:183).

Semantics:
- ``RetryOOM``: the work may succeed if re-run after other tasks release
  memory / inputs are spilled.  The retry loop makes inputs spillable, spills
  the catalog, optionally blocks, and re-runs.
- ``SplitAndRetryOOM``: re-running alone won't help; the input must be split
  into smaller pieces first.  Only the *top-most* retry frame of a thread
  splits (nested frames re-raise), matching the reference.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Iterable, Iterator, List, Optional, TypeVar

X = TypeVar("X")
K = TypeVar("K")


class RetryOOM(MemoryError):
    """Work should be retried after memory pressure is relieved
    (reference: com.nvidia.spark.rapids.jni.GpuRetryOOM)."""


class SplitAndRetryOOM(MemoryError):
    """Work must be split smaller and retried
    (reference: com.nvidia.spark.rapids.jni.GpuSplitAndRetryOOM)."""


class CpuRetryOOM(MemoryError):
    """Host-memory flavor of RetryOOM (reference CpuRetryOOM)."""


class _TaskContext(threading.local):
    """Per-thread task state (reference: RmmSpark thread registration)."""

    def __init__(self):
        self.task_id: Optional[int] = None
        self.retry_count = 0
        self.split_retry_count = 0
        self.retry_frame_depth = 0
        #: count of ACTIVE top-level with_retry frames on this thread —
        #: the frames that may absorb a SplitAndRetryOOM by splitting
        #: their input.  The memory arbiter reads this to decide whether
        #: a forced deadlock-break wake can be a SplitAndRetryOOM or must
        #: fall back to RetryOOM (memory/arbiter.py victim selection).
        self.split_frames = 0
        # fault injection counters: fire RetryOOM on the next N tracked allocs
        # after skipping `skip` of them
        self.inject_retry_oom = 0
        self.inject_retry_skip = 0
        self.inject_split_oom = 0
        self.inject_split_skip = 0
        #: conf-armed injection only faults inside retry frames (the
        #: reference's RMM-level retry covers EVERY allocation; ours is
        #: frame-scoped, so an unframed fault would escape as an error)
        self.inject_framed_only = False
        self.metrics = None  # TaskMetrics, attached by task_context()


_TL = _TaskContext()

# chaos hook bound once: maybe_inject_oom sits on the allocation hot path
# and must not pay a module lookup per tracked alloc (aux.faults has no
# import-time dependency on this module, so a top-of-call-graph bind is
# safe; the hook itself is one dict check when nothing is armed)
from spark_rapids_tpu.aux.faults import maybe_fire as _chaos_fire  # noqa: E402


def task_context() -> _TaskContext:
    return _TL


def force_retry_oom(num_ooms: int = 1, skip: int = 0,
                    framed_only: bool = False) -> None:
    """Arms deterministic RetryOOM injection for this thread
    (reference: RmmSpark.forceRetryOOM)."""
    _TL.inject_retry_oom = num_ooms
    _TL.inject_retry_skip = skip
    _TL.inject_framed_only = framed_only


def force_split_and_retry_oom(num_ooms: int = 1, skip: int = 0) -> None:
    """Arms deterministic SplitAndRetryOOM injection for this thread
    (reference: RmmSpark.forceSplitAndRetryOOM)."""
    _TL.inject_split_oom = num_ooms
    _TL.inject_split_skip = skip


def maybe_inject_oom() -> None:
    """Called at tracked allocation points (catalog adds, kernel staging).
    Mirrors the allocation-hook injection in the RmmSpark state machine.

    Two injection sources share this hook: the thread-local counters armed
    by ``force_retry_oom`` (per-task, frame-aware) and the process-wide
    chaos registry's ``memory.alloc`` point (``spark.rapids.chaos.*`` via
    aux/faults.py — the same mechanism the shuffle and task layers use)."""
    _chaos_fire("memory.alloc")
    if _TL.inject_retry_oom > 0:
        if _TL.inject_framed_only and _TL.retry_frame_depth == 0:
            pass        # unframed point: a fault here would escape
        elif _TL.inject_retry_skip > 0:
            _TL.inject_retry_skip -= 1
        else:
            _TL.inject_retry_oom -= 1
            raise RetryOOM("injected RetryOOM")
    if _TL.inject_split_oom > 0:
        if _TL.inject_split_skip > 0:
            _TL.inject_split_skip -= 1
        else:
            _TL.inject_split_oom -= 1
            raise SplitAndRetryOOM("injected SplitAndRetryOOM")


class AutoCloseableTargetSize:
    """A target size that can be halved on split-retry, with a floor
    (reference: RmmRapidsRetryIterator.scala AutoCloseableTargetSize)."""

    def __init__(self, target: int, minimum: int):
        self.target = max(target, minimum)
        self.minimum = minimum

    def split(self) -> "AutoCloseableTargetSize":
        halved = self.target // 2
        if halved < self.minimum:
            raise SplitAndRetryOOM(
                f"cannot split target {self.target} below minimum {self.minimum}")
        return AutoCloseableTargetSize(halved, self.minimum)


def split_half_by_rows(spillable) -> List:
    """Default split policy: split a SpillableColumnarBatch in half by rows
    (reference: RmmRapidsRetryIterator.splitSpillableInHalfByRows)."""
    batch = spillable.get_host_batch()
    n = batch.row_count
    if n < 2:
        raise SplitAndRetryOOM("cannot split a batch with fewer than 2 rows")
    spillable.close()
    mid = n // 2
    from spark_rapids_tpu.memory.spillable import SpillableColumnarBatch
    return [SpillableColumnarBatch.from_host(batch.slice(0, mid),
                                             spillable.priority),
            SpillableColumnarBatch.from_host(batch.slice(mid, n - mid),
                                             spillable.priority)]


def _relieve_pressure(caused_by: BaseException) -> None:
    """Between attempts: spill catalog buffers and give other tasks a chance
    (reference blocks the thread in RmmSpark until memory frees; here we
    synchronously spill, which is deterministic and single-process-friendly)."""
    from spark_rapids_tpu.memory.device_manager import get_runtime
    rt = get_runtime()
    if rt is not None:
        rt.catalog.synchronous_spill(target_free_bytes=None)
    if _TL.metrics is not None:
        _TL.metrics.retry_count += 1
    from spark_rapids_tpu.aux.events import emit
    emit("retryOOM", task_id=_TL.task_id,
         cause=f"{type(caused_by).__name__}: {caused_by}"[:160])
    time.sleep(0)  # yield


def with_retry_no_split(spillable_or_none, fn: Callable[..., X],
                        max_retries: int = 100) -> X:
    """Runs ``fn(spillable)`` (or ``fn()``) retrying on RetryOOM; a
    SplitAndRetryOOM is fatal here (reference: withRetryNoSplit)."""
    _TL.retry_frame_depth += 1
    try:
        attempts = 0
        while True:
            try:
                if spillable_or_none is None:
                    return fn()
                return fn(spillable_or_none)
            except RetryOOM as e:
                attempts += 1
                _TL.retry_count += 1
                if attempts > max_retries:
                    raise MemoryError(
                        f"giving up after {attempts} RetryOOMs") from e
                _relieve_pressure(e)
    finally:
        _TL.retry_frame_depth -= 1


def with_retry(spillables, fn: Callable[..., X],
               split_policy: Callable = split_half_by_rows,
               max_retries: int = 100) -> Iterator[X]:
    """Runs ``fn`` over each spillable input, retrying on RetryOOM and
    splitting inputs on SplitAndRetryOOM (reference: withRetry + withSplitAndRetry).

    Only a top-level retry frame may split; nested frames re-raise so the
    outermost owner of the inputs decides (reference semantics).
    """
    if not isinstance(spillables, (list, tuple)):
        spillables = [spillables]
    queue: List = list(spillables)
    # capture the nesting decision at CALL time, not at first next(): a
    # generator created at top level but drained inside another retry frame
    # must still be allowed to split (generator bodies run lazily)
    top_level = _TL.retry_frame_depth == 0
    return _with_retry_gen(queue, fn, split_policy, max_retries, top_level)


def _close_quietly(spillable) -> None:
    close = getattr(spillable, "close", None)
    if close is None:
        return
    try:
        close()
    except Exception:   # noqa: BLE001 - cleanup must not mask the cause
        pass


def _with_retry_gen(queue, fn, split_policy, max_retries, top_level):
    _TL.retry_frame_depth += 1
    if top_level:
        _TL.split_frames += 1
    item = None
    done = False
    try:
        while queue:
            item = queue.pop(0)
            attempts = 0
            while True:
                try:
                    yield fn(item)
                    # consumed: ownership passed through fn/the caller —
                    # a later failure must not close it behind their back
                    item = None
                    break
                except GeneratorExit:
                    # abandoned while suspended at the yield: this item's
                    # result was already delivered, so only the queue is
                    # unconsumed
                    item = None
                    raise
                except RetryOOM as e:
                    attempts += 1
                    _TL.retry_count += 1
                    if attempts > max_retries:
                        raise MemoryError(
                            f"giving up after {attempts} RetryOOMs") from e
                    _relieve_pressure(e)
                except SplitAndRetryOOM as e:
                    if not top_level:
                        raise
                    _TL.split_retry_count += 1
                    if _TL.metrics is not None:
                        _TL.metrics.split_retry_count += 1
                    pieces = split_policy(item)
                    # the policy closed the original and owns the pieces
                    # via the queue now (a policy that raises instead
                    # leaves `item` set for the finally-cleanup)
                    item = None
                    from spark_rapids_tpu.aux.events import emit
                    emit("splitRetry", task_id=_TL.task_id,
                         pieces=len(pieces))
                    queue = pieces + queue
                    break
        done = True
    finally:
        _TL.retry_frame_depth -= 1
        if top_level:
            _TL.split_frames -= 1
        if not done:
            # early exit — max-retries MemoryError, split exhaustion, or
            # the caller abandoning iteration (GeneratorExit): close the
            # in-flight item and everything still queued instead of
            # leaking catalog-registered spillables (they would pin
            # device/host bytes until process exit)
            if item is not None:
                _close_quietly(item)
            for pending in queue:
                _close_quietly(pending)


def drain_with_retry(spillables, fn: Callable[..., X],
                     split_policy: Callable = split_half_by_rows) -> List[X]:
    """Eager list-returning form of ``with_retry``."""
    return list(with_retry(spillables, fn, split_policy))
