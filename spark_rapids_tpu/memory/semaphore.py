"""Device admission semaphore (reference: GpuSemaphore.scala:51-120).

Limits how many tasks may hold the device concurrently
(``spark.rapids.sql.concurrentGpuTasks``).  Tasks acquire before their first
device section and release at completion; re-entrant per task.  Holders can
be dumped for debugging (reference: dumpActiveStackTracesToLog :120).
"""

from __future__ import annotations

import threading
import time
import traceback
from typing import Dict, Optional


class TpuSemaphore:
    def __init__(self, max_concurrent: int):
        self.max_concurrent = max_concurrent
        self._sem = threading.Semaphore(max_concurrent)
        self._holders: Dict[int, dict] = {}
        self._lock = threading.Lock()
        self._waiting = 0

    @staticmethod
    def _tid(task_id: Optional[int]) -> int:
        from spark_rapids_tpu.memory.retry import task_context
        if task_id is not None:
            return task_id
        ctx_id = task_context().task_id
        return ctx_id if ctx_id is not None else threading.get_ident()

    def acquire_if_necessary(self, task_id: Optional[int] = None) -> None:
        """Idempotent per-task acquire (reference: acquireIfNecessary :100)."""
        from spark_rapids_tpu.memory.retry import task_context
        tid = self._tid(task_id)
        with self._lock:
            if tid in self._holders:
                self._holders[tid]["depth"] += 1
                return
        t0 = time.monotonic()
        with self._lock:
            self._waiting += 1
        try:
            self._sem.acquire()
        finally:
            with self._lock:
                self._waiting -= 1
        wait = time.monotonic() - t0
        mt = task_context().metrics
        if mt is not None:
            mt.semaphore_wait_seconds += wait
        from spark_rapids_tpu.aux.events import emit
        emit("semaphoreAcquired", task_id=tid, wait_s=round(wait, 6))
        with self._lock:
            entry = self._holders.get(tid)
            if entry is not None:
                # raced with another thread of the same task: count the
                # acquire as a depth and return the duplicate permit
                entry["depth"] += 1
                self._sem.release()
                return
            self._holders[tid] = {"depth": 1, "since": time.monotonic(),
                                  "thread": threading.current_thread().name}

    def release_if_necessary(self, task_id: Optional[int] = None) -> None:
        tid = self._tid(task_id)
        with self._lock:
            entry = self._holders.get(tid)
            if entry is None:
                return
            entry["depth"] -= 1
            if entry["depth"] > 0:
                return
            del self._holders[tid]
        self._sem.release()

    def release_all(self, task_id: Optional[int] = None) -> None:
        """Drops the task's hold entirely regardless of depth (task
        completion listener analog — reference: GpuSemaphore completeTask)."""
        tid = self._tid(task_id)
        with self._lock:
            if self._holders.pop(tid, None) is None:
                return
        self._sem.release()

    def held_by(self, task_id: int) -> bool:
        with self._lock:
            return task_id in self._holders

    def stats(self) -> dict:
        """Read-only snapshot for the resource sampler: permit budget,
        current holders and threads queued on admission."""
        with self._lock:
            return {"max_concurrent": self.max_concurrent,
                    "holders": len(self._holders),
                    "waiting": self._waiting}

    def dump_active_holders(self) -> str:
        """reference: GpuSemaphore.dumpActiveStackTracesToLog"""
        lines = []
        with self._lock:
            for tid, entry in self._holders.items():
                held = time.monotonic() - entry["since"]
                lines.append(f"task {tid} thread={entry['thread']} "
                             f"held={held:.1f}s depth={entry['depth']}")
        frames = traceback.format_stack()
        return "\n".join(lines) + "\n" + "".join(frames[-3:])
