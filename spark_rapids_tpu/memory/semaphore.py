"""Device admission semaphore (reference: GpuSemaphore.scala:51-120).

Limits how many tasks may hold the device concurrently
(``spark.rapids.sql.concurrentGpuTasks``).  Tasks acquire before their first
device section and release at completion; re-entrant per task.  Holders can
be dumped for debugging (reference: dumpActiveStackTracesToLog :120).

Built on a condition variable (not a raw ``threading.Semaphore``) so waits
are INTERRUPTIBLE: a waiter polls the resource arbiter between bounded wait
slices, marking itself BLOCKED_ON_SEMAPHORE in the task thread-state
registry (``memory/arbiter.py``) and honoring watchdog cancellation — the
pre-arbiter semaphore waited forever with no escalation, exactly the hang
the hung-query watchdog exists to break.  Acquire/release also keep the
arbiter's device-holder view current, which is what the deadlock detector's
"all device-holding tasks are blocked" condition reads.
"""

from __future__ import annotations

import sys
import threading
import time
import traceback
from typing import Dict, Optional

#: wait slice between cancellation checks while queued on admission
_WAIT_SLICE_S = 0.05


class TpuSemaphore:
    def __init__(self, max_concurrent: int):
        self.max_concurrent = max_concurrent
        self._permits = max_concurrent
        from spark_rapids_tpu.aux.lockorder import tracked_condition
        self._cond = tracked_condition("semaphore")
        self._holders: Dict[int, dict] = {}
        self._waiting = 0

    @staticmethod
    def _tid(task_id: Optional[int]) -> int:
        from spark_rapids_tpu.memory.retry import task_context
        if task_id is not None:
            return task_id
        ctx_id = task_context().task_id
        return ctx_id if ctx_id is not None else threading.get_ident()

    def acquire_if_necessary(self, task_id: Optional[int] = None) -> None:
        """Idempotent per-task acquire (reference: acquireIfNecessary :100)."""
        from spark_rapids_tpu.memory.arbiter import TaskState, get_arbiter
        from spark_rapids_tpu.memory.retry import task_context
        tid = self._tid(task_id)
        arb = get_arbiter()
        with self._cond:
            entry = self._holders.get(tid)
            if entry is not None:
                entry["depth"] += 1
                return
            self._waiting += 1
            try:
                # another thread of the SAME task acquiring concurrently
                # creates the holder entry; re-check it each wake so both
                # land on one permit at depth 2 (the old duplicate-permit
                # return dance, folded into the wait condition)
                t0 = arb.wait_cancellable(
                    self._cond,
                    lambda: tid not in self._holders
                    and self._permits <= 0,
                    TaskState.BLOCKED_ON_SEMAPHORE,
                    slice_s=_WAIT_SLICE_S)
            finally:
                self._waiting -= 1
            entry = self._holders.get(tid)
            if entry is not None:
                # a sibling thread of the same task won the race and
                # created the holder entry: share its permit (depth 2),
                # but the wait this thread endured still counts below
                entry["depth"] += 1
                raced = True
            else:
                raced = False
                self._permits -= 1
                self._holders[tid] = {
                    "depth": 1, "since": time.monotonic(),
                    "thread": threading.current_thread().name,
                    "ident": threading.get_ident()}
        if not raced:
            arb.note_device_held(tid, True)
        wait = time.monotonic() - t0 if t0 is not None else 0.0
        mt = task_context().metrics
        if mt is not None:
            mt.semaphore_wait_seconds += wait
        from spark_rapids_tpu.aux.events import emit
        emit("semaphoreAcquired", task_id=tid, wait_s=round(wait, 6))

    def release_if_necessary(self, task_id: Optional[int] = None) -> None:
        tid = self._tid(task_id)
        with self._cond:
            entry = self._holders.get(tid)
            if entry is None:
                return
            entry["depth"] -= 1
            if entry["depth"] > 0:
                return
            del self._holders[tid]
            self._permits += 1
            self._cond.notify_all()
        from spark_rapids_tpu.memory.arbiter import get_arbiter
        get_arbiter().note_device_held(tid, False)

    def release_all(self, task_id: Optional[int] = None) -> None:
        """Drops the task's hold entirely regardless of depth (task
        completion listener analog — reference: GpuSemaphore completeTask)."""
        tid = self._tid(task_id)
        with self._cond:
            if self._holders.pop(tid, None) is None:
                return
            self._permits += 1
            self._cond.notify_all()
        from spark_rapids_tpu.memory.arbiter import get_arbiter
        get_arbiter().note_device_held(tid, False)

    def held_by(self, task_id: int) -> bool:
        with self._cond:
            return task_id in self._holders

    def resize(self, new_max: int) -> int:
        """Online permit-budget adjustment (the serving AutoTuner loop
        applies ``spark.rapids.sql.concurrentGpuTasks`` deltas between
        queries).  Growing wakes waiters immediately; shrinking lets
        permits go transiently negative and takes effect as holders
        release — a held permit is never revoked.  Returns the old
        budget."""
        new_max = max(1, int(new_max))
        with self._cond:
            old = self.max_concurrent
            if new_max == old:
                return old
            self._permits += new_max - old
            self.max_concurrent = new_max
            if new_max > old:
                self._cond.notify_all()
        return old

    def stats(self) -> dict:
        """Read-only snapshot for the resource sampler: permit budget,
        current holders and threads queued on admission."""
        with self._cond:
            return {"max_concurrent": self.max_concurrent,
                    "holders": len(self._holders),
                    "waiting": self._waiting}

    def dump_active_holders(self) -> str:
        """reference: GpuSemaphore.dumpActiveStackTracesToLog — each
        holder's LIVE stack (via sys._current_frames, keyed by the
        ident recorded at acquire), not the dumper's own stack."""
        frames = sys._current_frames()
        now = time.monotonic()
        with self._cond:
            holders = [(tid, dict(e)) for tid, e in self._holders.items()]
            waiting = self._waiting
        lines = [f"== semaphore: {len(holders)}/{self.max_concurrent} "
                 f"permit(s) held, {waiting} waiting =="]
        for tid, e in holders:
            held = now - e["since"]
            lines.append(f"task {tid} thread={e['thread']} "
                         f"held={held:.1f}s depth={e['depth']}")
            f = frames.get(e.get("ident"))
            if f is not None:
                for fl in traceback.format_stack(f)[-4:]:
                    lines.extend("  " + x
                                 for x in fl.rstrip().splitlines())
        return "\n".join(lines)
