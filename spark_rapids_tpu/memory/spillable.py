"""Spillable columnar batches (reference: SpillableColumnarBatch.scala:29
trait, :90 device impl, :178 host impl).

A ``SpillableColumnarBatch`` owns a catalog handle; holding one instead of a
raw ``ColumnarBatch`` makes the data movable by the catalog between attempts
of a retry frame — the core contract of the out-of-core discipline.
"""

from __future__ import annotations

from typing import Optional

from spark_rapids_tpu.columnar.batch import ColumnarBatch, HostColumnarBatch
from spark_rapids_tpu.memory.catalog import BufferCatalog, SpillPriority


def _default_catalog() -> BufferCatalog:
    from spark_rapids_tpu.memory.device_manager import get_runtime, initialize
    rt = get_runtime()
    if rt is None:
        rt = initialize()
    return rt.catalog


class SpillableColumnarBatch:
    """Owns a buffer via the catalog; ``get_batch()`` materializes on device
    (unspilling if needed), ``close()`` releases."""

    def __init__(self, handle, catalog: BufferCatalog,
                 row_count: int, sized_nbytes: int, priority: int):
        self._handle = handle
        self._catalog = catalog
        self.row_count = row_count
        self.sized_nbytes = sized_nbytes
        self.priority = priority

    # -- constructors -------------------------------------------------------
    @staticmethod
    def from_device(batch: ColumnarBatch,
                    priority: int = SpillPriority.ACTIVE_BATCHING,
                    catalog: Optional[BufferCatalog] = None,
                    owned: bool = True) -> "SpillableColumnarBatch":
        """``owned=False`` registers WITHOUT transferring array ownership:
        spill/close drop the catalog's reference instead of .delete()ing —
        required when the batch's arrays may be shared (scan device
        caches, exchange stores) or are handed onward while registered
        (pipeline prefetch queues)."""
        cat = catalog or _default_catalog()
        handle = cat.add_device_batch(batch, priority, owned=owned)
        return SpillableColumnarBatch(handle, cat, batch.row_count,
                                      batch.sized_nbytes(), priority)

    @staticmethod
    def from_host(batch: HostColumnarBatch,
                  priority: int = SpillPriority.HOST_MEMORY,
                  catalog: Optional[BufferCatalog] = None
                  ) -> "SpillableColumnarBatch":
        cat = catalog or _default_catalog()
        handle = cat.add_host_batch(batch, priority)
        return SpillableColumnarBatch(handle, cat, batch.row_count,
                                      batch.nbytes(), priority)

    # -- access -------------------------------------------------------------
    def get_batch(self) -> ColumnarBatch:
        """Device batch; unspills if it was pushed down a tier
        (reference: SpillableColumnarBatchImpl.getColumnarBatch); the
        catalog emits the ``unspill`` event for the call that promotes.
        Materializing counts as task progress for the hung-query
        watchdog (a long unspill chain is slow, not wedged)."""
        from spark_rapids_tpu.memory.arbiter import note_progress_current
        note_progress_current()
        return self._catalog.get_device_batch(self._handle)

    def get_host_batch(self) -> HostColumnarBatch:
        return self._catalog.get_host_batch(self._handle)

    def release(self) -> ColumnarBatch:
        """Unwraps: returns the live device batch and unregisters WITHOUT
        deleting its arrays — ownership transfers to the caller.  The
        disown happens BEFORE materializing so a racing spill can no
        longer delete the arrays out from under the returned batch."""
        self._catalog.disown(self._handle)
        batch = self.get_batch()
        self.close()
        return batch

    def make_unspillable(self) -> None:
        """Pin while actively computing (reference setSpillable(false))."""
        self._catalog.set_spillable(self._handle, False)

    def make_spillable(self) -> None:
        self._catalog.set_spillable(self._handle, True)

    @property
    def closed(self) -> bool:
        return self._handle.closed

    def close(self) -> None:
        if not self._handle.closed:
            self._catalog.remove(self._handle)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def __repr__(self):
        return (f"SpillableColumnarBatch(rows={self.row_count}, "
                f"bytes={self.sized_nbytes}, closed={self.closed})")
