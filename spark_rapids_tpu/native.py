"""ctypes bindings for libtpucol, the native C++ host runtime.

The reference's engine is JVM code calling into native C++/CUDA through JNI
(cuDF Java bindings + spark-rapids-jni; SURVEY.md §2.16).  Here the engine is
Python calling into native C++ through ctypes: ``native/tpucol.cpp`` provides
the host memory pool (RMM analog), the LZ4 block codec (nvcomp analog), bulk
murmur3/xxhash64 row hashing (jni ``Hash`` analog), row⇄columnar conversion
(jni ``RowConversion`` analog) and the shuffle partition/gather hot loops
(``GpuPartitioning`` host half).

The library is compiled on first use (single translation unit, ~1s) and
cached next to the source.  Every entry point has a pure-numpy fallback so
the engine still runs where no C++ toolchain exists; ``HAVE_NATIVE`` tells
callers (and tests) which path is live.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional, Tuple

import numpy as np

_NATIVE_DIR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "native")
_SO_PATH = os.path.join(_NATIVE_DIR, "libtpucol.so")

_lib = None
_lib_lock = threading.Lock()
_build_attempted = False


def _try_build() -> bool:
    global _build_attempted
    if _build_attempted:
        return os.path.exists(_SO_PATH)
    _build_attempted = True
    src = os.path.join(_NATIVE_DIR, "tpucol.cpp")
    if not os.path.exists(src):
        return False
    try:
        subprocess.run(["make", "-s", "-C", _NATIVE_DIR],
                       check=True, capture_output=True, timeout=120)
        return os.path.exists(_SO_PATH)
    except Exception:
        return False


def _bind(lib):
    u64, u32, i64, i32, u8 = (ctypes.c_uint64, ctypes.c_uint32,
                              ctypes.c_int64, ctypes.c_int32, ctypes.c_uint8)
    vp = ctypes.c_void_p
    p = ctypes.POINTER
    lib.tpucol_abi_version.restype = ctypes.c_int
    lib.tpucol_pool_create.restype = vp
    lib.tpucol_pool_create.argtypes = [u64]
    lib.tpucol_pool_destroy.argtypes = [vp]
    lib.tpucol_pool_alloc.restype = vp
    lib.tpucol_pool_alloc.argtypes = [vp, u64]
    lib.tpucol_pool_free.restype = ctypes.c_int
    lib.tpucol_pool_free.argtypes = [vp]
    lib.tpucol_pool_stats.argtypes = [vp, p(u64)]
    lib.tpucol_pool_set_limit.argtypes = [vp, u64]
    lib.tpucol_lz4_max_compressed.restype = u64
    lib.tpucol_lz4_max_compressed.argtypes = [u64]
    lib.tpucol_lz4_compress.restype = u64
    lib.tpucol_lz4_compress.argtypes = [p(u8), u64, p(u8), u64]
    lib.tpucol_lz4_decompress.restype = u64
    lib.tpucol_lz4_decompress.argtypes = [p(u8), u64, p(u8), u64]
    lib.tpucol_murmur3_i64.argtypes = [p(i64), p(u8), u64, p(u32)]
    lib.tpucol_murmur3_i32.argtypes = [p(i32), p(u8), u64, p(u32)]
    lib.tpucol_murmur3_bytes.argtypes = [p(u8), p(i32), p(u8), u64, u64, p(u32)]
    lib.tpucol_xxhash64_i64.argtypes = [p(i64), p(u8), u64, p(u64)]
    lib.tpucol_rows_to_cols.restype = ctypes.c_int
    lib.tpucol_rows_to_cols.argtypes = [p(u8), u64, p(u32), u32,
                                        p(vp), p(vp)]
    lib.tpucol_cols_to_rows.restype = ctypes.c_int
    lib.tpucol_cols_to_rows.argtypes = [p(u8), u64, p(u32), u32,
                                        p(vp), p(vp)]
    lib.tpucol_partition_indices.restype = ctypes.c_int
    lib.tpucol_partition_indices.argtypes = [p(i32), u64, u32, p(u32), p(u32)]
    lib.tpucol_gather.argtypes = [p(u8), p(u32), u64, u32, p(u8)]
    return lib


def get_lib():
    """The loaded native library, or None (fallbacks engage)."""
    global _lib
    if _lib is not None:
        return _lib if _lib is not False else None
    with _lib_lock:
        if _lib is not None:
            return _lib if _lib is not False else None
        if os.environ.get("SPARK_RAPIDS_TPU_DISABLE_NATIVE") == "1":
            _lib = False
            return None
        if not os.path.exists(_SO_PATH) and not _try_build():
            _lib = False
            return None
        try:
            lib = _bind(ctypes.CDLL(_SO_PATH))
            if lib.tpucol_abi_version() != 1:
                _lib = False
                return None
            _lib = lib
        except OSError:
            _lib = False
            return None
    return _lib


def have_native() -> bool:
    return get_lib() is not None


def _u8p(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8))


# ---------------------------------------------------------------------------
# Host memory pool (RMM analog) — accounting + limit, feeding the retry layer
# ---------------------------------------------------------------------------

class NativeHostPool:
    """Tracking host allocator.  With the native lib, allocations live in C++
    with header-tagged accounting; otherwise a Python-accounted dict of numpy
    buffers.  A failed allocation returns None — callers translate that into
    the engine's RetryOOM discipline (memory/retry.py)."""

    def __init__(self, limit_bytes: int = 0):
        self._lib = get_lib()
        self._limit = limit_bytes
        # liveness is owned HERE, not by the C++ header magic: a handle is a
        # plain int, and probing freed memory for a magic value is UB.
        self._live = set()
        self._live_mu = threading.Lock()
        if self._lib is not None:
            self._pool = self._lib.tpucol_pool_create(limit_bytes)
        else:
            self._pool = None
            self._in_use = 0
            self._peak = 0
            self._total = 0
            self._failed = 0
            self._bufs = {}
            self._mu = threading.Lock()

    def _require_open(self):
        if self._lib is not None and self._pool is None:
            raise ValueError("pool is closed")

    def alloc(self, size: int) -> Optional[int]:
        """Returns an opaque handle (address) or None on OOM."""
        if self._lib is not None:
            self._require_open()
            ptr = self._lib.tpucol_pool_alloc(self._pool, size)
            if ptr:
                with self._live_mu:
                    self._live.add(ptr)
            return ptr or None
        with self._mu:
            if self._limit and self._in_use + size > self._limit:
                self._failed += 1
                return None
            buf = np.empty(size, dtype=np.uint8)
            addr = buf.ctypes.data
            self._bufs[addr] = (buf, size)
            self._in_use += size
            self._peak = max(self._peak, self._in_use)
            self._total += 1
            return addr

    def free(self, handle: Optional[int]) -> None:
        if handle is None:
            return
        if self._lib is not None:
            self._require_open()
            with self._live_mu:
                if handle not in self._live:
                    raise ValueError(
                        "bad free: not a live pool allocation (double free?)")
                self._live.discard(handle)
            if self._lib.tpucol_pool_free(ctypes.c_void_p(handle)) != 0:
                raise ValueError("bad free: not a pool allocation")
            return
        with self._mu:
            if handle not in self._bufs:
                raise ValueError(
                    "bad free: not a live pool allocation (double free?)")
            _, size = self._bufs.pop(handle)
            self._in_use -= size

    def view(self, handle: int, size: int) -> np.ndarray:
        """uint8 view of an allocation (zero-copy)."""
        if self._lib is not None:
            return np.ctypeslib.as_array(
                ctypes.cast(handle, ctypes.POINTER(ctypes.c_uint8)),
                shape=(size,))
        return self._bufs[handle][0][:size]

    def stats(self) -> dict:
        if self._lib is not None:
            self._require_open()
            out = (ctypes.c_uint64 * 5)()
            self._lib.tpucol_pool_stats(self._pool, out)
            return {"in_use": out[0], "peak": out[1], "total_allocs": out[2],
                    "failed_allocs": out[3], "limit": out[4]}
        with self._mu:
            return {"in_use": self._in_use, "peak": self._peak,
                    "total_allocs": self._total, "failed_allocs": self._failed,
                    "limit": self._limit}

    def set_limit(self, limit_bytes: int) -> None:
        self._limit = limit_bytes
        if self._lib is not None:
            self._require_open()
            self._lib.tpucol_pool_set_limit(self._pool, limit_bytes)

    def close(self) -> None:
        if self._lib is not None and self._pool:
            self._lib.tpucol_pool_destroy(self._pool)
            self._pool = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


# ---------------------------------------------------------------------------
# LZ4 block codec (nvcomp analog)
# ---------------------------------------------------------------------------

_FRAME_HDR = 14  # tag(2) + raw_len(8) + crc32(4)


def lz4_compress(data: bytes | np.ndarray) -> bytes:
    """LZ4 block compression with a crc32 of the raw payload in the frame
    header (shuffle payloads cross worker boundaries; LZ4 blocks have no
    integrity check of their own).  Falls back to zlib framing when the
    native lib is absent — the tag byte tells the decoder which it got."""
    import zlib
    if isinstance(data, (bytes, bytearray, memoryview)):
        src = np.frombuffer(data, dtype=np.uint8)
    else:
        # reinterpret the array's BYTES (a value-cast would corrupt payloads)
        src = np.ascontiguousarray(data).reshape(-1).view(np.uint8)
    n = src.size
    crc = zlib.crc32(src)
    lib = get_lib()
    if lib is not None and n:
        cap = int(lib.tpucol_lz4_max_compressed(n))
        dst = np.empty(cap, dtype=np.uint8)
        out = int(lib.tpucol_lz4_compress(_u8p(src), n, _u8p(dst), cap))
        if out:
            return (b"L4" + n.to_bytes(8, "little") +
                    crc.to_bytes(4, "little") + dst[:out].tobytes())
    return (b"ZL" + n.to_bytes(8, "little") + crc.to_bytes(4, "little") +
            zlib.compress(src.tobytes(), 1))


def lz4_decompress(frame: bytes) -> bytes:
    import zlib
    tag = frame[:2]
    raw_len = int.from_bytes(frame[2:10], "little")
    crc = int.from_bytes(frame[10:14], "little")
    payload = frame[_FRAME_HDR:]
    if tag == b"ZL":
        out = zlib.decompress(payload)
    elif tag == b"L4":
        lib = get_lib()
        if lib is None:
            out = _lz4_decompress_py(payload, raw_len)
        else:
            src = np.frombuffer(payload, dtype=np.uint8)
            dst = np.empty(raw_len, dtype=np.uint8)
            got = int(lib.tpucol_lz4_decompress(_u8p(src), src.size,
                                                _u8p(dst), raw_len))
            if got != raw_len:
                raise ValueError(
                    f"corrupt LZ4 frame: got {got}, want {raw_len}")
            out = dst.tobytes()
    else:
        raise ValueError(f"unknown codec frame tag {tag!r}")
    if len(out) != raw_len or zlib.crc32(out) != crc:
        raise ValueError("corrupt frame: checksum mismatch")
    return out


def _lz4_decompress_py(src: bytes, raw_len: int) -> bytes:
    """Pure-python LZ4 block decoder (interop path when native is absent).
    Fully bounds-checked: truncated/malformed frames raise ValueError, the
    same contract the native decoder keeps."""
    try:
        return _lz4_decompress_py_inner(src, raw_len)
    except IndexError:
        raise ValueError("corrupt LZ4 frame: truncated input") from None


def _lz4_decompress_py_inner(src: bytes, raw_len: int) -> bytes:
    out = bytearray()
    i, n = 0, len(src)
    while i < n:
        token = src[i]
        i += 1
        litlen = token >> 4
        if litlen == 15:
            while True:
                b = src[i]
                i += 1
                litlen += b
                if b != 255:
                    break
        out += src[i:i + litlen]
        i += litlen
        if i >= n:
            break
        off = src[i] | (src[i + 1] << 8)
        i += 2
        mlen = (token & 15) + 4
        if (token & 15) == 15:
            while True:
                b = src[i]
                i += 1
                mlen += b
                if b != 255:
                    break
        start = len(out) - off
        if start < 0:
            raise ValueError("corrupt LZ4 frame: bad offset")
        for k in range(mlen):
            out.append(out[start + k])
    if len(out) != raw_len:
        raise ValueError("corrupt LZ4 frame: length mismatch")
    return bytes(out)


# ---------------------------------------------------------------------------
# Bulk hash kernels (host-side partitioning path)
# ---------------------------------------------------------------------------

def murmur3_bulk(columns, seed: int = 42) -> np.ndarray:
    """Spark-compatible murmur3_x86_32 over rows of fixed-width/string
    columns.  ``columns`` is a list of (data, validity) where data is a numpy
    array (int/float/bool; or (chars uint8[n,w], lens int32[n]) tuple for
    strings).  Returns int32[n] hashes; must agree with the device
    implementation in expressions/hashing.py."""
    first = columns[0][0]
    n = len(first[1]) if isinstance(first, tuple) else len(first)
    seeds = np.full(n, seed, dtype=np.uint32)
    lib = get_lib()
    for data, valid in columns:
        v8 = None if valid is None else \
            np.ascontiguousarray(valid, dtype=np.uint8)
        vp = None if v8 is None else _u8p(v8)
        if isinstance(data, tuple):  # string: (chars, lens)
            chars, lens = data
            chars = np.ascontiguousarray(chars, dtype=np.uint8)
            lens = np.ascontiguousarray(lens, dtype=np.int32)
            if lib is not None:
                lib.tpucol_murmur3_bytes(
                    _u8p(chars), lens.ctypes.data_as(
                        ctypes.POINTER(ctypes.c_int32)),
                    vp, n, chars.shape[1],
                    seeds.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)))
            else:
                _murmur3_bytes_py(chars, lens, v8, seeds)
            continue
        data = np.asarray(data)
        if data.dtype == np.bool_:
            words = data.astype(np.int32)
        elif data.dtype in (np.dtype(np.int8), np.dtype(np.int16)):
            words = data.astype(np.int32)
        elif data.dtype == np.dtype(np.float32):
            # Spark hashes floatToIntBits: -0.0 -> +0.0, NaN -> canonical NaN
            f = data.astype(np.float32, copy=True)
            f[f == 0.0] = 0.0
            f[np.isnan(f)] = np.float32(np.nan)
            words = f.view(np.int32)
        elif data.dtype == np.dtype(np.float64):
            f = data.astype(np.float64, copy=True)
            f[f == 0.0] = 0.0
            f[np.isnan(f)] = np.nan
            words = f.view(np.int64)
        else:
            words = data
        words = np.ascontiguousarray(words)
        if words.dtype.itemsize == 8:
            w64 = words.view(np.int64) if words.dtype != np.int64 else words
            if lib is not None:
                lib.tpucol_murmur3_i64(
                    w64.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)), vp, n,
                    seeds.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)))
            else:
                _murmur3_i64_py(w64, v8, seeds)
        else:
            w32 = np.ascontiguousarray(words, dtype=np.int32)
            if lib is not None:
                lib.tpucol_murmur3_i32(
                    w32.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)), vp, n,
                    seeds.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)))
            else:
                _murmur3_i32_py(w32, v8, seeds)
    return seeds.view(np.int32)


def _mmh3_mix_k1(k1):
    k1 = (k1 * np.uint32(0xcc9e2d51)).astype(np.uint32)
    k1 = (k1 << np.uint32(15)) | (k1 >> np.uint32(17))
    return (k1 * np.uint32(0x1b873593)).astype(np.uint32)


def _mmh3_mix_h1(h1, k1):
    h1 = h1 ^ k1
    h1 = (h1 << np.uint32(13)) | (h1 >> np.uint32(19))
    return (h1 * np.uint32(5) + np.uint32(0xe6546b64)).astype(np.uint32)


def _mmh3_fmix(h1, length):
    h1 = h1 ^ np.uint32(length)
    h1 ^= h1 >> np.uint32(16)
    h1 = (h1 * np.uint32(0x85ebca6b)).astype(np.uint32)
    h1 ^= h1 >> np.uint32(13)
    h1 = (h1 * np.uint32(0xc2b2ae35)).astype(np.uint32)
    h1 ^= h1 >> np.uint32(16)
    return h1


def _murmur3_i32_py(vals, valid, seeds):
    with np.errstate(over="ignore"):
        h = _mmh3_fmix(_mmh3_mix_h1(seeds.copy(),
                                    _mmh3_mix_k1(vals.view(np.uint32))), 4)
    mask = slice(None) if valid is None else valid.astype(bool)
    seeds[mask] = h[mask]


def _murmur3_i64_py(vals, valid, seeds):
    u = vals.view(np.uint64)
    with np.errstate(over="ignore"):
        h1 = _mmh3_mix_h1(seeds.copy(),
                          _mmh3_mix_k1(u.astype(np.uint32)))
        h1 = _mmh3_mix_h1(h1, _mmh3_mix_k1((u >> np.uint64(32)).astype(np.uint32)))
        h = _mmh3_fmix(h1, 8)
    mask = slice(None) if valid is None else valid.astype(bool)
    seeds[mask] = h[mask]


def _murmur3_bytes_py(chars, lens, valid, seeds):
    with np.errstate(over="ignore"):
        for i in range(len(seeds)):
            if valid is not None and not valid[i]:
                continue
            data = chars[i, :lens[i]]
            h1 = np.uint32(seeds[i])
            nb = len(data) // 4
            if nb:
                blocks = data[:nb * 4].view(np.uint32)
                for b in blocks:
                    h1 = _mmh3_mix_h1(h1, _mmh3_mix_k1(b))
            for b in data[nb * 4:]:
                h1 = _mmh3_mix_h1(
                    h1, _mmh3_mix_k1(np.uint32(np.int32(np.int8(b)))))
            seeds[i] = _mmh3_fmix(h1, len(data))


def xxhash64_bulk_i64(vals: np.ndarray, valid, seed: int = 42) -> np.ndarray:
    """Spark-compatible xxhash64 over an int64 column."""
    n = len(vals)
    seeds = np.full(n, seed, dtype=np.uint64)
    vals = np.ascontiguousarray(vals, dtype=np.int64)
    lib = get_lib()
    if lib is not None:
        v8 = None if valid is None else np.ascontiguousarray(valid, np.uint8)
        lib.tpucol_xxhash64_i64(
            vals.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            None if v8 is None else _u8p(v8), n,
            seeds.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)))
        return seeds.view(np.int64)
    P1 = np.uint64(0x9E3779B185EBCA87)
    P2 = np.uint64(0xC2B2AE3D27D4EB4F)
    P3 = np.uint64(0x165667B19E3779F9)
    P4 = np.uint64(0x85EBCA77C2B2AE63)
    P5 = np.uint64(0x27D4EB2F165667C5)
    with np.errstate(over="ignore"):
        u = vals.view(np.uint64)
        h = seeds + P5 + np.uint64(8)
        k = (u * P2).astype(np.uint64)
        k = ((k << np.uint64(31)) | (k >> np.uint64(33))) * P1
        h = h ^ k
        h = ((h << np.uint64(27)) | (h >> np.uint64(37))) * P1 + P4
        h ^= h >> np.uint64(33)
        h = (h * P2).astype(np.uint64)
        h ^= h >> np.uint64(29)
        h = (h * P3).astype(np.uint64)
        h ^= h >> np.uint64(32)
    if valid is not None:
        h = np.where(np.asarray(valid, dtype=bool), h, seeds)
    return h.view(np.int64)


# ---------------------------------------------------------------------------
# Row ⇄ columnar conversion (RowConversion analog)
# ---------------------------------------------------------------------------

def rows_to_columns(rows: np.ndarray, widths) -> Tuple[list, list]:
    """Unpacks tightly packed records (leading null bitmap + fixed-width
    fields) into per-column (uint8[n*w] data, uint8[n] validity)."""
    widths = np.asarray(widths, dtype=np.uint32)
    ncols = len(widths)
    bitmap = (ncols + 7) // 8
    row_size = bitmap + int(widths.sum())
    n = rows.size // row_size
    rows = np.ascontiguousarray(rows, dtype=np.uint8)
    datas = [np.empty(n * int(w), dtype=np.uint8) for w in widths]
    valids = [np.empty(n, dtype=np.uint8) for _ in widths]
    lib = get_lib()
    if lib is not None and n:
        dptr = (ctypes.c_void_p * ncols)(*[d.ctypes.data for d in datas])
        vptr = (ctypes.c_void_p * ncols)(*[v.ctypes.data for v in valids])
        lib.tpucol_rows_to_cols(
            _u8p(rows), n,
            widths.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)), ncols,
            dptr, vptr)
        return datas, valids
    rec = rows[:n * row_size].reshape(n, row_size)
    off = bitmap
    for c, w in enumerate(widths):
        w = int(w)
        datas[c][:] = rec[:, off:off + w].reshape(-1)
        valids[c][:] = (rec[:, c // 8] >> (c % 8)) & 1
        off += w
    return datas, valids


def columns_to_rows(datas, valids, widths) -> np.ndarray:
    """Packs per-column buffers into tight records (inverse of
    rows_to_columns)."""
    widths = np.asarray(widths, dtype=np.uint32)
    ncols = len(widths)
    bitmap = (ncols + 7) // 8
    row_size = bitmap + int(widths.sum())
    n = len(valids[0]) if valids and valids[0] is not None else \
        (datas[0].size // int(widths[0]))
    out = np.zeros(n * row_size, dtype=np.uint8)
    datas = [np.ascontiguousarray(d, dtype=np.uint8) for d in datas]
    valids = [None if v is None else np.ascontiguousarray(v, dtype=np.uint8)
              for v in valids]
    lib = get_lib()
    if lib is not None and n:
        ones = np.ones(n, dtype=np.uint8)
        dptr = (ctypes.c_void_p * ncols)(*[d.ctypes.data for d in datas])
        vptr = (ctypes.c_void_p * ncols)(
            *[(ones if v is None else v).ctypes.data for v in valids])
        lib.tpucol_cols_to_rows(
            _u8p(out), n,
            widths.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)), ncols,
            dptr, vptr)
        return out
    rec = out.reshape(n, row_size)
    off = bitmap
    for c, w in enumerate(widths):
        w = int(w)
        rec[:, off:off + w] = datas[c].reshape(n, w)
        v = valids[c]
        bit = np.uint8(1 << (c % 8))
        if v is None:
            rec[:, c // 8] |= bit
        else:
            rec[:, c // 8] |= np.where(v.astype(bool), bit, 0).astype(np.uint8)
        off += w
    return out


# ---------------------------------------------------------------------------
# Shuffle split hot loops
# ---------------------------------------------------------------------------

def partition_indices(pids: np.ndarray, n_parts: int
                      ) -> Tuple[np.ndarray, np.ndarray]:
    """Stable counting-sort of row indices by partition id.  Returns
    (offsets uint32[n_parts+1], indices uint32[n]): partition p's rows are
    ``indices[offsets[p]:offsets[p+1]]``."""
    pids = np.ascontiguousarray(pids, dtype=np.int32)
    n = pids.size
    lib = get_lib()
    if lib is not None:
        offsets = np.empty(n_parts + 1, dtype=np.uint32)
        indices = np.empty(n, dtype=np.uint32)
        rc = lib.tpucol_partition_indices(
            pids.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)), n, n_parts,
            offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)),
            indices.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)))
        if rc != 0:
            raise ValueError(f"partition id out of range [0, {n_parts})")
        return offsets, indices
    if n and (pids.min() < 0 or pids.max() >= n_parts):
        raise ValueError(f"partition id out of range [0, {n_parts})")
    order = np.argsort(pids, kind="stable").astype(np.uint32)
    counts = np.bincount(pids, minlength=n_parts).astype(np.uint32)
    offsets = np.zeros(n_parts + 1, dtype=np.uint32)
    np.cumsum(counts, out=offsets[1:])
    return offsets, order


def gather_fixed(src: np.ndarray, indices: np.ndarray, width: int
                 ) -> np.ndarray:
    """Gathers fixed-width elements by row index from a flat byte buffer."""
    indices = np.ascontiguousarray(indices, dtype=np.uint32)
    src = np.ascontiguousarray(src, dtype=np.uint8)
    n = indices.size
    lib = get_lib()
    if lib is not None:
        dst = np.empty(n * width, dtype=np.uint8)
        lib.tpucol_gather(_u8p(src),
                          indices.ctypes.data_as(
                              ctypes.POINTER(ctypes.c_uint32)),
                          n, width, _u8p(dst))
        return dst
    return src.reshape(-1, width)[indices].reshape(-1)
