"""Device batch kernels (gather/compact/concat/slice) shared by execs.

Counterpart of cuDF Table-level primitives the reference leans on
(SURVEY.md §2.16: gather maps, contiguous split/pack, concat) — here
implemented as jnp ops over padded batches so XLA owns scheduling/fusion.
"""

from spark_rapids_tpu.ops.batch_ops import (  # noqa: F401
    gather_batch, compact_batch, concat_batches, slice_batch, take_front)
