"""Segmented groupby kernels.

Reference: GpuAggregateExec.scala AggHelper (:175) pipelines cuDF hash
groupby.  TPU-first redesign: XLA has no hash tables but excels at sort +
segmented reductions — groupby = stable sort by keys (ops/sort_ops), detect
segment boundaries, ``jax.ops.segment_*`` with ``num_segments = bucket``
(static shape; group count is the only host sync).  The whole
sort+boundaries+N-reductions pipeline is one jitted program per
(shapes, spec) signature.

Reduction kinds (update & merge lower to the same set):
  sum, count, min, max, first, last, first_valid, last_valid, mean, m2,
  m2_cnt/m2_mean/m2_m2 (joint Chan-merge of variance partials)
"""

from __future__ import annotations

from typing import List, Sequence, Tuple

import numpy as np

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.batch import ColumnarBatch
from spark_rapids_tpu.columnar.column import DeviceColumn


def _jx():
    from spark_rapids_tpu.columnar.column import _jnp
    return _jnp()




def _col_sig(c: DeviceColumn) -> Tuple:
    return (str(c.data.dtype), tuple(c.data.shape), c.lengths is not None)


def _masked_group_words(col: DeviceColumn, jnp) -> List:
    """Words where equal-group rows compare equal: nulls grouped together
    (rank word) with data masked to 0 so null garbage doesn't split groups."""
    from spark_rapids_tpu.ops.sort_ops import sortable_words
    words = [col.validity.astype(np.int8)]
    for w in sortable_words(col, jnp):
        if w.ndim == 1:
            words.append(jnp.where(col.validity, w, jnp.zeros_like(w)))
        else:
            words.append(jnp.where(col.validity[:, None], w,
                                   jnp.zeros_like(w)))
    return words


def _segment_reduce(kind: str, x, valid, seg, inrow, bucket, jnp,
                    count_valid_only=True):
    """One reduction -> (data[bucket], valid[bucket]) per segment id."""
    import jax
    present = valid & inrow
    any_valid = jax.ops.segment_max(present.astype(np.int32), seg,
                                    num_segments=bucket) > 0
    if kind == "count":
        src = present if count_valid_only else inrow
        cnt = jax.ops.segment_sum(src.astype(np.int64), seg,
                                  num_segments=bucket)
        return cnt, jnp.ones(bucket, dtype=bool)
    if kind == "sum":
        if getattr(x, "ndim", 1) == 2:
            # decimal128 (hi, lo) limbs: mod-2^128 two's-complement sum.
            # 4x 32-bit limbs segment-summed in int64 lanes (limb < 2^32,
            # rows < 2^31 -> no lane overflow), then ONE carry
            # normalization; wrapped negatives add correctly mod 2^128.
            from spark_rapids_tpu.expressions.decimal_math import (
                _normalize, join128, split128)
            limbs = split128(x[:, 0], x[:, 1], jnp)
            limbs = [jnp.where(present, l, jnp.zeros_like(l))
                     for l in limbs]
            sums = [jax.ops.segment_sum(l, seg, num_segments=bucket)
                    for l in limbs]
            norm, _carry = _normalize(sums, jnp)
            hi_s, lo_s = join128(norm, jnp)
            return jnp.stack([hi_s, lo_s], axis=1), any_valid
        z = jnp.where(present, x, jnp.zeros_like(x))
        return jax.ops.segment_sum(z, seg, num_segments=bucket), any_valid
    if kind in ("min", "max"):
        if jnp.issubdtype(x.dtype, jnp.inexact):
            # Spark: NaN > every double.  min skips NaN (unless the group
            # is all-NaN); max yields NaN when any present.  Explicit, not
            # left to backend NaN propagation (XLA CPU and TPU differ).
            ident = jnp.asarray(np.inf if kind == "min" else -np.inf, x.dtype)
            nanrow = present & jnp.isnan(x)
            z = jnp.where(present & ~jnp.isnan(x), x, ident)
            f = jax.ops.segment_min if kind == "min" else jax.ops.segment_max
            red = f(z, seg, num_segments=bucket)
            has_nan = jax.ops.segment_max(nanrow.astype(np.int32), seg,
                                          num_segments=bucket) > 0
            if kind == "max":
                red = jnp.where(has_nan, jnp.asarray(np.nan, x.dtype), red)
            else:
                has_num = jax.ops.segment_max(
                    (present & ~jnp.isnan(x)).astype(np.int32), seg,
                    num_segments=bucket) > 0
                red = jnp.where(has_nan & ~has_num,
                                jnp.asarray(np.nan, x.dtype), red)
            return red, any_valid
        info = jnp.iinfo(x.dtype)
        ident = jnp.asarray(info.max if kind == "min" else info.min,
                            x.dtype)
        z = jnp.where(present, x, ident)
        f = jax.ops.segment_min if kind == "min" else jax.ops.segment_max
        return f(z, seg, num_segments=bucket), any_valid
    if kind in ("first", "last", "first_valid", "last_valid"):
        want_valid = kind.endswith("_valid")
        cond = present if want_valid else inrow
        pos = jnp.arange(x.shape[0], dtype=np.int64)
        if kind.startswith("first"):
            p = jnp.where(cond, pos, x.shape[0])
            idx = jax.ops.segment_min(p, seg, num_segments=bucket)
            found = idx < x.shape[0]
        else:
            p = jnp.where(cond, pos, -1)
            idx = jax.ops.segment_max(p, seg, num_segments=bucket)
            found = idx >= 0
        safe = jnp.clip(idx, 0, x.shape[0] - 1)
        data = jnp.take(x, safe, axis=0)
        v = found & jnp.take(valid, safe, axis=0)
        return data, v
    if kind == "mean":
        z = jnp.where(present, x, jnp.zeros_like(x))
        s = jax.ops.segment_sum(z, seg, num_segments=bucket)
        n = jax.ops.segment_sum(present.astype(x.dtype), seg,
                                num_segments=bucket)
        return jnp.where(n > 0, s / jnp.where(n > 0, n, 1), 0.0), any_valid
    raise ValueError(f"unknown reduction kind {kind!r}")


def _lengths_reduce(kind, col, valid, seg, inrow, bucket, jnp):
    """first/last variants for string columns carry data+lengths."""
    import jax
    want_valid = kind.endswith("_valid")
    present = col.validity & inrow
    cond = present if want_valid else inrow
    pos = jnp.arange(col.data.shape[0], dtype=np.int64)
    if kind.startswith("first"):
        p = jnp.where(cond, pos, col.data.shape[0])
        idx = jax.ops.segment_min(p, seg, num_segments=bucket)
        found = idx < col.data.shape[0]
    else:
        p = jnp.where(cond, pos, -1)
        idx = jax.ops.segment_max(p, seg, num_segments=bucket)
        found = idx >= 0
    safe = jnp.clip(idx, 0, col.data.shape[0] - 1)
    data = jnp.take(col.data, safe, axis=0)
    lens = jnp.take(col.lengths, safe, axis=0)
    v = found & jnp.take(col.validity, safe, axis=0)
    return data, v, lens


_GLOBAL_OUT_BUCKET = 8


def _global_reduce(kind: str, x, valid, inrow, jnp, count_valid_only=True):
    """Whole-array reduction -> (scalar, scalar_valid).  The global-agg
    analog of _segment_reduce: plain jnp reductions instead of segment ops
    (segment_* with num_segments=bucket costs ~80ms/call on v5e; jnp.sum
    costs ~1ms)."""
    present = valid & inrow
    any_valid = jnp.any(present)
    if kind == "count":
        src = present if count_valid_only else inrow
        return jnp.sum(src.astype(np.int64)), jnp.asarray(True)
    if kind == "sum":
        if getattr(x, "ndim", 1) == 2:
            # decimal128 limbs: see _segment_reduce's 4x32-bit scheme
            from spark_rapids_tpu.expressions.decimal_math import (
                _normalize, join128, split128)
            limbs = split128(x[:, 0], x[:, 1], jnp)
            sums = [jnp.sum(jnp.where(present, l, jnp.zeros_like(l)))
                    for l in limbs]
            norm, _carry = _normalize(sums, jnp)
            hi_s, lo_s = join128(norm, jnp)
            return jnp.stack([hi_s, lo_s]), any_valid
        return jnp.sum(jnp.where(present, x, jnp.zeros_like(x))), any_valid
    if kind in ("min", "max"):
        if jnp.issubdtype(x.dtype, jnp.inexact):
            # Spark NaN-greatest semantics, explicit (see _segment_reduce)
            ident = jnp.asarray(np.inf if kind == "min" else -np.inf, x.dtype)
            nanrow = present & jnp.isnan(x)
            z = jnp.where(present & ~jnp.isnan(x), x, ident)
            red = jnp.min(z) if kind == "min" else jnp.max(z)
            has_nan = jnp.any(nanrow)
            if kind == "max":
                red = jnp.where(has_nan, jnp.asarray(np.nan, x.dtype), red)
            else:
                has_num = jnp.any(present & ~jnp.isnan(x))
                red = jnp.where(has_nan & ~has_num,
                                jnp.asarray(np.nan, x.dtype), red)
            return red, any_valid
        info = jnp.iinfo(x.dtype)
        ident = jnp.asarray(info.max if kind == "min" else info.min,
                            x.dtype)
        z = jnp.where(present, x, ident)
        return (jnp.min(z) if kind == "min" else jnp.max(z)), any_valid
    if kind in ("first", "last", "first_valid", "last_valid"):
        want_valid = kind.endswith("_valid")
        cond = present if want_valid else inrow
        n = x.shape[0]
        pos = jnp.arange(n, dtype=np.int64)
        if kind.startswith("first"):
            idx = jnp.min(jnp.where(cond, pos, n))
            found = idx < n
        else:
            idx = jnp.max(jnp.where(cond, pos, -1))
            found = idx >= 0
        safe = jnp.clip(idx, 0, n - 1)
        return x[safe], found & valid[safe]
    if kind == "mean":
        z = jnp.where(present, x, jnp.zeros_like(x))
        s = jnp.sum(z)
        cnt = jnp.sum(present.astype(x.dtype))
        return jnp.where(cnt > 0, s / jnp.where(cnt > 0, cnt, 1), 0.0), \
            any_valid
    raise ValueError(f"unknown reduction kind {kind!r}")


def _global_aggregate(batch: ColumnarBatch,
                      specs: Sequence[Tuple[int, str, bool, T.DataType]],
                      ) -> ColumnarBatch:
    """num_keys == 0: no sort, no segments; output planes are tiny
    (bucket 8) so downstream merge/final passes and the result download
    never touch input-sized buffers."""
    jnp = _jx()
    bucket = batch.bucket
    spec_key = tuple((o, k, cv, str(dt)) for o, k, cv, dt in specs)
    key = ("globalagg", tuple(_col_sig(c) for c in batch.columns), spec_key)
    def build():
        dtypes = [c.data_type for c in batch.columns]

        def run(arrs, row_count):
            cols = [DeviceColumn(d, v, bucket, dtypes[i], ln)
                    for i, (d, v, ln) in enumerate(arrs)]
            sel = jnp.arange(bucket, dtype=np.int32) < row_count
            return global_agg_trace(cols, sel, specs, jnp)

        return run
    from spark_rapids_tpu.exec.stage_compiler import get_or_build
    fn = get_or_build("agg.global", key, build)
    from spark_rapids_tpu.columnar.column import rc_traceable
    arrs = [(c.data, c.validity, c.lengths) for c in batch.columns]
    outs = fn(arrs, rc_traceable(batch.row_count))
    names = [f"a{j}" for j in range(len(specs))]
    cols = []
    for j, (d, v, ln) in enumerate(outs):
        dt = specs[j][3]
        if ln is None and dt.np_dtype is not None and \
                d.dtype != np.dtype(dt.np_dtype):
            d = d.astype(dt.np_dtype)
        cols.append(DeviceColumn(d, v, 1, dt, ln))
    return ColumnarBatch(cols, 1, names)


def global_agg_trace(cols, sel, specs, jnp):
    """Traceable global-agg update/merge pass over (cols, selection mask):
    returns [(data, valid, lengths)] 8-row planes, value in row 0.  Called
    by _global_aggregate and by the whole-stage fuser (exec/fused.py)."""
    inrow = sel

    def slot(val, ok, width=None):
        """scalar -> 8-row plane with the value at row 0."""
        if width is None:
            d = jnp.zeros(_GLOBAL_OUT_BUCKET, dtype=val.dtype).at[0].set(val)
        else:
            d = jnp.zeros((_GLOBAL_OUT_BUCKET, width),
                          dtype=val.dtype).at[0].set(val)
        v = jnp.zeros(_GLOBAL_OUT_BUCKET, dtype=bool).at[0].set(ok)
        return d, v

    outs = []
    i = 0
    while i < len(specs):
        o, kind, cvo, _dt = specs[i]
        c = cols[o]
        if kind == "m2_cnt":
            oc, om, o2 = specs[i][0], specs[i + 1][0], specs[i + 2][0]
            cnt_c, mean_c, m2_c = cols[oc], cols[om], cols[o2]
            pres = cnt_c.validity & inrow
            n_i = jnp.where(pres, cnt_c.data, 0.0)
            mu_i = jnp.where(pres, mean_c.data, 0.0)
            m2_i = jnp.where(pres, m2_c.data, 0.0)
            tot = jnp.sum(n_i)
            wsum = jnp.sum(n_i * mu_i)
            mu = jnp.where(tot > 0, wsum / jnp.where(tot > 0, tot, 1), 0.0)
            dev = mu_i - mu
            m2 = jnp.sum(m2_i + n_i * dev * dev)
            ok = jnp.asarray(True)
            for val in (tot, mu, m2):
                d, v = slot(val, ok)
                outs.append((d, v, None))
            i += 3
            continue
        if kind == "m2":
            x = c.data
            pres = c.validity & inrow
            z = jnp.where(pres, x, 0.0)
            cnt = jnp.sum(pres.astype(x.dtype))
            s = jnp.sum(z)
            mu = jnp.where(cnt > 0, s / jnp.where(cnt > 0, cnt, 1), 0.0)
            dctr = jnp.where(pres, x - mu, 0.0)
            d, v = slot(jnp.sum(dctr * dctr), jnp.asarray(True))
            outs.append((d, v, None))
            i += 1
            continue
        if c.lengths is not None and kind != "count":
            # first/last over strings: pick the row, carry lengths
            want_valid = kind.endswith("_valid")
            pres = c.validity & inrow
            cond = pres if want_valid else inrow
            nn = c.data.shape[0]
            pos = jnp.arange(nn, dtype=np.int64)
            if kind.startswith("first"):
                idx = jnp.min(jnp.where(cond, pos, nn))
                found = idx < nn
            else:
                idx = jnp.max(jnp.where(cond, pos, -1))
                found = idx >= 0
            safe = jnp.clip(idx, 0, nn - 1)
            d, v = slot(c.data[safe], found & c.validity[safe],
                        width=c.data.shape[1])
            ln = jnp.zeros(_GLOBAL_OUT_BUCKET,
                           dtype=c.lengths.dtype).at[0].set(c.lengths[safe])
            outs.append((d, v, ln))
        else:
            val, ok = _global_reduce(kind, c.data, c.validity, inrow, jnp,
                                     count_valid_only=cvo)
            # decimal128 sums return a (hi, lo) pair -> 2-wide plane
            width = val.shape[0] if getattr(val, "ndim", 0) == 1 else None
            d, v = slot(val, ok, width=width)
            outs.append((d, v, None))
        i += 1
    return outs


def segmented_aggregate(batch: ColumnarBatch, num_keys: int,
                        specs: Sequence[Tuple[int, str, bool, T.DataType]],
                        ) -> ColumnarBatch:
    """Groups ``batch`` by its first ``num_keys`` columns and reduces the
    remaining columns per ``specs``: (value_ordinal, kind, count_valid_only,
    out_dtype).  Returns keys+results, one row per group.

    The full pipeline (sort, boundaries, reductions) is one jit per
    signature; only the group count syncs to host.
    """
    jnp = _jx()
    from spark_rapids_tpu.ops.sort_ops import SortOrder, sortable_words
    if num_keys == 0:
        return _global_aggregate(batch, specs)
    bucket = batch.bucket
    spec_key = tuple((o, k, cv, str(dt)) for o, k, cv, dt in specs)
    key = ("segagg", tuple(_col_sig(c) for c in batch.columns), num_keys,
           spec_key)
    def build():
        # capture only scalars/types, never the batch (module-cache pinning)
        dtypes = [c.data_type for c in batch.columns]

        def run(arrs, row_count):
            cols = [DeviceColumn(d, v, bucket, dtypes[i], ln)
                    for i, (d, v, ln) in enumerate(arrs)]
            sel = jnp.arange(bucket, dtype=np.int32) < row_count
            return keyed_agg_trace(cols, sel, num_keys, specs, bucket, jnp)

        return run
    from spark_rapids_tpu.exec.stage_compiler import get_or_build
    fn = get_or_build("agg.segmented", key, build)
    from spark_rapids_tpu.columnar.column import DeferredCount, rc_traceable
    arrs = [(c.data, c.validity, c.lengths) for c in batch.columns]
    outs, ng = fn(arrs, rc_traceable(batch.row_count))
    n = DeferredCount(ng)      # group count stays on device
    names = (batch.names or [f"c{i}" for i in range(batch.num_columns)])
    out_names = names[:num_keys] + [f"a{j}" for j in range(len(specs))]
    cols = []
    for j, (d, v, ln) in enumerate(outs):
        if j < num_keys:
            dt = batch.columns[j].data_type
        else:
            dt = specs[j - num_keys][3]
            if ln is None and dt.np_dtype is not None and \
                    d.dtype != np.dtype(dt.np_dtype):
                d = d.astype(dt.np_dtype)
        cols.append(DeviceColumn(d, v, n, dt, ln))
    return ColumnarBatch(cols, n, out_names)


def keyed_agg_trace(cols, sel, num_keys, specs, bucket, jnp):
    """Traceable keyed groupby pass over (cols, selection mask): sort by
    keys, detect segments, reduce.  Returns ([(data, valid, lengths)],
    num_groups).  Called by segmented_aggregate and the whole-stage fuser."""
    import jax
    from spark_rapids_tpu.ops.sort_ops import SortOrder, _order_words
    orders = [SortOrder(i, True, True) for i in range(num_keys)]
    rowpos = jnp.arange(bucket, dtype=np.int32)
    inrow = sel
    row_count = jnp.sum(sel)  # selected rows sort to the front
    # ---- sort by keys (padding last); every 1-D plane rides the
    # sort as an operand (gathers cost ~40ms/col/M on v5e, sort
    # operands are near-free) ----
    words = [(~inrow).astype(np.int8)]
    for o in orders:
        words.extend(_order_words(cols[o.ordinal], o, jnp))
    flat_planes = []
    twod_planes = []
    for c in cols:
        (flat_planes if c.data.ndim == 1 else
         twod_planes).append(c.data)
        flat_planes.append(c.validity)
        if c.lengths is not None:
            flat_planes.append(c.lengths)
    operands = tuple(words) + (rowpos,) + tuple(flat_planes)
    sorted_ops = jax.lax.sort(operands, num_keys=len(words),
                              is_stable=True)
    perm = sorted_ops[len(words)]
    flat_sorted = list(sorted_ops[len(words) + 1:])
    twod_sorted = [jnp.take(p, perm, axis=0) for p in twod_planes]
    scols = []
    fi = ti = 0
    for c in cols:
        if c.data.ndim == 1:
            d = flat_sorted[fi]
            fi += 1
        else:
            d = twod_sorted[ti]
            ti += 1
        v = flat_sorted[fi]
        fi += 1
        ln = None
        if c.lengths is not None:
            ln = flat_sorted[fi]
            fi += 1
        scols.append(DeviceColumn(d, v, bucket, c.data_type, ln))
    inrow_s = jnp.take(inrow, perm, axis=0)  # still a prefix
    # ---- segment boundaries over masked key words ----
    boundary = jnp.zeros(bucket, dtype=bool).at[0].set(True)
    for kcol in scols[:num_keys]:
        for w in _masked_group_words(kcol, jnp):
            if w.ndim == 1:
                diff = w[1:] != w[:-1]
            else:
                diff = jnp.any(w[1:] != w[:-1], axis=-1)
            boundary = boundary.at[1:].max(diff)
    # first padding row opens its own (discarded) segment
    boundary = boundary | (rowpos == row_count)
    seg = jnp.cumsum(boundary.astype(np.int32)) - 1
    num_groups = jnp.max(jnp.where(inrow_s, seg, -1)) + 1
    # ---- unique keys: value at each segment's first row ----
    outs = []
    first_pos = jax.ops.segment_min(
        jnp.where(inrow_s, rowpos.astype(np.int64), bucket), seg,
        num_segments=bucket)
    safe_first = jnp.clip(first_pos, 0, bucket - 1)
    gvalid = jnp.arange(bucket) < num_groups
    for kcol in scols[:num_keys]:
        d = jnp.take(kcol.data, safe_first, axis=0)
        v = jnp.take(kcol.validity, safe_first, axis=0) & gvalid
        ln = None if kcol.lengths is None else \
            jnp.take(kcol.lengths, safe_first, axis=0)
        outs.append((d, v, ln))
    # ---- reductions ----
    i = 0
    while i < len(specs):
        o, kind, cvo, _dt = specs[i]
        c = scols[o]
        if kind == "m2_cnt":
            # joint Chan merge over partial (cnt, mean, m2) triples
            oc, om, o2 = specs[i][0], specs[i + 1][0], specs[i + 2][0]
            cnt_c, mean_c, m2_c = scols[oc], scols[om], scols[o2]
            pres = cnt_c.validity & inrow_s
            n_i = jnp.where(pres, cnt_c.data, 0.0)
            mu_i = jnp.where(pres, mean_c.data, 0.0)
            m2_i = jnp.where(pres, m2_c.data, 0.0)
            tot = jax.ops.segment_sum(n_i, seg, num_segments=bucket)
            wsum = jax.ops.segment_sum(n_i * mu_i, seg,
                                       num_segments=bucket)
            mu = jnp.where(tot > 0, wsum / jnp.where(tot > 0, tot, 1),
                           0.0)
            dev = mu_i - jnp.take(mu, seg)
            m2 = jax.ops.segment_sum(m2_i + n_i * dev * dev, seg,
                                     num_segments=bucket)
            ok = jnp.ones(bucket, dtype=bool)
            outs.append((tot, ok, None))
            outs.append((mu, ok, None))
            outs.append((m2, ok, None))
            i += 3
            continue
        if kind == "m2":
            # update: needs this input's per-segment mean first
            x = c.data
            pres = c.validity & inrow_s
            z = jnp.where(pres, x, 0.0)
            n = jax.ops.segment_sum(pres.astype(x.dtype), seg,
                                    num_segments=bucket)
            s = jax.ops.segment_sum(z, seg, num_segments=bucket)
            mu = jnp.where(n > 0, s / jnp.where(n > 0, n, 1), 0.0)
            d = jnp.where(pres, x - jnp.take(mu, seg), 0.0)
            m2 = jax.ops.segment_sum(d * d, seg, num_segments=bucket)
            outs.append((m2, jnp.ones(bucket, dtype=bool), None))
            i += 1
            continue
        if c.lengths is not None and kind != "count":
            d, v, ln = _lengths_reduce(kind, c, c.validity, seg,
                                       inrow_s, bucket, jnp)
            outs.append((d, v, ln))
        else:
            d, v = _segment_reduce(kind, c.data, c.validity, seg,
                                   inrow_s, bucket, jnp,
                                   count_valid_only=cvo)
            outs.append((d, v, None))
        i += 1
    # mask group-slot padding in-trace (eager masking would cost one
    # tunnel dispatch per output column)
    gv = jnp.arange(bucket) < num_groups
    outs = [(d, v & gv, ln) for (d, v, ln) in outs]
    return outs, num_groups


# ---------------------------------------------------------------------------
# device collect_list / collect_set (reference: aggregateFunctions.scala
# collect ops over cuDF lists; TPU-first reformulation = stable sort by
# keys [+ value for sets], segment boundaries, scatter into a padded
# [group, max_len] plane)
# ---------------------------------------------------------------------------



def segmented_collect_many(batch: ColumnarBatch, num_keys: int,
                           slots):
    """Collects several value columns per group into device array
    columns: ``slots`` = [(value_ordinal, distinct)], returns one
    keys+array ColumnarBatch per slot, all sharing segmented_aggregate's
    group order.

    Null values are skipped (Spark collect semantics); ``distinct``
    dedupes by sorting (key, value) and keeping first occurrences — set
    ORDER is value-sorted, which Spark leaves unspecified.

    Sync discipline: ONE host fetch total for every slot's max group
    length (stacked — a fetch per slot would cost ~185ms each on a
    tunnel-attached chip); group counts stay deferred."""
    phase1 = [_collect_phase1(batch, num_keys, o, d) for o, d in slots]
    maxws = np.asarray(_jx().stack([p[6] for p in phase1]))  # the one sync
    return [_collect_phase2(batch, num_keys, o, p, int(w))
            for (o, _d), p, w in zip(slots, phase1, maxws)]


def _collect_phase1(batch: ColumnarBatch, num_keys: int, value_ord: int,
                    distinct: bool):
    import jax
    from spark_rapids_tpu.columnar.column import rc_traceable
    from spark_rapids_tpu.ops.sort_ops import SortOrder, _order_words
    jnp = _jx()
    bucket = batch.bucket
    sig = ("collect1", tuple(_col_sig(c) for c in batch.columns), num_keys,
           value_ord, distinct)
    def build():
        dtypes = [c.data_type for c in batch.columns]

        def phase1(arrs, row_count):
            cols = [DeviceColumn(d, v, bucket, dtypes[i], ln)
                    for i, (d, v, ln) in enumerate(arrs)]
            rowpos = jnp.arange(bucket, dtype=np.int32)
            inrow = rowpos < row_count
            orders = [SortOrder(i, True, True) for i in range(num_keys)]
            words = [(~inrow).astype(np.int8)]
            for o in orders:
                words.extend(_order_words(cols[o.ordinal], o, jnp))
            n_keywords = len(words)
            if distinct:
                words.extend(_order_words(
                    cols[value_ord], SortOrder(value_ord, True, True), jnp))
            flat = []
            for c in cols:
                flat.append(c.data)
                flat.append(c.validity)
                if c.lengths is not None:
                    flat.append(c.lengths)
            sorted_ops = jax.lax.sort(tuple(words) + (rowpos,) + tuple(flat),
                                      num_keys=len(words), is_stable=True)
            perm = sorted_ops[len(words)]
            flat_s = list(sorted_ops[len(words) + 1:])
            scols = []
            fi = 0
            for c in cols:
                d = flat_s[fi]; fi += 1
                v = flat_s[fi]; fi += 1
                ln = None
                if c.lengths is not None:
                    ln = flat_s[fi]; fi += 1
                scols.append(DeviceColumn(d, v, bucket, c.data_type, ln))
            inrow_s = jnp.take(inrow, perm, axis=0)
            # group boundaries on KEY words only
            boundary = jnp.zeros(bucket, dtype=bool).at[0].set(True)
            for kcol in scols[:num_keys]:
                for w in _masked_group_words(kcol, jnp):
                    diff = (w[1:] != w[:-1]) if w.ndim == 1 else \
                        jnp.any(w[1:] != w[:-1], axis=-1)
                    boundary = boundary.at[1:].max(diff)
            boundary = boundary | (rowpos == row_count)
            seg = jnp.cumsum(boundary.astype(np.int32)) - 1
            num_groups = jnp.max(jnp.where(inrow_s, seg, -1)) + 1
            sval = scols[value_ord]
            kept = inrow_s & sval.validity
            if distinct:
                first = boundary.copy()
                for w in _masked_group_words(sval, jnp):
                    diff = (w[1:] != w[:-1]) if w.ndim == 1 else \
                        jnp.any(w[1:] != w[:-1], axis=-1)
                    first = first.at[1:].max(diff)
                kept = kept & first
            # position within the group counting only kept rows
            ck = jnp.cumsum(kept.astype(np.int64))
            base = jax.ops.segment_min(
                jnp.where(inrow_s, ck - kept, 1 << 62), seg,
                num_segments=bucket)
            pos = ck - 1 - jnp.take(base, seg)
            lengths = jax.ops.segment_sum(kept.astype(np.int32), seg,
                                          num_segments=bucket)
            maxw = jnp.max(lengths)
            # group key rows (same rule as keyed_agg_trace)
            first_pos = jax.ops.segment_min(
                jnp.where(inrow_s, rowpos.astype(np.int64), bucket), seg,
                num_segments=bucket)
            key_outs = []
            safe_first = jnp.clip(first_pos, 0, bucket - 1)
            gvalid = jnp.arange(bucket) < num_groups
            for kcol in scols[:num_keys]:
                d = jnp.take(kcol.data, safe_first, axis=0)
                v = jnp.take(kcol.validity, safe_first, axis=0) & gvalid
                ln = None if kcol.lengths is None else \
                    jnp.take(kcol.lengths, safe_first, axis=0)
                key_outs.append((d, v, ln))
            return (sval.data, kept, seg, pos, lengths, num_groups, maxw,
                    key_outs)

        return phase1
    from spark_rapids_tpu.exec.stage_compiler import get_or_build
    fn = get_or_build("agg.collect_phase1", sig, build)
    arrs = [(c.data, c.validity, c.lengths) for c in batch.columns]
    return fn(arrs, rc_traceable(batch.row_count))


def _collect_phase2(batch: ColumnarBatch, num_keys: int, value_ord: int,
                    p1, maxw: int):
    from spark_rapids_tpu.columnar.column import (DeferredCount,
                                                  bucket_strlen)
    jnp = _jx()
    bucket = batch.bucket
    vcol = batch.columns[value_ord]
    (svals, kept, seg, pos, lengths, ng, _maxw_d, key_outs) = p1
    W = bucket_strlen(max(maxw, 1))
    sig2 = ("collect2", bucket, W, str(svals.dtype))
    def build():
        def phase2(svals, kept, seg, pos, lengths, ng):
            plane = jnp.zeros((bucket, W), dtype=svals.dtype)
            dest_g = jnp.where(kept, seg.astype(np.int64), bucket)
            dest_p = jnp.clip(pos, 0, W - 1)
            plane = plane.at[(dest_g, dest_p)].set(svals, mode="drop")
            ev = jnp.arange(W)[None, :] < lengths[:, None]
            gvalid = jnp.arange(bucket) < ng
            return plane, ev, gvalid

        return phase2
    from spark_rapids_tpu.exec.stage_compiler import get_or_build
    fn2 = get_or_build("agg.collect_phase2", sig2, build)
    plane, ev, gvalid = fn2(svals, kept, seg, pos, lengths, ng)
    n = DeferredCount(ng)
    arr_col = DeviceColumn(plane, gvalid, n,
                           T.ArrayType(vcol.data_type, contains_null=False),
                           lengths=lengths.astype(np.int32),
                           elem_valid=ev)
    cols = []
    names = (batch.names or [f"c{i}" for i in range(batch.num_columns)])
    for j, (d, v, ln) in enumerate(key_outs):
        cols.append(DeviceColumn(d, v, n, batch.columns[j].data_type, ln))
    cols.append(arr_col)
    return ColumnarBatch(cols, n, names[:num_keys] + ["collected"])
