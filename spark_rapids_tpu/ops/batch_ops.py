"""Batch-level device kernels.

Key TPU-first decisions:
- ``compact_batch`` implements filtering as a stable argsort on the keep
  mask + gather — dynamic-shape-free, so the same compiled program serves
  every batch; only the resulting row COUNT syncs to host (one scalar).
  (cuDF's apply_boolean_mask materializes a shorter column; XLA wants the
  static shape kept and the logical length tracked separately.)
- ``concat_batches`` re-packs several padded batches into one bigger padded
  bucket with a single jit'ed copy per (shapes, bucket) signature.
"""

from __future__ import annotations

from typing import List, Sequence, Tuple

import numpy as np

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.batch import ColumnarBatch
from spark_rapids_tpu.columnar.column import (DeferredCount, DeviceColumn,
                                              bucket_rows, rc_traceable,
                                              sum_counts)


def _jx():
    from spark_rapids_tpu.columnar.column import _jnp
    return _jnp()


#: deferred-concat padding guard (ADVICE r5): above this summed padded
#: input footprint, ``concat_batches`` forces the counts (one batched
#: sync) and sizes the output from live rows instead of next-pow2 of the
#: summed padded buckets
CONCAT_FORCE_SYNC_BYTES = 64 << 20




def _col_sig(c: DeviceColumn) -> Tuple:
    return (str(c.data.dtype), tuple(c.data.shape), c.lengths is not None,
            c.elem_valid is not None)


def gather_batch(batch: ColumnarBatch, idx, row_count: int,
                 idx_valid=None) -> ColumnarBatch:
    """Gathers rows by index (device gather-map application; reference:
    cuDF Table.gather via JoinGatherer).  ``idx`` may exceed row bounds for
    padding positions; callers pass ``idx_valid`` to invalidate those rows.
    Dictionary columns gather their code planes (encoding survives);
    RLE columns are run-shaped and materialize first."""
    from spark_rapids_tpu.columnar.encoding import (materialize_rle_batch,
                                                    rewrap_like)
    batch = materialize_rle_batch(batch)
    jnp = _jx()
    out = []
    n = idx.shape[0]
    safe = jnp.clip(idx, 0, batch.bucket - 1)
    for c in batch.columns:
        data = jnp.take(c.data, safe, axis=0)
        valid = jnp.take(c.validity, safe, axis=0)
        if idx_valid is not None:
            valid = valid & idx_valid
        lengths = None if c.lengths is None else jnp.take(c.lengths, safe, axis=0)
        ev = None if c.elem_valid is None else jnp.take(c.elem_valid, safe,
                                                        axis=0)
        out.append(rewrap_like(c, data, valid, row_count, lengths, ev))
    return ColumnarBatch(out, row_count, batch.names)


def compact_batch(batch: ColumnarBatch, keep) -> ColumnarBatch:
    """Moves kept rows to the front (stable), returns batch with new count.
    Dictionary code planes compact like any int plane (the encoding
    survives — late materialization); RLE materializes first.

    No host sync: the count stays deferred on device.  Implementation is a
    single multi-operand ``lax.sort`` keyed on the drop flag: TPU sorts are
    heavily optimized (measured ~11x faster than the cumsum+scatter
    compaction and ~3x faster than argsort+gather on v5e for a 3-column 1M
    batch), and every 1-D plane rides the one sort as an operand.  2-D
    planes (strings/arrays/decimal128) are gathered by the sorted row
    permutation.
    """
    import jax
    from spark_rapids_tpu.columnar.encoding import materialize_rle_batch
    batch = materialize_rle_batch(batch)
    jnp = _jx()
    key = ("compact", tuple(_col_sig(c) for c in batch.columns))
    def build():
        def run(arrs, keep):
            n = keep.shape[0]
            cnt = jnp.sum(keep)
            live = jnp.arange(n) < cnt
            # one stable sort carries every 1-D plane; 2-D planes gather by
            # the permutation (rowpos operand)
            flat: List = []
            twod: List = []
            for d, v, ln, ev in arrs:
                (flat if d.ndim == 1 else twod).append(d)
                flat.append(v)
                if ln is not None:
                    flat.append(ln)
                if ev is not None:
                    twod.append(ev)
            rowpos = jnp.arange(n, dtype=np.int32)
            operands = ((~keep).astype(np.int8), rowpos) + tuple(flat)
            sorted_ops = jax.lax.sort(operands, num_keys=1, is_stable=True)
            perm = sorted_ops[1]
            flat_sorted = list(sorted_ops[2:])
            twod_sorted = [jnp.take(p, perm, axis=0) for p in twod]
            fi = ti = 0
            outs = []
            for d, v, ln, ev in arrs:
                if d.ndim == 1:
                    nd = flat_sorted[fi]
                    fi += 1
                else:
                    nd = twod_sorted[ti]
                    ti += 1
                nv = flat_sorted[fi] & live
                fi += 1
                nl = None
                if ln is not None:
                    nl = flat_sorted[fi]
                    fi += 1
                ne = None
                if ev is not None:
                    ne = twod_sorted[ti]
                    ti += 1
                outs.append((nd, nv, nl, ne))
            return outs, cnt

        return run
    from spark_rapids_tpu.exec.stage_compiler import get_or_build
    fn = get_or_build("batch.compact", key, build)
    arrs = [(c.data, c.validity, c.lengths, c.elem_valid)
            for c in batch.columns]
    outs, cnt = fn(arrs, keep)
    # count stays on device: chained kernels consume it sync-free
    row_count = DeferredCount(cnt)
    from spark_rapids_tpu.columnar.encoding import rewrap_like
    cols = [rewrap_like(c, d, v, row_count, ln, ne)
            for (d, v, ln, ne), c in zip(outs, batch.columns)]
    return ColumnarBatch(cols, row_count, batch.names)


def shrink_batch(batch: ColumnarBatch, minimum: int = 1024) -> ColumnarBatch:
    """Re-buckets a batch whose logical rows are far fewer than its bucket
    (e.g. aggregate output, post-filter shuffle input) by slicing every
    plane to the next power of two >= row_count.  Forces the deferred count
    (one sync) — call only at materialization boundaries (shuffle write,
    spill) where the count is needed anyway."""
    n = int(batch.row_count)
    target = bucket_rows(max(n, 1), minimum=minimum)
    if not batch.columns or target >= batch.bucket:
        return batch
    from spark_rapids_tpu.columnar.encoding import (materialize_rle_batch,
                                                    rewrap_like)
    batch = materialize_rle_batch(batch)
    cols = []
    for c in batch.columns:
        cols.append(rewrap_like(
            c, c.data[:target], c.validity[:target], n,
            None if c.lengths is None else c.lengths[:target],
            None if c.elem_valid is None else c.elem_valid[:target]))
    return ColumnarBatch(cols, n, batch.names)


def slice_batch(batch: ColumnarBatch, start: int, length: int) -> ColumnarBatch:
    """Logical slice via gather (static shapes preserved)."""
    jnp = _jx()
    idx = jnp.arange(batch.bucket) + start
    valid_rows = jnp.arange(batch.bucket) < length
    return gather_batch(batch, idx, length, idx_valid=valid_rows)


def take_front(batch: ColumnarBatch, n) -> ColumnarBatch:
    """First n rows (limit); no data movement, just count + validity mask.
    ``n`` may itself be deferred/a device scalar (limit budget carried on
    device across batches — no per-batch sync)."""
    jnp = _jx()
    from spark_rapids_tpu.columnar.encoding import (materialize_rle_batch,
                                                    rewrap_like)
    batch = materialize_rle_batch(batch)
    rc = batch.row_count
    n_deferred = isinstance(n, DeferredCount) or not isinstance(n, int)
    if n_deferred or (isinstance(rc, DeferredCount) and not rc.is_forced):
        from spark_rapids_tpu.columnar.column import rc_traceable
        n_t = jnp.minimum(jnp.asarray(rc_traceable(n)),
                          jnp.asarray(rc_traceable(rc)))
        n = DeferredCount(n_t)
    else:
        n = min(int(n), int(rc))
        n_t = n
    keep = jnp.arange(batch.bucket) < n_t
    cols = [rewrap_like(c, c.data, c.validity & keep, n, c.lengths,
                        c.elem_valid)
            for c in batch.columns]
    return ColumnarBatch(cols, n, batch.names)


def _committed_device(b: ColumnarBatch):
    """The single device a batch's planes are committed to, or None for
    uncommitted/empty batches."""
    for c in b.columns:
        devices = getattr(c.data, "devices", None)
        if callable(devices):
            try:
                ds = list(devices())
            except Exception:  # noqa: BLE001 - best-effort placement probe
                return None
            if len(ds) == 1:
                return ds[0]
    return None


def _align_batch_devices(batches: Sequence[ColumnarBatch]
                         ) -> Sequence[ColumnarBatch]:
    """Moves batches committed to DIFFERENT devices onto one device
    before they meet in a single program (jax refuses cross-device
    inputs).  Mesh execution makes this real: a shard-local pipeline
    keeps each partition's batches on its own device, but partition
    merges (coalesced AQE reads above a host-staged exchange fed by
    mesh shards, out-of-core agg merges) legitimately combine shards —
    that transfer rides ICI on real hardware."""
    devs = {id(d): d for d in (_committed_device(b) for b in batches)
            if d is not None}
    if len(devs) <= 1:
        return batches
    import jax
    from spark_rapids_tpu.columnar.encoding import materialize_batch
    target = next(iter(devs.values()))

    moved_counts: dict = {}

    def move_count(rc):
        # unforced deferred counts are 0-d arrays committed to the
        # batch's device — they meet in the concat's size math too.
        # Memoized by identity: a batch and its columns SHARE one count
        # object (ColumnarBatch invariant) and must keep sharing it.
        if isinstance(rc, DeferredCount) and not rc.is_forced:
            if id(rc) not in moved_counts:
                moved_counts[id(rc)] = DeferredCount(
                    jax.device_put(rc.traceable(), target))
            return moved_counts[id(rc)]
        return rc

    def put(x):
        return None if x is None else jax.device_put(x, target)

    out = []
    for b in batches:
        dev = _committed_device(b)
        if dev is None or dev is target:
            out.append(b)
            continue
        # decode encoded columns BEFORE moving: an RLE column's planes
        # are run-space (rebuilding them as row planes corrupts rows),
        # and a dictionary column's value planes are shared + committed
        # to the SOURCE device — moving only the codes would hand the
        # next program cross-device inputs, the exact failure this
        # helper exists to prevent
        b = materialize_batch(b, site="device-align")
        cols = []
        for c in b.columns:
            cols.append(DeviceColumn(
                put(c.data), put(c.validity),
                move_count(c.row_count), c.data_type,
                put(c.lengths), put(getattr(c, "elem_valid", None))))
        out.append(ColumnarBatch(cols, move_count(b.row_count), b.names))
    return out


def concat_batches(batches: Sequence[ColumnarBatch]) -> ColumnarBatch:
    """Concatenates device batches into one padded batch (coalesce).

    reference: GpuCoalesceBatches/ConcatAndConsumeAll use cudf concat; here
    one jitted scatter per (input shapes) signature.
    """
    batches = list(batches)
    if len(batches) > 1:
        # drop known-empty batches without forcing deferred counts
        kept = [b for b in batches
                if isinstance(b.row_count, DeferredCount) or b.row_count > 0]
        batches = kept or batches[:1]
    if len(batches) == 1:
        return batches[0]
    batches = _align_batch_devices(batches)
    # dictionary code planes concat like int planes when every input
    # shares the fingerprint; mismatched positions decode first
    from spark_rapids_tpu.columnar.encoding import (align_batches,
                                                    rewrap_like)
    batches = align_batches(batches, site="concat")
    jnp = _jx()
    deferred_in = any(
        isinstance(b.row_count, DeferredCount) and not b.row_count.is_forced
        for b in batches)
    if deferred_in and \
            sum(b.nbytes() for b in batches) > CONCAT_FORCE_SYNC_BYTES:
        # padding guard: sizing by next-pow2 of SUMMED padded buckets can
        # allocate far past the live rows (every input carries its own
        # pow2 padding; mostly-filtered batches are nearly all padding).
        # Past this footprint one batched count sync is cheaper than the
        # OOM risk — force the counts, size from the REAL total below,
        # and drop each oversized input's padding first
        from spark_rapids_tpu.columnar.column import force_counts
        force_counts([b.row_count for b in batches])
        batches = [shrink_batch(b) for b in batches]
        deferred_in = False
    if deferred_in:
        # deferred inputs: size by the (static) bucket sum — a host sync
        # per concat costs a ~185ms tunnel round trip; the scatter kernel
        # masks by traced counts either way, so a roomier bucket only pads
        from spark_rapids_tpu.columnar.column import rc_traceable as _rt
        out_bucket = bucket_rows(sum(b.bucket for b in batches))
        tot = jnp.asarray(_rt(batches[0].row_count), dtype=np.int64)
        for b in batches[1:]:
            tot = tot + jnp.asarray(_rt(b.row_count), dtype=np.int64)
        total = DeferredCount(tot)
    else:
        total = sum_counts([b.row_count for b in batches])
        out_bucket = bucket_rows(total)
    ncols = batches[0].num_columns
    # per-column max string/array width across inputs
    widths = []
    for ci in range(ncols):
        w = 0
        for b in batches:
            c = b.columns[ci]
            if c.lengths is not None:
                w = max(w, c.data.shape[1])
        widths.append(w)
    key = ("concat", out_bucket,
           tuple(tuple(_col_sig(c) for c in b.columns) for b in batches))
    def build():
        def run(all_arrs, counts_arr):
            offsets = jnp.cumsum(counts_arr) - counts_arr
            outs = []
            for ci in range(ncols):
                tgt_rows = out_bucket
                acc_d = None
                for bi in range(len(all_arrs)):
                    d, v, ln, ev = all_arrs[bi][ci]
                    w = widths[ci]
                    if ln is not None and d.shape[1] < w:
                        d = jnp.pad(d, ((0, 0), (0, w - d.shape[1])))
                        if ev is not None:
                            ev = jnp.pad(ev,
                                         ((0, 0), (0, w - ev.shape[1])))
                    rowpos = jnp.arange(d.shape[0])
                    valid_rows = rowpos < counts_arr[bi]
                    # padding rows scatter out of range -> dropped
                    dest = jnp.where(valid_rows, rowpos + offsets[bi], tgt_rows)
                    if acc_d is None:
                        shape = (tgt_rows,) + d.shape[1:] if ln is None else \
                            (tgt_rows, w)
                        acc_d = jnp.zeros(shape, dtype=d.dtype)
                        acc_v = jnp.zeros(tgt_rows, dtype=bool)
                        acc_l = None if ln is None else \
                            jnp.zeros(tgt_rows, dtype=np.int32)
                        acc_e = None if ev is None else \
                            jnp.zeros((tgt_rows, w), dtype=bool)
                    acc_d = acc_d.at[dest].set(d, mode="drop")
                    acc_v = acc_v.at[dest].set(v & valid_rows, mode="drop")
                    if acc_l is not None:
                        acc_l = acc_l.at[dest].set(ln, mode="drop")
                    if acc_e is not None:
                        acc_e = acc_e.at[dest].set(ev, mode="drop")
                outs.append((acc_d, acc_v, acc_l, acc_e))
            return outs

        return run
    from spark_rapids_tpu.exec.stage_compiler import get_or_build
    fn = get_or_build("batch.concat", key, build)
    counts_arr = jnp.stack([jnp.asarray(rc_traceable(b.row_count),
                                        dtype=np.int64) for b in batches])
    all_arrs = [[(c.data, c.validity, c.lengths, c.elem_valid)
                 for c in b.columns] for b in batches]
    outs = fn(all_arrs, counts_arr)
    cols = []
    for (d, v, ln, ev), proto in zip(outs, batches[0].columns):
        cols.append(rewrap_like(proto, d, v, total, ln, ev))
    return ColumnarBatch(cols, total, batches[0].names)
