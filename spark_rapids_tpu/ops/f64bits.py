"""TPU-safe float64 bit access via double-double (dd) decomposition.

XLA:TPU has no native f64.  With x64 enabled, the X64 rewriter emulates
f64 as a pair of f32 values ("double-double": value = hi + lo with
|lo| <= ulp(hi)/2), giving ~49-bit precision and the f32 exponent range
(~1e+/-38).  Crucially, the rewriter does NOT implement
``bitcast_convert_type`` from f64 to any integer type — every bit-level
trick the reference uses on doubles (cuDF sort-key normalization,
murmur3 over IEEE bytes: spark-rapids HashFunctions.scala,
SortUtils.scala) needs a TPU-native reformulation.  This module is that
reformulation:

- ``dd_split(x)``: (hi_f32, lo_f32) with hi = f32(x), lo = f32(x - hi).
  Exact and *injective* on device-representable doubles: hi is a
  monotone function of x and (hi, lo) reconstructs x exactly, so
  equality and lexicographic order of the pair match the double's
  equality and order.  Two 32-bit bitcasts (which TPU supports) then
  yield integer words for sorting, grouping and join-key hashing.
- ``f64_ieee_bits(x)``: reassembles the IEEE-754 bit pattern of the
  (rounded-to-f64) device value as an int64 using only arithmetic and
  32-bit bitcasts — used by the Spark-compatible murmur3/xxhash64
  device paths.  For any value that is exactly representable on device
  (all f32-exact doubles, integers up to 2^48, etc.) this matches
  Spark's hash bit-for-bit.

Everything here canonicalizes -0.0 -> 0.0 and NaN -> one canonical NaN
first (Spark sort/hash semantics; reference NormalizeFloatingNumbers).
"""

from __future__ import annotations

import numpy as np

_EXP_MASK = np.int64(0x7FF0000000000000)
_NAN_BITS = np.int64(0x7FF8000000000000)
_MANT_MASK = np.int64((1 << 52) - 1)

_BITCAST64: "bool | None" = None


def f64_bitcast_ok() -> bool:
    """Does the active JAX backend support 64-bit float bitcasts?

    True on CPU/GPU (real binary64 — the single u64 word is exact and
    the dd split would LOSE precision there), False on TPU (dd
    emulation: the X64 rewriter has no f64 bitcast, and the dd split
    loses nothing because dd *is* the representation).  Decided from
    the backend name — a probe compile would deadlock when first hit
    inside another program's trace.
    """
    global _BITCAST64
    if _BITCAST64 is None:
        import jax
        _BITCAST64 = jax.default_backend() not in ("tpu", "axon")
    return _BITCAST64


def dd_canonical(x, jnp):
    """-0.0 -> 0.0, every NaN -> canonical NaN (float32 or float64)."""
    zero = jnp.asarray(0, dtype=x.dtype)
    x = jnp.where(x == zero, zero, x)
    return jnp.where(jnp.isnan(x), jnp.asarray(np.nan, dtype=x.dtype), x)


def dd_split(x, jnp):
    """f64 -> (hi_f32, lo_f32) with x == hi + lo exactly (device dd).

    Monotone in hi, injective as a pair; lo is +/-0-free only through
    canonicalization by the caller's word transform.
    """
    hi = x.astype(np.float32)
    lo = (x - hi.astype(x.dtype)).astype(np.float32)
    return hi, lo


def f32_sortable_u32(x, jnp):
    """IEEE f32 -> uint32 whose unsigned order == float total order
    (-NaN-free: NaN canonicalized to positive, sorts above +inf;
    -0.0 == 0.0).  Same trick as cuDF/radix-sort key normalization.

    Canonicalization happens at the BIT level: an arithmetic ``x == 0``
    compare would flush f32-subnormal magnitudes to zero on TPU,
    collapsing distinct tiny doubles into one sort/group/hash key."""
    import jax
    u = jax.lax.bitcast_convert_type(x, np.uint32)
    u = jnp.where(u == np.uint32(0x80000000), np.uint32(0), u)  # -0.0
    u = jnp.where(jnp.isnan(x), np.uint32(0x7FC00000), u)       # canon NaN
    sign = np.uint32(0x80000000)
    return jnp.where((u & sign) != 0, u ^ np.uint32(0xFFFFFFFF), u | sign)


def f64_sortable_words(x, jnp):
    """f64 -> order- and equality-preserving unsigned words.

    Backends with a real binary64 (CPU): one exact uint64 word via the
    classic sign-flip bitcast.  TPU (dd emulation, no f64 bitcast): TWO
    uint32 words from the dd split, each f32-normalized.  Why the pair
    works: hi = f32(x) is monotone non-decreasing in x, and for equal hi
    the order of x equals the order of lo = x - hi.  +/-inf: lo becomes
    NaN (inf - inf), identical for all same-signed infinities so
    equality holds; NaN x sorts above +inf via the hi word alone.
    """
    import jax
    if f64_bitcast_ok():
        x = dd_canonical(x, jnp)
        u = jax.lax.bitcast_convert_type(x, np.uint64)
        sign = np.uint64(1) << np.uint64(63)
        return [jnp.where((u & sign) != 0, u ^ ~np.uint64(0), u | sign)]
    # no arithmetic canonicalization on the dd path (a == 0 compare would
    # flush f32-subnormal hi parts); each f32 word canonicalizes by bits.
    hi, lo = dd_split(x, jnp)
    return [f32_sortable_u32(hi, jnp), f32_sortable_u32(lo, jnp)]


def f64_word_count() -> int:
    """How many unsigned words f64_sortable_words yields on this backend
    (join-side width agreement)."""
    return 1 if f64_bitcast_ok() else 2


def _exp2_small(e, dtype, jnp):
    """Exact 2.0**e for integer |e| <= 64 (bit-ladder of exact
    power-of-two constants; every intermediate <= 2^64, dd-safe)."""
    neg = e < 0
    a = jnp.abs(e)
    r = jnp.ones(e.shape, dtype=dtype)
    for k in range(7):  # bits 1..64
        c = jnp.asarray(float(2.0 ** (2 ** k)), dtype=dtype)
        r = r * jnp.where((a >> k) & 1 == 1, c, jnp.ones_like(r))
    return jnp.where(neg, 1.0 / r, r)


def scale_exp2(x, e, jnp):
    """x * 2.0**e exactly, |e| <= 320, without materializing 2**e
    (which would overflow the dd exponent range): +/-64 chunks applied
    multiplicatively, each partial product stays between x and the
    (in-range) target."""
    r = x
    rem = e
    for _ in range(5):
        step = jnp.clip(rem, -64, 64)
        r = r * _exp2_small(step, x.dtype, jnp)
        rem = rem - step
    return r


def f64_ieee_bits(x, jnp):
    """Device f64 -> int64 IEEE-754 bit pattern of the value rounded to
    binary64, via arithmetic exponent/mantissa extraction (no 64-bit
    bitcasts).  Canonicalizes -0.0 and NaN.

    Zero/tiny classification happens at the BIT level of the dd words
    (dd_split + 32-bit bitcasts, like f64_sortable_words): arithmetic
    ``x == 0`` compares flush f32-subnormal magnitudes on TPU, which
    would collapse distinct tiny keys to the bits of +0.0 and diverge
    from the CPU oracle's exact bitcast (ADVICE r3).  Values whose hi
    word is f32-subnormal (|x| < 2^-126; the dd representation bottoms
    out at 2^-149, where lo is always ±0) get their bits reassembled
    from the hi word's integer mantissa directly — arithmetic on such
    magnitudes would flush.
    """
    import jax
    if f64_bitcast_ok():
        x = dd_canonical(x, jnp)
        return jax.lax.bitcast_convert_type(x, np.int64)
    isnan = jnp.isnan(x)
    isinf = jnp.isinf(x)
    hi, lo = dd_split(x, jnp)
    uh = jax.lax.bitcast_convert_type(hi, np.uint32)
    ul = jax.lax.bitcast_convert_type(lo, np.uint32)
    mag_h = uh & np.uint32(0x7FFFFFFF)
    mag_l = ul & np.uint32(0x7FFFFFFF)
    nonzero = (mag_h != 0) | (mag_l != 0)
    # hi in the f32-subnormal range: exponent bits all zero, mantissa set
    tiny = (mag_h >> np.uint32(23) == 0) & nonzero & ~isnan & ~isinf
    finite = ~isnan & ~isinf & nonzero & ~tiny
    a = jnp.abs(jnp.where(finite, x, jnp.ones_like(x)))
    # lift near-f32-subnormal magnitudes into the safe range (exact scale)
    small = a < 2.0 ** -60
    a = a * jnp.where(small, jnp.asarray(2.0 ** 64, a.dtype),
                      jnp.ones_like(a))
    off = jnp.where(small, -64, 0).astype(np.int32)
    # exponent estimate from the f32 hi part, corrected by one step
    ua = jax.lax.bitcast_convert_type(a.astype(np.float32), np.uint32)
    e0 = ((ua >> np.uint32(23)) & np.uint32(0xFF)).astype(np.int32) - 127
    m0 = scale_exp2(a, -e0, jnp)
    e1 = e0 + jnp.where(m0 >= 2.0, 1, 0) - jnp.where(m0 < 1.0, 1, 0)
    m = scale_exp2(a, -e1, jnp)           # in [1, 2)
    exp = (e1 + off).astype(np.int64)
    mant = (m * (2.0 ** 52)).astype(np.int64) - np.int64(1 << 52)
    mant = jnp.clip(mant, 0, _MANT_MASK)
    bits = ((exp + np.int64(1023)) << np.int64(52)) | mant
    bits = jnp.where(finite, bits, np.int64(0))
    # tiny path: |x| = m_int * 2^-149 exactly (m_int = hi's 23 mantissa
    # bits; lo is ±0 here).  floor(log2 m_int) comes from the exact
    # f32 representation of the INTEGER m_int — integer bit math only,
    # no flushable arithmetic.
    m_int = mag_h.astype(np.int64)
    m_f = jnp.maximum(m_int, 1).astype(np.float32)    # exact for < 2^24
    um = jax.lax.bitcast_convert_type(m_f, np.uint32)
    e_m = ((um >> np.uint32(23)) & np.uint32(0xFF)).astype(np.int64) - 127
    t_exp = e_m - 149
    t_mant = (jnp.left_shift(m_int, (52 - e_m)) - np.int64(1 << 52)) \
        & _MANT_MASK
    t_bits = ((t_exp + np.int64(1023)) << np.int64(52)) | t_mant
    bits = jnp.where(tiny, t_bits, bits)
    bits = jnp.where(isinf, _EXP_MASK, bits)
    bits = jnp.where(isnan, _NAN_BITS, bits)
    # sign from the hi word's bit, canonicalized: -0.0 -> +0.0, NaN -> +
    neg = (uh >> np.uint32(31) != 0) & nonzero & ~isnan
    sign = jnp.where(neg, np.int64(-2 ** 63), np.int64(0))
    return bits | sign
