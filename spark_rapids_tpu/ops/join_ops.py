"""Device join kernels: equi-joins and nested-loop pair generation.

Reference: GpuHashJoin (execution/GpuHashJoin.scala) lowers joins to cuDF
hash-table gather maps; JoinGatherer.scala applies them.  TPU-first
redesign — XLA has no device hash tables, but is excellent at sort +
binary search, so an equi-join becomes:

1. hash every row's key columns into one uint64 word (padding/invalid rows
   get a sentinel hash);
2. sort the BUILD side by hash (``jax.lax.sort``, one fused op);
3. ``searchsorted`` each PROBE hash into the sorted build hashes -> a
   candidate range [lo, hi) per probe row (static shapes throughout);
4. expand candidate pairs into a padded pair table (the only host syncs are
   the candidate total and the final row count);
5. VERIFY true key equality per pair (hash collisions and null semantics are
   resolved here, on masked sortable words), and
6. finalize per join type: compact kept pairs, append null-extended
   unmatched rows for outer joins, or reduce to per-row match flags for
   semi/anti.

Nested-loop (cross / condition-only) joins reuse steps 4-6 with the
candidate set = the full cartesian product of in-row positions.

Null semantics match Spark: null keys never match (unless the key is
null-safe, i.e. ``<=>``); NaN == NaN and -0.0 == 0.0 for join keys (the
sortable-word normalization gives this for free, sort_ops.py).
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Sequence, Tuple

import numpy as np

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.batch import ColumnarBatch
from spark_rapids_tpu.columnar.column import DeviceColumn, bucket_rows


def _jx():
    from spark_rapids_tpu.columnar.column import _jnp
    return _jnp()


# Join types (reference: Spark JoinType; GpuHashJoin supports all of these)
INNER = "inner"
LEFT_OUTER = "left_outer"
RIGHT_OUTER = "right_outer"
FULL_OUTER = "full_outer"
LEFT_SEMI = "left_semi"
LEFT_ANTI = "left_anti"
CROSS = "cross"

_SENTINEL = np.uint64(0xFFFFFFFFFFFFFFFF)


def _mix64(h, jnp):
    """murmur3 fmix64 — avalanches a uint64 word."""
    h = h ^ (h >> np.uint64(33))
    h = h * np.uint64(0xFF51AFD7ED558CCD)
    h = h ^ (h >> np.uint64(33))
    h = h * np.uint64(0xC4CEB9FE1A85EC53)
    h = h ^ (h >> np.uint64(33))
    return h


def _key_words(col: DeviceColumn, jnp, width_words: Optional[int] = None):
    """(validity-rank word, masked value words) for one key column; equal
    keys (with both-null == both-null) produce identical word tuples.
    ``width_words`` pads string word lists so both sides agree."""
    from spark_rapids_tpu.ops.sort_ops import sortable_words
    words = []
    for w in sortable_words(col, jnp):
        words.append(jnp.where(col.validity, w, jnp.zeros_like(w)))
    if width_words is not None:
        while len(words) < width_words:
            words.append(jnp.zeros(col.bucket, dtype=np.uint64))
    return [col.validity.astype(np.int8)] + words


def _n_value_words(col: DeviceColumn) -> int:
    """How many value words _key_words yields for this column (static)."""
    dt = col.data_type
    if isinstance(dt, (T.StringType, T.BinaryType)):
        w = int(col.data.shape[1]) if col.data.ndim == 2 else 0
        return max(1, -(-w // 7))
    if isinstance(dt, T.DecimalType) and dt.is_decimal128:
        return 2
    if isinstance(dt, T.DoubleType):
        from spark_rapids_tpu.ops.f64bits import f64_word_count
        return f64_word_count()   # 1 exact u64 (CPU) / 2 dd u32s (TPU)
    return 1


def _hash_rows(cols: List[DeviceColumn], widths: List[int], inrow, jnp):
    """uint64 hash per row over all key columns; padding rows -> sentinel."""
    h = jnp.full(cols[0].bucket if cols else inrow.shape[0], 0x9E3779B97F4A7C15,
                 dtype=np.uint64)
    for c, w in zip(cols, widths):
        for word in _key_words(c, jnp, w):
            u = word.astype(np.uint64) if word.dtype != np.uint64 else word
            h = _mix64(h ^ _mix64(u, jnp), jnp)
    return jnp.where(inrow, h, _SENTINEL)


def _col_sig(c: DeviceColumn) -> Tuple:
    return (str(c.data.dtype), tuple(c.data.shape), c.lengths is not None,
            c.elem_valid is not None)


@dataclasses.dataclass
class BuiltSide:
    """The build (hash) side, sorted by key hash — reusable across many
    probe batches (reference: the build-side hash table in GpuHashJoin)."""
    batch: ColumnarBatch          # original build batch
    key_ordinals: Tuple[int, ...]
    hashes_sorted: object         # uint64[bucket] ascending
    perm: object                  # int32[bucket]: sorted pos -> original row
    widths: List[int]             # string word widths agreed with probe side




def build_side(batch: ColumnarBatch, key_ordinals: Sequence[int],
               probe_key_cols: Sequence[DeviceColumn]) -> BuiltSide:
    """Sorts the build side by key hash (one jitted program)."""
    import jax
    jnp = _jx()
    key_ordinals = tuple(key_ordinals)
    kcols = [batch.columns[i] for i in key_ordinals]
    widths = [max(_n_value_words(b), _n_value_words(p))
              for b, p in zip(kcols, probe_key_cols)]
    key = ("build", tuple(_col_sig(c) for c in kcols), tuple(widths))
    def build():
        bucket = kcols[0].bucket if kcols else batch.bucket
        dtypes = [c.data_type for c in kcols]

        def run(arrs, row_count):
            cols = [DeviceColumn(d, v, bucket, dtypes[i], ln)
                    for i, (d, v, ln) in enumerate(arrs)]
            rowpos = jnp.arange(bucket, dtype=np.int32)
            inrow = rowpos < row_count
            h = _hash_rows(cols, widths, inrow, jnp)
            hs, perm = jax.lax.sort((h, rowpos), num_keys=1, is_stable=True)
            return hs, perm

        return run
    from spark_rapids_tpu.exec.stage_compiler import get_or_build
    fn = get_or_build("join.build", key, build)
    from spark_rapids_tpu.columnar.column import rc_traceable
    arrs = [(c.data, c.validity, c.lengths) for c in kcols]
    hs, perm = fn(arrs, rc_traceable(batch.row_count))
    return BuiltSide(batch, key_ordinals, hs, perm, widths)


def _probe_ranges(probe_keys: List[DeviceColumn], built: BuiltSide):
    """Per-probe-row candidate range in the sorted build hashes.
    Returns (lo, counts, offsets, total) — total is the one host sync."""
    jnp = _jx()
    key = ("probe", tuple(_col_sig(c) for c in probe_keys),
           built.hashes_sorted.shape, tuple(built.widths))
    def build():
        bucket = probe_keys[0].bucket
        dtypes = [c.data_type for c in probe_keys]
        widths = built.widths

        def run(arrs, row_count, hs):
            cols = [DeviceColumn(d, v, bucket, dtypes[i], ln)
                    for i, (d, v, ln) in enumerate(arrs)]
            rowpos = jnp.arange(bucket, dtype=np.int32)
            inrow = rowpos < row_count
            h = _hash_rows(cols, widths, inrow, jnp)
            lo = jnp.searchsorted(hs, h, side="left").astype(np.int64)
            hi = jnp.searchsorted(hs, h, side="right").astype(np.int64)
            # sentinel probe rows (padding) must not match sentinel build pad
            counts = jnp.where(inrow & (h != _SENTINEL), hi - lo, 0)
            offsets = jnp.cumsum(counts) - counts
            return lo, counts, offsets, jnp.sum(counts)

        return run
    from spark_rapids_tpu.exec.stage_compiler import get_or_build
    fn = get_or_build("join.probe", key, build)
    arrs = [(c.data, c.validity, c.lengths) for c in probe_keys]
    from spark_rapids_tpu.columnar.column import rc_traceable
    lo, counts, offsets, total = fn(arrs, rc_traceable(probe_keys[0].row_count),
                                    built.hashes_sorted)
    return lo, counts, offsets, total   # total: 0-d device (caller decides)


def _expand_verify(probe: ColumnarBatch, probe_ordinals, built: BuiltSide,
                   null_safe: Tuple[bool, ...], lo, offsets, total,
                   out_bucket: int):
    """Expands candidate ranges to a padded pair table and verifies true key
    equality.  Returns (l_idx, r_idx, keep, pair_bucket).  ``total`` may be
    a 0-d device scalar (speculative sizing: caller picked ``out_bucket``
    and tracks overflow via ops/speculation.py) or a host int (exact)."""
    jnp = _jx()
    pkeys = [probe.columns[i] for i in probe_ordinals]
    bkeys = [built.batch.columns[i] for i in built.key_ordinals]
    key = ("pairs", out_bucket, tuple(_col_sig(c) for c in pkeys),
           tuple(_col_sig(c) for c in bkeys), null_safe, tuple(built.widths))
    def build():
        p_bucket = probe.bucket
        b_bucket = built.batch.bucket
        pdt = [c.data_type for c in pkeys]
        bdt = [c.data_type for c in bkeys]
        widths = built.widths

        def run(parrs, barrs, lo, offsets, total, perm, p_count, b_count):
            pcols = [DeviceColumn(d, v, p_bucket, pdt[i], ln)
                     for i, (d, v, ln) in enumerate(parrs)]
            bcols = [DeviceColumn(d, v, b_bucket, bdt[i], ln)
                     for i, (d, v, ln) in enumerate(barrs)]
            r = jnp.arange(out_bucket, dtype=np.int64)
            # probe row for each output pair: last offset <= r
            p = jnp.searchsorted(offsets, r, side="right").astype(np.int64) - 1
            p = jnp.clip(p, 0, p_bucket - 1)
            j = r - jnp.take(offsets, p)
            spos = jnp.take(lo, p) + j          # position in sorted build
            spos = jnp.clip(spos, 0, b_bucket - 1)
            b = jnp.take(perm, spos).astype(np.int64)   # original build row
            live = r < total
            keep = live & (p < p_count) & (b < b_count)
            # verify true equality on masked words (collisions + nulls)
            for ki, (pc, bc) in enumerate(zip(pcols, bcols)):
                pw = _key_words(pc, jnp, widths[ki])
                bw = _key_words(bc, jnp, widths[ki])
                eq = jnp.ones(out_bucket, dtype=bool)
                for a, bword in zip(pw, bw):
                    av = jnp.take(a, p, axis=0)
                    bv = jnp.take(bword, b, axis=0)
                    eq = eq & (av == bv)
                if not null_safe[ki]:
                    eq = eq & jnp.take(pc.validity, p) & \
                        jnp.take(bc.validity, b)
                keep = keep & eq
            return p, b, keep

        return run
    from spark_rapids_tpu.exec.stage_compiler import get_or_build
    fn = get_or_build("join.pair", key, build)
    parrs = [(c.data, c.validity, c.lengths) for c in pkeys]
    barrs = [(c.data, c.validity, c.lengths) for c in bkeys]
    from spark_rapids_tpu.columnar.column import rc_traceable as _rt
    l_idx, r_idx, keep = fn(parrs, barrs, lo, offsets, total, built.perm,
                            _rt(probe.row_count), _rt(built.batch.row_count))
    return l_idx, r_idx, keep, out_bucket


def cross_pairs(probe: ColumnarBatch, build: ColumnarBatch):
    """Candidate set for nested-loop joins: full cartesian product.
    Returns (l_idx, r_idx, keep, pair_bucket)."""
    jnp = _jx()
    from spark_rapids_tpu.columnar.column import rc_traceable
    total = int(probe.row_count) * int(build.row_count)
    out_bucket = bucket_rows(max(total, 1))
    key = ("cross", out_bucket)
    def build_fn():
        def run(total, b_count):
            r = jnp.arange(out_bucket, dtype=np.int64)
            bc = jnp.maximum(b_count, 1)
            p = r // bc
            b = r % bc
            keep = r < total
            return p, b, keep

        return run
    from spark_rapids_tpu.exec.stage_compiler import get_or_build
    fn = get_or_build("join.cross_pairs", key, build_fn)
    l_idx, r_idx, keep = fn(total, rc_traceable(build.row_count))
    return l_idx, r_idx, keep, out_bucket


def matched_flags(idx, keep, side_bucket: int):
    """Per-row "has >= 1 kept pair" flags (semi/anti/outer bookkeeping)."""
    jnp = _jx()
    key = ("flags", int(idx.shape[0]), side_bucket)
    def build():
        def run(idx, keep):
            safe = jnp.clip(idx, 0, side_bucket - 1)
            return jnp.zeros(side_bucket, dtype=bool).at[safe].max(keep)

        return run
    from spark_rapids_tpu.exec.stage_compiler import get_or_build
    fn = get_or_build("join.matched_flags", key, build)
    return fn(idx, keep)


def compact_pairs(l_idx, r_idx, keep):
    """Moves kept pairs to the front; returns (l, r, count).

    The count stays a :class:`DeferredCount` — forcing it here would cost a
    host round trip per probe batch (the dominant latency on a
    tunnel-attached chip); consumers size their output by the pair bucket
    (static) and mask by the deferred count instead."""
    from spark_rapids_tpu.columnar.column import DeferredCount
    jnp = _jx()
    key = ("cpairs", int(l_idx.shape[0]))
    def build():
        def run(l_idx, r_idx, keep):
            order = jnp.argsort(~keep, stable=True)
            return (jnp.take(l_idx, order), jnp.take(r_idx, order),
                    jnp.sum(keep))

        return run
    from spark_rapids_tpu.exec.stage_compiler import get_or_build
    fn = get_or_build("join.compact_pairs", key, build)
    l, r, n = fn(l_idx, r_idx, keep)
    return l, r, DeferredCount(n)


def unmatched_positions(flags, row_count: int):
    """Row positions with no kept match, compacted; returns
    (idx, DeferredCount) — no host sync (see compact_pairs)."""
    from spark_rapids_tpu.columnar.column import DeferredCount
    jnp = _jx()
    bucket = int(flags.shape[0])
    key = ("unmatched", bucket)
    def build():
        def run(flags, row_count):
            rowpos = jnp.arange(bucket, dtype=np.int64)
            want = (~flags) & (rowpos < row_count)
            order = jnp.argsort(~want, stable=True)
            return jnp.take(rowpos, order), jnp.sum(want)

        return run
    from spark_rapids_tpu.exec.stage_compiler import get_or_build
    fn = get_or_build("join.unmatched", key, build)
    from spark_rapids_tpu.columnar.column import rc_traceable as _rt2
    idx, n = fn(flags, _rt2(row_count))
    return idx, DeferredCount(n)


def gather_join_output(probe: ColumnarBatch, build: ColumnarBatch,
                       l_map, r_map, count,
                       names: Optional[List[str]] = None,
                       out_bucket: Optional[int] = None) -> ColumnarBatch:
    """Materializes join output rows: probe columns gathered by ``l_map``,
    build columns by ``r_map``; a negative map entry yields a null row for
    that side (outer-join null extension).  ``count`` may be a
    :class:`DeferredCount` (no host sync) when ``out_bucket`` is given;
    either map may be ``None``, meaning "all null rows for that side"
    (the constant -1 map is generated inside the program — shipping a
    bucket-sized host constant would cost a real transfer)."""
    from spark_rapids_tpu.columnar.column import (DeferredCount,
                                                  rc_traceable)
    jnp = _jx()
    if out_bucket is None:
        out_bucket = bucket_rows(max(int(count), 1))
    # pad maps to a bucketed length so the program caches across batches
    some_map = l_map if l_map is not None else r_map
    maps_bucket = bucket_rows(max(int(some_map.shape[0]), 1))

    def _pad(m):
        if m is None or int(m.shape[0]) == maps_bucket:
            return m
        pad = maps_bucket - int(m.shape[0])
        return jnp.pad(jnp.asarray(m), (0, pad), constant_values=-1)

    l_map, r_map = _pad(l_map), _pad(r_map)
    key = ("jgather", out_bucket, maps_bucket,
           l_map is None, r_map is None,
           tuple(_col_sig(c) for c in probe.columns),
           tuple(_col_sig(c) for c in build.columns))
    def build_fn():
        p_bucket, b_bucket = probe.bucket, build.bucket
        no_l, no_r = l_map is None, r_map is None

        def run(parrs, barrs, l_map, r_map, count):
            r = jnp.arange(out_bucket, dtype=np.int64)
            live = r < count
            safe_r = jnp.clip(r, 0, maps_bucket - 1)
            neg = jnp.full(out_bucket, -1, dtype=np.int64)
            lm = neg if no_l else jnp.take(l_map, safe_r)
            rm = neg if no_r else jnp.take(r_map, safe_r)
            outs = []
            for (d, v, ln, ev) in parrs:
                sl = jnp.clip(lm, 0, p_bucket - 1)
                nd = jnp.take(d, sl, axis=0)
                nv = jnp.take(v, sl, axis=0) & (lm >= 0) & live
                nl = None if ln is None else jnp.take(ln, sl, axis=0)
                ne = None if ev is None else jnp.take(ev, sl, axis=0)
                outs.append((nd, nv, nl, ne))
            for (d, v, ln, ev) in barrs:
                sr = jnp.clip(rm, 0, b_bucket - 1)
                nd = jnp.take(d, sr, axis=0)
                nv = jnp.take(v, sr, axis=0) & (rm >= 0) & live
                nl = None if ln is None else jnp.take(ln, sr, axis=0)
                ne = None if ev is None else jnp.take(ev, sr, axis=0)
                outs.append((nd, nv, nl, ne))
            return outs

        return run
    from spark_rapids_tpu.exec.stage_compiler import get_or_build
    fn = get_or_build("join.gather", key, build_fn)
    parrs = [(c.data, c.validity, c.lengths, c.elem_valid)
             for c in probe.columns]
    barrs = [(c.data, c.validity, c.lengths, c.elem_valid)
             for c in build.columns]
    zero = np.zeros(0, np.int64)
    outs = fn(parrs, barrs,
              zero if l_map is None else l_map,
              zero if r_map is None else r_map,
              rc_traceable(count))
    if isinstance(count, DeferredCount) and count.is_forced:
        count = int(count)
    cols = []
    from spark_rapids_tpu.columnar.encoding import rewrap_like
    protos = list(probe.columns) + list(build.columns)
    for (d, v, ln, ev), proto in zip(outs, protos):
        # dictionary payload columns gather their code planes and stay
        # encoded through the join (late materialization)
        cols.append(rewrap_like(proto, d, v, count, ln, ev))
    return ColumnarBatch(cols, count, names)


def concat_matched_unmatched(l, r, n, ul, un):
    """Concatenates the matched-pair maps (l, r, count n) with null-extended
    unmatched probe rows (positions ul, count un) entirely on device:
    returns (l_map, r_map, DeferredCount(total), out_bucket).  The
    fragments keep their kept entries front-compacted, so writing fragment
    2 at traced offset ``n`` overwrites fragment 1's dead tail; positions
    past ``n + un`` are masked by the deferred total downstream."""
    import jax
    from spark_rapids_tpu.columnar.column import DeferredCount, rc_traceable
    jnp = _jx()
    b1, b2 = int(l.shape[0]), int(ul.shape[0])
    out_bucket = bucket_rows(max(b1 + b2, 1))
    key = ("concat_mu", b1, b2)
    def build():
        def run(l, r, n, ul, un):
            lmap = jnp.full(out_bucket, -1, dtype=np.int64)
            rmap = jnp.full(out_bucket, -1, dtype=np.int64)
            lmap = jax.lax.dynamic_update_slice(
                lmap, l.astype(np.int64), (jnp.zeros((), np.int64),))
            rmap = jax.lax.dynamic_update_slice(
                rmap, r.astype(np.int64), (jnp.zeros((), np.int64),))
            lmap = jax.lax.dynamic_update_slice(
                lmap, ul.astype(np.int64), (n.astype(np.int64),))
            rmap = jax.lax.dynamic_update_slice(
                rmap, jnp.full(b2, -1, dtype=np.int64),
                (n.astype(np.int64),))
            return lmap, rmap, n + un
        return run
    from spark_rapids_tpu.exec.stage_compiler import get_or_build
    fn = get_or_build("join.concat_maps", key, build)
    jnp_n = jnp.asarray(rc_traceable(n), dtype=np.int64)
    jnp_un = jnp.asarray(rc_traceable(un), dtype=np.int64)
    lmap, rmap, total = fn(l, r, jnp_n, ul, jnp_un)
    return lmap, rmap, DeferredCount(total), out_bucket
