"""Device sort kernels: multi-key lexicographic sort.

Reference: GpuSortExec.scala + SortUtils.scala lower sorting to cuDF
``Table.sortOrder``/``gather``.  TPU-first redesign: every key column is
normalized into one or more integer "sortable words" such that plain
ascending integer order == the SQL order (nulls-first/last, asc/desc, NaN
ordering, string lexicographic order), then a single ``jax.lax.sort`` over
all words (variadic operands, ``num_keys``) yields the permutation.  This
keeps the whole sort one fused XLA op on static shapes — no comparator
callbacks, no dynamic shapes.

Normalization rules:
- padding rows (>= row_count) sort last via a leading global rank word
- null rank word per key: 0/1 per nulls_first
- floats: IEEE bit trick (flip all bits when negative, flip sign bit when
  positive) -> unsigned order; NaN canonicalized positive (sorts after +inf,
  Spark semantics), -0.0 normalized to 0.0
- strings/binary: bytes+1 packed 7-per-uint64 big-endian (pad rank 0) so a
  prefix sorts first and embedded NULs stay ordered; exact, not truncated
- decimal128: hi limb signed word, lo limb unsigned word
- descending: bitwise-NOT of the word (monotone order reversal, no overflow)
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Sequence, Tuple

import numpy as np

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.batch import ColumnarBatch
from spark_rapids_tpu.columnar.column import DeviceColumn


def _jx():
    from spark_rapids_tpu.columnar.column import _jnp
    return _jnp()


@dataclasses.dataclass(frozen=True)
class SortOrder:
    """One sort key (reference: Spark SortOrder child/direction/nullOrdering).

    ``ordinal`` indexes the batch being sorted; exec layers project key
    expressions into leading columns first.
    """
    ordinal: int
    ascending: bool = True
    nulls_first: bool = True   # Spark default: NULLS FIRST for ASC, LAST for DESC

    @staticmethod
    def asc(ordinal: int) -> "SortOrder":
        return SortOrder(ordinal, True, True)

    @staticmethod
    def desc(ordinal: int) -> "SortOrder":
        return SortOrder(ordinal, False, False)


def _float_sortable(x, jnp, ubits_dtype):
    # f32: one u32 word; f64: TWO u32 words via double-double split —
    # the TPU X64 rewriter has no f64 bitcast (see ops/f64bits.py)
    from spark_rapids_tpu.ops.f64bits import (f32_sortable_u32,
                                              f64_sortable_words)
    if np.dtype(ubits_dtype).itemsize == 8:
        return f64_sortable_words(x, jnp)
    return [f32_sortable_u32(x, jnp)]


def _string_words(col: DeviceColumn, jnp) -> List:
    """Packs bytes+1 (pad=0) 7-per-word big-endian -> uint64 words."""
    data = col.data          # uint8 [bucket, w]
    lens = col.lengths
    w = int(data.shape[1]) if data.ndim == 2 else 0
    if w == 0:
        return [jnp.zeros(data.shape[0], dtype=np.uint64)]
    pos = jnp.arange(w, dtype=np.int32)
    vals = jnp.where(pos[None, :] < lens[:, None],
                     data.astype(np.uint64) + 1, 0)
    words = []
    for start in range(0, w, 7):
        chunk = vals[:, start:start + 7]
        word = jnp.zeros(data.shape[0], dtype=np.uint64)
        k = chunk.shape[1]
        for j in range(k):
            word = word | (chunk[:, j] << np.uint64(9 * (6 - j)))
        words.append(word)
    return words


def sortable_words(col: DeviceColumn, jnp) -> List:
    """Key words in ascending-SQL order; nulls carry garbage (rank separates).

    Used both by sort (with null-rank words) and by group-boundary detection
    (with null masking)."""
    import jax
    dt = col.data_type
    if isinstance(dt, (T.StringType, T.BinaryType)):
        return _string_words(col, jnp)
    if isinstance(dt, T.DecimalType) and dt.is_decimal128:
        hi = col.data[:, 0]
        lo = jax.lax.bitcast_convert_type(col.data[:, 1], np.uint64)
        return [hi, lo]
    if isinstance(dt, T.FloatType):
        return _float_sortable(col.data, jnp, np.uint32)
    if isinstance(dt, T.DoubleType):
        return _float_sortable(col.data, jnp, np.uint64)
    if isinstance(dt, T.BooleanType):
        return [col.data.astype(np.int8)]
    # integral / date / timestamp / decimal64: native integer order
    return [col.data]


def _order_words(col: DeviceColumn, order: SortOrder, jnp) -> List:
    """null-rank word + (possibly flipped) value words for one sort key."""
    rank_null = np.int8(0 if order.nulls_first else 1)
    rank_val = np.int8(1 if order.nulls_first else 0)
    words = [jnp.where(col.validity, rank_val, rank_null)]
    for w in sortable_words(col, jnp):
        if not order.ascending:
            w = ~w
        words.append(w)
    return words


# ---------------------------------------------------------------------------
# numpy twin (CPU oracle paths, e.g. RangePartitioning.partition_ids_cpu):
# same normalization semantics, classic host-side bit tricks
# ---------------------------------------------------------------------------

def _float_sortable_np(x: np.ndarray) -> np.ndarray:
    x = np.where(x == 0, np.zeros((), dtype=x.dtype), x)
    x = np.where(np.isnan(x), np.array(np.nan, dtype=x.dtype), x)
    ub = np.uint64 if x.dtype == np.float64 else np.uint32
    u = np.ascontiguousarray(x).view(ub)
    nbits = np.dtype(ub).itemsize * 8
    sign = ub(1) << ub(nbits - 1)
    allbits = ~ub(0)
    return np.where((u & sign) != 0, u ^ allbits, u | sign)


def _string_words_np(chars: np.ndarray, lens: np.ndarray) -> List[np.ndarray]:
    w = chars.shape[1] if chars.ndim == 2 else 0
    if w == 0:
        return [np.zeros(chars.shape[0], dtype=np.uint64)]
    pos = np.arange(w, dtype=np.int32)
    vals = np.where(pos[None, :] < lens[:, None],
                    chars.astype(np.uint64) + 1, np.uint64(0))
    words = []
    for start in range(0, w, 7):
        chunk = vals[:, start:start + 7]
        word = np.zeros(chars.shape[0], dtype=np.uint64)
        for j in range(chunk.shape[1]):
            word = word | (chunk[:, j] << np.uint64(9 * (6 - j)))
        words.append(word)
    return words


def host_order_words(col, order: SortOrder,
                     string_width: Optional[int] = None,
                     string_pair=None) -> List[np.ndarray]:
    """Numpy order words for one HostColumn: [null-rank] + value words in
    the same SQL order as the device path.  ``string_width`` pads string
    rectangles so two batches (rows vs range bounds) agree on word count;
    ``string_pair`` reuses an already-rectangularized (chars, lens) so
    callers that probed the width don't pay the ragged->rect scatter twice."""
    dt = col.data_type
    valid = col.validity_np()
    rank_null = np.int8(0 if order.nulls_first else 1)
    rank_val = np.int8(1 if order.nulls_first else 0)
    words: List[np.ndarray] = [np.where(valid, rank_val, rank_null)]
    if isinstance(dt, (T.StringType, T.BinaryType)):
        if string_pair is not None:
            chars, lens = string_pair
            if string_width and chars.shape[1] < string_width:
                chars = np.pad(chars,
                               ((0, 0), (0, string_width - chars.shape[1])))
        else:
            chars, lens = col.string_np(max_len=string_width)
        vw = _string_words_np(chars, lens)
    elif isinstance(dt, T.DecimalType) and dt.is_decimal128:
        raw = col.data_np()
        vw = [raw[:, 0], np.ascontiguousarray(raw[:, 1]).view(np.uint64)]
    elif isinstance(dt, (T.FloatType, T.DoubleType)):
        vw = [_float_sortable_np(col.data_np())]
    elif isinstance(dt, T.BooleanType):
        vw = [col.data_np().astype(np.int8)]
    else:
        vw = [col.data_np()]
    for w in vw:
        w = np.where(valid, w, np.zeros((), dtype=w.dtype))
        if not order.ascending:
            w = ~w
        words.append(w)
    return words




def _col_sig(c: DeviceColumn) -> Tuple:
    return (str(c.data.dtype), tuple(c.data.shape), c.lengths is not None)


def sort_permutation(batch: ColumnarBatch, orders: Sequence[SortOrder]):
    """Returns int32[bucket] permutation placing rows in SQL order,
    padding rows last.  One jitted program per (shapes, orders) signature."""
    import jax
    jnp = _jx()
    orders = tuple(orders)
    key = ("perm", tuple(_col_sig(c) for c in batch.columns), orders)
    def build():
        bucket = batch.bucket
        # capture only scalars/types, never the batch itself: the jitted
        # closure lives in the module cache and would pin device buffers
        dtypes = [c.data_type for c in batch.columns]

        def run(arrs, row_count):
            cols = [DeviceColumn(d, v, bucket, dtypes[i], ln)
                    for i, (d, v, ln) in enumerate(arrs)]
            rowpos = jnp.arange(bucket, dtype=np.int32)
            words = [(rowpos >= row_count).astype(np.int8)]  # padding last
            for o in orders:
                words.extend(_order_words(cols[o.ordinal], o, jnp))
            out = jax.lax.sort(tuple(words) + (rowpos,),
                               num_keys=len(words), is_stable=True)
            return out[-1]

        return run
    from spark_rapids_tpu.exec.stage_compiler import get_or_build
    fn = get_or_build("sort.perm", key, build)
    from spark_rapids_tpu.columnar.column import rc_traceable
    arrs = [(c.data, c.validity, c.lengths) for c in batch.columns]
    return fn(arrs, rc_traceable(batch.row_count))


def sort_gather_batch(batch: ColumnarBatch, orders: Sequence[SortOrder],
                      key_exprs: Sequence = ()) -> ColumnarBatch:
    """Fused sort-key prep + permutation + payload gather: ONE compiled
    program.  ``key_exprs`` are non-reference sort keys evaluated
    IN-TRACE (ordinals past the payload width address them), so an
    expression sort pays zero extra dispatches — previously key
    projection, permutation and gather were three programs (the gather
    even dispatched per column).  The payload keeps the input layout;
    key columns never materialize in HBM."""
    import jax
    jnp = _jx()
    orders = tuple(orders)
    key_exprs = list(key_exprs or ())
    key = ("sortgather", tuple(_col_sig(c) for c in batch.columns),
           tuple((c.elem_valid is not None) for c in batch.columns),
           orders, tuple((e.sql(), str(e.data_type)) for e in key_exprs),
           batch.bucket)

    def build():
        bucket = batch.bucket
        dtypes = [c.data_type for c in batch.columns]
        exprs = list(key_exprs)

        def run(arrs, row_count):
            from spark_rapids_tpu.expressions.base import EvalContext, TCol
            from spark_rapids_tpu.expressions.evaluator import \
                tcol_to_device_column
            cols = [DeviceColumn(d, v, bucket, dtypes[i], ln, ev)
                    for i, (d, v, ln, ev) in enumerate(arrs)]
            keycols = list(cols)
            if exprs:
                tcols = [TCol(c.data, c.validity, c.data_type,
                              lengths=c.lengths, elem_valid=c.elem_valid)
                         for c in cols]
                ctx = EvalContext(tcols, "tpu", bucket)
                for e in exprs:
                    dc = tcol_to_device_column(e.eval_tpu(ctx), 0, bucket,
                                               jnp)
                    keycols.append(DeviceColumn(dc.data, dc.validity,
                                                bucket, e.data_type,
                                                dc.lengths))
            rowpos = jnp.arange(bucket, dtype=np.int32)
            words = [(rowpos >= row_count).astype(np.int8)]  # padding last
            for o in orders:
                words.extend(_order_words(keycols[o.ordinal], o, jnp))
            perm = jax.lax.sort(tuple(words) + (rowpos,),
                                num_keys=len(words), is_stable=True)[-1]
            outs = []
            for c in cols:
                d = jnp.take(c.data, perm, axis=0)
                v = jnp.take(c.validity, perm, axis=0)
                ln = None if c.lengths is None else \
                    jnp.take(c.lengths, perm, axis=0)
                ev = None if c.elem_valid is None else \
                    jnp.take(c.elem_valid, perm, axis=0)
                outs.append((d, v, ln, ev))
            return outs

        return run

    from spark_rapids_tpu.exec.stage_compiler import get_or_build
    fn = get_or_build("sort.fused", key, build)
    from spark_rapids_tpu.columnar.column import rc_traceable
    arrs = [(c.data, c.validity, c.lengths, c.elem_valid)
            for c in batch.columns]
    outs = fn(arrs, rc_traceable(batch.row_count))
    cols = [DeviceColumn(d, v, batch.row_count, c.data_type, ln, ev)
            for (d, v, ln, ev), c in zip(outs, batch.columns)]
    return ColumnarBatch(cols, batch.row_count, batch.names)


def sort_batch(batch: ColumnarBatch, orders: Sequence[SortOrder]) -> ColumnarBatch:
    return sort_gather_batch(batch, orders)
