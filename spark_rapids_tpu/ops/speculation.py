"""Optimistic device-side sizing with replay-on-overflow.

The static-shape discipline needs a host-known bucket for every padded
output, but fetching an exact size costs a ~185ms tunnel round trip per
fetch — per-JOIN syncs dominated TPC-DS wall time.  This module lets an
operator GUESS a bucket from static information (e.g. join pair table =
probe bucket: exact for the FK->PK joins that dominate star schemas),
record a 0-d device overflow flag, and defer the truth test to the one
sync the query already pays at collect.  If any flag fired, the action
replays with speculation disabled (exact, sync-per-join sizing).

Reference analog: the retry-OOM framework (RmmRapidsRetryIterator.scala)
re-executes work when a resource guess was wrong; here the guessed
resource is an output shape instead of memory.
"""

from __future__ import annotations

import contextvars
import threading
from typing import List

_LOCK = threading.Lock()
#: active context stack — a contextvar, so concurrent collects on
#: different threads never see each other's contexts.  Partition tasks on
#: the pool run inside a COPY of the submitting thread's context
#: (plan/base.py iter_partition_tasks), which routes their overflow flags
#: to the right collect.
_STACK: "contextvars.ContextVar[tuple]" = contextvars.ContextVar(
    "speculation_stack", default=())
#: replay mode: operators must size exactly (same contextvar propagation)
_DISABLED: "contextvars.ContextVar[int]" = contextvars.ContextVar(
    "speculation_disabled", default=0)


class SpeculationOverflow(Exception):
    """A speculative bucket was too small; the action must replay."""


class SpeculationContext:
    def __init__(self):
        self._flags = []
        self._lock = threading.Lock()

    def add(self, flag) -> None:
        """Registers a 0-d bool device array: True = overflow."""
        with self._lock:
            self._flags.append(flag)

    def check(self) -> None:
        """ONE device sync over every flag; raises on any overflow."""
        with self._lock:
            flags, self._flags = self._flags, []
        if not flags:
            return
        import numpy as np
        from spark_rapids_tpu.columnar.column import _jnp
        jnp = _jnp()
        # flags produced by shard-local pipelines are committed to
        # DIFFERENT devices under a mesh — they cannot meet in one
        # stack; group per device so the sync count stays one per
        # device, not one per flag
        by_dev: dict = {}
        for f in flags:
            devices = getattr(f, "devices", None)
            key = None
            if callable(devices):
                try:
                    key = tuple(sorted(d.id for d in devices()))
                except Exception:  # noqa: BLE001 - placement probe only
                    key = None
            by_dev.setdefault(key, []).append(f)
        from spark_rapids_tpu.aux import transitions as TR
        if any(bool(TR.fetch(jnp.any(jnp.stack(group)),
                             site="speculation-overflow"))
               for group in by_dev.values()):
            raise SpeculationOverflow()


def active() -> "SpeculationContext | None":
    if _DISABLED.get():
        return None
    stack = _STACK.get()
    return stack[-1] if stack else None


class speculation_scope:
    """``with speculation_scope() as ctx:`` — ctx is None in replay mode."""

    def __enter__(self):
        if _DISABLED.get():
            self._ctx = None
            self._token = None
            return None
        self._ctx = SpeculationContext()
        self._token = _STACK.set(_STACK.get() + (self._ctx,))
        return self._ctx

    def __exit__(self, *exc):
        if self._token is not None:
            _STACK.reset(self._token)
        return False


class no_speculation:
    """Replay mode: every operator sizes exactly (sync-per-decision)."""

    def __enter__(self):
        self._token = _DISABLED.set(_DISABLED.get() + 1)

    def __exit__(self, *exc):
        _DISABLED.reset(self._token)
        return False
