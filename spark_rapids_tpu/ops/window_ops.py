"""Device window kernels: one fused sort + segmented-scan program per spec.

Reference: the cuDF rolling/scan aggregations behind GpuWindowExpression
(GpuWindowExpression.scala maps frames to RollingAggregation/ScanAggregation)
and the batched algorithms in window/GpuRunningWindowExec.scala etc.

TPU-first design: the whole spec group — sort by (partition, order) keys,
partition/peer boundary detection, and EVERY window column — is one jitted
XLA program over static shapes:

- running (unbounded-preceding) aggregates: ``cumsum`` / segmented
  ``associative_scan`` re-based at partition starts; RANGE frames gather the
  running value at each row's last peer (Spark's default frame includes
  peers of the current row).
- whole-partition aggregates: ``segment_*`` reductions broadcast back.
- bounded ROWS frames: sum/count/mean via prefix-array gathers
  (``c[hi] - c[lo-1]``); min/max via an unrolled gather over the (small,
  static) frame width — the exec tags wide frames back to CPU.
- ranking: row_number/rank/dense_rank from partition/peer first positions;
  lag/lead are bounds-checked gathers.

Window column specs (``funcs``) are tuples:
  ("row_number",) | ("rank",) | ("dense_rank",) | ("ntile", n)
  ("offset", value_ordinal, signed_row_offset)           # lag/lead
  ("agg", kind, value_ordinal, frame_kind, lo, hi, count_valid_only)
     kind in sum|count|min|max|mean; lo/hi are row/peer offsets or None
     (unbounded); frame_kind "rows"|"range" ("range" only with lo=None and
     hi in (0, None) — Spark's default frames)
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import numpy as np

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.batch import ColumnarBatch
from spark_rapids_tpu.columnar.column import DeviceColumn

# widest bounded ROWS frame lowered to the unrolled min/max gather
MAX_UNROLLED_FRAME = 256


def _jx():
    from spark_rapids_tpu.columnar.column import _jnp
    return _jnp()




def _col_sig(c: DeviceColumn) -> Tuple:
    return (str(c.data.dtype), tuple(c.data.shape), c.lengths is not None)


def _seg_scan(vals, boundary, combine, jnp):
    """Segmented inclusive scan: restarts ``combine`` at boundary rows."""
    import jax

    def op(a, b):
        av, af = a
        bv, bf = b
        return jnp.where(bf, bv, combine(av, bv)), af | bf

    out, _ = jax.lax.associative_scan(op, (vals, boundary))
    return out


def _identity_for(kind: str, dtype, jnp):
    if kind == "min":
        if jnp.issubdtype(dtype, jnp.inexact):
            return jnp.asarray(np.inf, dtype)
        return jnp.asarray(jnp.iinfo(dtype).max, dtype)
    if jnp.issubdtype(dtype, jnp.inexact):
        return jnp.asarray(-np.inf, dtype)
    return jnp.asarray(jnp.iinfo(dtype).min, dtype)


def compute_windows(batch: ColumnarBatch, num_payload: int, num_pkeys: int,
                    order_specs: Sequence[Tuple[int, bool, bool]],
                    funcs: Sequence[Tuple],
                    out_dtypes: Optional[Sequence[T.DataType]] = None,
                    ) -> ColumnarBatch:
    """``batch`` columns = payload ++ partition keys ++ order keys ++ value
    inputs; returns sorted payload ++ one column per func.  ``order_specs``
    are (ordinal, ascending, nulls_first) into the batch."""
    import jax
    jnp = _jx()
    from spark_rapids_tpu.ops.sort_ops import SortOrder, _order_words
    from spark_rapids_tpu.ops.agg_ops import _masked_group_words
    bucket = batch.bucket
    funcs = tuple(tuple(f) for f in funcs)
    key = ("window", tuple(_col_sig(c) for c in batch.columns), num_payload,
           num_pkeys, tuple(order_specs), funcs)
    pk_range = range(num_payload, num_payload + num_pkeys)

    def build():
        dtypes = [c.data_type for c in batch.columns]
        orders = [SortOrder(i, True, True) for i in pk_range] + \
            [SortOrder(o, a, nf) for o, a, nf in order_specs]

        def run(arrs, row_count):
            cols = [DeviceColumn(d, v, bucket, dtypes[i], ln)
                    for i, (d, v, ln) in enumerate(arrs)]
            rowpos = jnp.arange(bucket, dtype=np.int64)
            inrow = rowpos < row_count
            # ---- sort by partition keys then order keys, padding last ----
            words = [(~inrow).astype(np.int8)]
            for o in orders:
                words.extend(_order_words(cols[o.ordinal], o, jnp))
            perm = jax.lax.sort(
                tuple(words) + (rowpos.astype(np.int32),),
                num_keys=len(words), is_stable=True)[-1]
            scols = []
            for c in cols:
                d = jnp.take(c.data, perm, axis=0)
                v = jnp.take(c.validity, perm, axis=0)
                ln = None if c.lengths is None else \
                    jnp.take(c.lengths, perm, axis=0)
                scols.append(DeviceColumn(d, v, bucket, c.data_type, ln))
            # ---- partition / peer boundaries ----
            def boundaries(idxs):
                b = jnp.zeros(bucket, dtype=bool).at[0].set(True)
                for i in idxs:
                    for w in _masked_group_words(scols[i], jnp):
                        diff = w[1:] != w[:-1] if w.ndim == 1 else \
                            jnp.any(w[1:] != w[:-1], axis=-1)
                        b = b.at[1:].max(diff)
                return b | (rowpos == row_count)

            seg_b = boundaries(list(pk_range))
            peer_b = boundaries(list(pk_range) +
                                [o for o, _, _ in order_specs])
            seg = jnp.cumsum(seg_b.astype(np.int64)) - 1
            # first/last row position of each row's partition / peer group
            def first_last(bnd):
                gid = jnp.cumsum(bnd.astype(np.int64)) - 1
                fp = jax.ops.segment_min(rowpos, gid, num_segments=bucket)
                lp = jax.ops.segment_max(jnp.where(inrow, rowpos, -1), gid,
                                         num_segments=bucket)
                return jnp.take(fp, gid), jnp.take(lp, gid)

            sfp, slp = first_last(seg_b)
            pfp, plp = first_last(peer_b)
            slp = jnp.maximum(slp, sfp)    # all-padding tail safety
            plp = jnp.maximum(plp, pfp)
            outs = []
            for f in funcs:
                outs.append(_one_func(f, scols, jnp, rowpos, inrow, seg,
                                      sfp, slp, pfp, plp, bucket, row_count))
            payload = [(c.data, c.validity, c.lengths)
                       for c in scols[:num_payload]]
            return payload, outs

        return run
    from spark_rapids_tpu.exec.stage_compiler import get_or_build
    fn = get_or_build("window.frame", key, build)
    from spark_rapids_tpu.columnar.column import rc_traceable
    arrs = [(c.data, c.validity, c.lengths) for c in batch.columns]
    payload, outs = fn(arrs, rc_traceable(batch.row_count))
    cols = []
    for (d, v, ln), proto in zip(payload, batch.columns[:num_payload]):
        cols.append(DeviceColumn(d, v, batch.row_count, proto.data_type, ln))
    for i, ((d, v, ln), f) in enumerate(zip(outs, funcs)):
        dt = out_dtypes[i] if out_dtypes is not None else None
        if dt is not None and ln is None and dt.np_dtype is not None and \
                d.dtype != np.dtype(dt.np_dtype):
            d = d.astype(dt.np_dtype)
        cols.append(DeviceColumn(d, v, batch.row_count, dt, ln))
    return ColumnarBatch(cols, batch.row_count, None)


def _one_func(f, scols, jnp, rowpos, inrow, seg, sfp, slp, pfp, plp,
              bucket, row_count):
    """One window output column -> (data, valid, lengths)."""
    kind = f[0]
    if kind == "row_number":
        return ((rowpos - sfp + 1).astype(np.int32), inrow, None)
    if kind == "rank":
        return ((pfp - sfp + 1).astype(np.int32), inrow, None)
    if kind == "dense_rank":
        # segment-rebased count of peer-group starts
        peer_start = (rowpos == pfp).astype(np.int64)
        c = jnp.cumsum(peer_start)
        dense = c - jnp.take(c, sfp) + 1
        return (dense.astype(np.int32), inrow, None)
    if kind == "ntile":
        n = f[1]
        cnt = slp - sfp + 1
        pos = rowpos - sfp
        base, rem = cnt // n, cnt % n
        # first `rem` tiles get base+1 rows
        big = rem * (base + 1)
        tile = jnp.where(pos < big, pos // jnp.maximum(base + 1, 1),
                         rem + (pos - big) // jnp.maximum(base, 1))
        return ((tile + 1).astype(np.int32), inrow, None)
    if kind == "offset":
        _, vo, off, dflt = f
        c = scols[vo]
        idx = rowpos + off
        ok = (idx >= sfp) & (idx <= slp) & inrow
        safe = jnp.clip(idx, 0, bucket - 1)
        d = jnp.take(c.data, safe, axis=0)
        v = jnp.take(c.validity, safe, axis=0) & ok
        ln = None if c.lengths is None else jnp.take(c.lengths, safe, axis=0)
        if dflt is not None:     # scalar default for out-of-partition rows
            d = jnp.where(ok, d, jnp.asarray(dflt, dtype=d.dtype))
            v = v | (~ok & inrow)
        return (d, v, ln)
    if kind == "agg":
        _, agg, vo, fkind, lo, hi, cvo = f
        c = scols[vo]
        present = c.validity & inrow
        # frame end positions per row (row offsets, clamped to partition)
        if fkind == "range":
            if lo is not None:
                raise NotImplementedError("bounded RANGE start")
            lo_pos = sfp
            hi_pos = slp if hi is None else plp      # peers of current row
        else:
            lo_pos = sfp if lo is None else jnp.maximum(rowpos + lo, sfp)
            hi_pos = slp if hi is None else jnp.minimum(rowpos + hi, slp)
        empty = hi_pos < lo_pos
        if agg in ("sum", "count", "mean"):
            if agg == "count" and not cvo:
                src = inrow
            else:
                src = present

            def win(csum, zrow):
                at_hi = jnp.take(csum, jnp.clip(hi_pos, 0, bucket - 1),
                                 axis=0)
                lo_c = jnp.clip(lo_pos, 0, bucket - 1)
                at_lo = jnp.take(csum, lo_c, axis=0) - \
                    jnp.take(zrow, lo_c, axis=0)
                return at_hi - at_lo

            n_ = jnp.cumsum(src.astype(np.int64))
            cnt = win(n_, src.astype(np.int64))
            cnt = jnp.where(empty, 0, cnt)
            if agg == "count":
                return (cnt.astype(np.int64), inrow, None)
            x = c.data
            is_float = jnp.issubdtype(x.dtype, jnp.inexact)
            if is_float:
                # the prefix-sum difference trick NaN/inf-poisons: one NaN
                # (or inf: inf - inf = NaN) anywhere in the batch corrupts
                # every LATER window, across segment boundaries.  Sum the
                # finite values only and recover IEEE results from exact
                # integer occurrence counters per window.
                isn = jnp.isnan(x)
                isp = present & (x == np.inf)
                ism = present & (x == -np.inf)
                nan_i = (present & isn).astype(np.int64)
                z = jnp.where(present & ~isn & ~isp & ~ism, x,
                              jnp.zeros_like(x))
            else:
                z = jnp.where(present, x, jnp.zeros_like(x))
            cs = jnp.cumsum(z, axis=0)
            s = win(cs, z)
            s = jnp.where(empty | (cnt == 0), jnp.zeros_like(s), s)
            if is_float:
                nan_w = win(jnp.cumsum(nan_i), nan_i) > 0
                p_i = isp.astype(np.int64)
                m_i = ism.astype(np.int64)
                p_w = win(jnp.cumsum(p_i), p_i) > 0
                m_w = win(jnp.cumsum(m_i), m_i) > 0
                s = jnp.where(nan_w | (p_w & m_w),
                              jnp.asarray(np.nan, s.dtype),
                              jnp.where(p_w, jnp.asarray(np.inf, s.dtype),
                                        jnp.where(m_w,
                                                  jnp.asarray(-np.inf,
                                                              s.dtype), s)))
            ok = inrow & (cnt > 0)
            if agg == "sum":
                return (s, ok, None)
            mean = s / jnp.where(cnt > 0, cnt, 1).astype(s.dtype)
            return (mean, ok, None)
        if agg in ("min", "max"):
            # Spark NaN-greatest float semantics: min skips NaN (NaN only
            # when the frame has no real value); max is NaN when any NaN
            # is present.  NaN must not ride jnp.minimum/maximum (both
            # propagate it unconditionally).
            ident = _identity_for(agg, c.data.dtype, jnp)
            is_float = jnp.issubdtype(c.data.dtype, jnp.inexact)
            if is_float:
                isn = jnp.isnan(c.data)
                pres_val = present & ~isn      # contributes a real value
                # aux indicator: min -> "any real value"; max -> "any NaN"
                pres_aux = pres_val if agg == "min" else (present & isn)
                nanv = jnp.asarray(np.nan, c.data.dtype)
            else:
                pres_val = present
                pres_aux = None
            z = jnp.where(pres_val, c.data, ident)
            op = jnp.minimum if agg == "min" else jnp.maximum

            def patch(d, aux):
                if not is_float:
                    return d
                if agg == "min":
                    return jnp.where(aux, d, nanv)
                return jnp.where(aux, nanv, d)

            bounded = lo is not None and hi is not None and fkind == "rows"
            if bounded:
                acc = jnp.full(bucket, ident, dtype=c.data.dtype)
                got = jnp.zeros(bucket, dtype=bool)
                got_aux = jnp.zeros(bucket, dtype=bool)
                for off in range(lo, hi + 1):
                    idx = rowpos + off
                    ok_i = (idx >= lo_pos) & (idx <= hi_pos)
                    safe = jnp.clip(idx, 0, bucket - 1)
                    val = jnp.take(z, safe, axis=0)
                    pv = jnp.take(pres_val, safe, axis=0) & ok_i
                    acc = jnp.where(pv, op(acc, val), acc)
                    got = got | (jnp.take(present, safe, axis=0) & ok_i)
                    if is_float:
                        got_aux = got_aux | \
                            (jnp.take(pres_aux, safe, axis=0) & ok_i)
                return (patch(acc, got_aux), got & inrow, None)
            seg_b_here = rowpos == sfp
            if lo is None and (hi is None or fkind == "range" or hi == 0):
                run_f = _seg_scan(z, seg_b_here, op, jnp)
                have_f = _seg_scan(present.astype(np.int32), seg_b_here,
                                   jnp.add, jnp) > 0
                aux_f = None if not is_float else _seg_scan(
                    pres_aux.astype(np.int32), seg_b_here, jnp.add, jnp) > 0
                if hi is None:       # whole partition
                    pos = slp
                else:
                    pos = plp if fkind == "range" else rowpos
                d = jnp.take(run_f, pos, axis=0)
                v = jnp.take(have_f, pos, axis=0)
                if is_float:
                    d = patch(d, jnp.take(aux_f, pos, axis=0))
                return (d, v & inrow, None)
            if hi is None and lo == 0 and fkind == "rows":
                # current-to-unbounded: reversed segmented scan
                z_r = z[::-1]
                # boundary in reversed domain = last row of each partition
                b_r = (rowpos == slp)[::-1]
                run_r = _seg_scan(z_r, b_r, op, jnp)[::-1]
                have_r = _seg_scan(present[::-1].astype(np.int32), b_r,
                                   jnp.add, jnp)[::-1] > 0
                d = run_r
                if is_float:
                    aux_r = _seg_scan(pres_aux[::-1].astype(np.int32), b_r,
                                      jnp.add, jnp)[::-1] > 0
                    d = patch(d, aux_r)
                return (d, have_r & inrow, None)
            raise NotImplementedError(f"min/max frame {fkind} {lo} {hi}")
        raise NotImplementedError(f"window agg {agg}")
    raise NotImplementedError(f"window func {kind}")
