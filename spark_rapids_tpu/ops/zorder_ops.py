"""Z-order (Morton) interleaving kernels.

Reference: org/apache/spark/sql/rapids/zorder/ + JNI ``ZOrder``/
``InterleaveBits``/``GpuHilbertLongIndex`` — Delta OPTIMIZE ZORDER BY
clusters files by the interleaved bit pattern of the key columns.

Pure bit arithmetic over int64 lanes: rank-normalize each key to uint32
(order-preserving), then interleave bits round-robin — elementwise jnp
ops that fuse on device."""

from __future__ import annotations

from typing import List, Sequence

import numpy as np


def _to_u32_rank(col, xp):
    """Order-preserving map of an int64 column to [0, 2^32): flip the sign
    bit of the top 32 bits (the reference's InterleaveBits does the same
    sign-flip trick per type width)."""
    v = xp.asarray(col).astype(np.int64)
    # compress to 32 bits preserving order for the common value ranges:
    # take the high 32 of (v - min) when wide, else v - min directly
    return v


def interleave_bits(cols: Sequence, xp=np, bits: int = 21):
    """Interleaves the low ``bits`` of each normalized key column into one
    int64 z-value (k * bits <= 63).  Keys are first shifted to be
    non-negative (order preserved)."""
    k = len(cols)
    if k == 0:
        raise ValueError("zorder needs at least one column")
    bits = min(bits, 63 // k)
    norm = []
    for c in cols:
        v = xp.asarray(c).astype(np.int64)
        v = v - v.min() if xp is np else v - xp.min(v)
        # clamp into the bit budget (top bits dropped order-preservingly
        # by scaling when the range overflows)
        maxv = int(v.max()) if xp is np else None
        if xp is np and maxv is not None and maxv >= (1 << bits):
            shift = maxv.bit_length() - bits
            v = v >> shift
        norm.append(v)
    z = xp.zeros_like(norm[0])
    for b in range(bits):
        for ci, v in enumerate(norm):
            bit = (v >> np.int64(b)) & np.int64(1)
            z = z | (bit << np.int64(b * k + ci))
    return z


def zorder_permutation(cols: Sequence, xp=np):
    """Row ordering by z-value (the OPTIMIZE ZORDER sort key)."""
    z = interleave_bits(cols, xp)
    return xp.argsort(z, stable=True)
