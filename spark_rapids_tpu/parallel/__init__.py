"""Distributed execution: device meshes and collective data movement.

Reference: SURVEY.md §2.8 — the reference's distributed backend is a UCX
RDMA peer-to-peer shuffle (shuffle-plugin/, RapidsShuffleClient/Server,
bounce buffers, heartbeats).  The TPU-native equivalent replaces the whole
transport stack with XLA collectives over ICI (within a slice) / DCN
(across slices): a hash shuffle is ONE fused program — partition, pack,
``all_to_all``, compact — with no serialization, no bounce buffers, and no
control-plane protocol (the collective is the protocol).
"""

from spark_rapids_tpu.parallel.mesh import (MeshContext,  # noqa: F401
                                            active_mesh, data_mesh,
                                            set_active_mesh)
from spark_rapids_tpu.parallel.collective import (  # noqa: F401
    collective_hash_shuffle, shard_batch, unshard_batch)
from spark_rapids_tpu.parallel.spmd import (SpmdHbmExceeded,  # noqa: F401
                                            spmd_hash_exchange)
