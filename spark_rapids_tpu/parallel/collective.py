"""Device-resident collective shuffle: hash partition + all_to_all, fused.

Reference: the UCX peer-to-peer shuffle (SURVEY.md §2.8 mode 3) keeps map
output ON DEVICE (ShuffleBufferCatalog) and moves blocks over RDMA with
bounce buffers and a flatbuffers control plane.  The TPU-native redesign
collapses all of that into one SPMD program per signature:

    per device (shard_map over the 1-D ``data`` mesh axis):
      1. stable-sort local rows by destination partition id
      2. pack rows into a [n_dev, B] send buffer (destination-major;
         quota = the full local bucket B, so no overflow is possible —
         ICI collectives need static shapes, SURVEY.md §7 hard part 3)
      3. ``lax.all_to_all`` the send buffer + per-destination counts
      4. compact received blocks to the front; the only host syncs are the
         per-device received totals

No serialization, no host copies, no heartbeat protocol: the collective IS
the transport, and partial-failure handling rides the runtime (a lost chip
fails the whole step — Spark-style stage retry re-runs it; the reference
reaches the same end state via fetch-failure => stage retry).

Data layout: "sharded batches" are global jax arrays of shape
[n_dev * B, ...] with axis 0 sharded over the mesh; each device owns a
padded local bucket B with its own logical row count (``counts`` vector,
one entry per device).
"""

from __future__ import annotations

from typing import List, Sequence

import numpy as np

from spark_rapids_tpu.columnar.batch import HostColumnarBatch
from spark_rapids_tpu.columnar.column import DeviceColumn, bucket_rows
from spark_rapids_tpu.parallel.mesh import MeshContext


def _jx():
    from spark_rapids_tpu.columnar.column import _jnp
    return _jnp()


def shard_batch(ctx: MeshContext, host_batches: Sequence[HostColumnarBatch]):
    """Distributes host batches round-robin to mesh devices: returns
    (cols, counts) in the sharded-batch layout above.  ``cols`` is a list
    of (data, validity, lengths) global arrays."""
    import jax
    jnp = _jx()
    n = ctx.num_devices
    per_dev: List[List[HostColumnarBatch]] = [[] for _ in range(n)]
    for i, hb in enumerate(host_batches):
        per_dev[i % n].append(hb)
    from spark_rapids_tpu.columnar.batch import concat_host_batches
    merged = [concat_host_batches(bs) if bs else host_batches[0].slice(0, 0)
              for bs in per_dev]
    B = bucket_rows(max(1, max(hb.row_count for hb in merged)))
    locals_ = [hb.to_device(B) for hb in merged]
    sharding = ctx.data_sharding()
    cols = []
    for ci in range(locals_[0].num_columns):
        parts_d = [lb.columns[ci].data for lb in locals_]
        parts_v = [lb.columns[ci].validity for lb in locals_]
        # string columns: align widths before stacking
        if locals_[0].columns[ci].lengths is not None:
            w = max(int(p.shape[1]) for p in parts_d)
            parts_d = [jnp.pad(p, ((0, 0), (0, w - p.shape[1])))
                       for p in parts_d]
            parts_l = [lb.columns[ci].lengths for lb in locals_]
            ln = jax.device_put(jnp.concatenate(parts_l), sharding)
        else:
            ln = None
        d = jax.device_put(jnp.concatenate(parts_d), sharding)
        v = jax.device_put(jnp.concatenate(parts_v), sharding)
        cols.append((d, v, ln))
    counts = jax.device_put(
        jnp.asarray([lb.row_count for lb in locals_], dtype=np.int64),
        ctx.data_sharding())
    return cols, counts


def unshard_batch(ctx: MeshContext, cols, counts,
                  dtypes, names=None) -> HostColumnarBatch:
    """Gathers a sharded batch back to one host batch (driver collect)."""
    import pyarrow as pa
    from spark_rapids_tpu import types as T
    from spark_rapids_tpu.columnar.batch import concat_host_batches
    from spark_rapids_tpu.columnar.column import HostColumn
    n = ctx.num_devices
    counts_h = np.asarray(counts)
    total_bucket = int(cols[0][0].shape[0])
    B = total_bucket // n
    # one device->host transfer per column; slices assembled host-side
    host = [(np.asarray(d), np.asarray(v),
             None if ln is None else np.asarray(ln)) for d, v, ln in cols]
    batches = []
    for dev in range(n):
        cnt = int(counts_h[dev])
        lo = dev * B
        dev_cols = []
        for (d, v, ln), dt in zip(host, dtypes):
            vv = v[lo:lo + cnt]
            if isinstance(dt, (T.StringType, T.BinaryType)):
                # packed-bytes repr: reuse the device column decoder
                dc = DeviceColumn(_jx().asarray(d[lo:lo + B]),
                                  _jx().asarray(v[lo:lo + B]), cnt, dt,
                                  _jx().asarray(ln[lo:lo + B]))
                dev_cols.append(dc.to_host())
            elif isinstance(dt, T.DecimalType) and dt.is_decimal128:
                # two-limb physical repr: reuse the device column decoder
                dc = DeviceColumn(_jx().asarray(d[lo:lo + B]),
                                  _jx().asarray(v[lo:lo + B]), cnt, dt)
                dev_cols.append(dc.to_host())
            else:
                dev_cols.append(HostColumn.from_numpy(d[lo:lo + cnt], vv,
                                                      dt))
        batches.append(HostColumnarBatch(dev_cols, cnt, names))
    return concat_host_batches(batches)


def shard_engine_batches(ctx: MeshContext, batches, schema):
    """Places engine batches (host or device ColumnarBatch) into the
    sharded-batch layout: the single-controller input-pipeline step of the
    SPMD model (scan output -> device_put with a NamedSharding); all
    subsequent shuffle/compute rides the mesh."""
    from spark_rapids_tpu.columnar.batch import (ColumnarBatch,
                                                 HostColumnarBatch)
    host = []
    for b in batches:
        if isinstance(b, ColumnarBatch):
            b = b.to_host()
        host.append(b)
    if not host:
        import pyarrow as pa
        from spark_rapids_tpu import types as T
        from spark_rapids_tpu.columnar.batch import batch_from_arrow
        empty = pa.table({f.name: pa.array([], type=T.to_arrow(f.data_type))
                          for f in schema.fields})
        host = [batch_from_arrow(empty)]
    return shard_batch(ctx, host)


def shard_to_batch(ctx: MeshContext, cols, counts, schema, shard: int):
    """Reduce-side read: materializes mesh shard ``shard`` as a regular
    engine ColumnarBatch (the reduce task's fetch; all data already sits on
    that device).

    The shard planes are COPIED (a device-local copy, no transfer):
    ``addressable_shards[i].data`` shares buffers with the exchange's
    global arrays, and downstream consumers legitimately register their
    input batches spillable and ``.delete()`` them (the out-of-core agg
    merge does) — deleting a shared buffer would poison the exchange
    store for every re-read of the same shard (task retry, plan
    reuse)."""
    jnp = _jx()
    from spark_rapids_tpu.columnar.batch import ColumnarBatch
    n = ctx.num_devices
    cnt = int(np.asarray(counts)[shard])
    out_cols = []
    for (d, v, ln), f in zip(cols, schema.fields):
        ds = jnp.copy(d.addressable_shards[shard].data)
        vs = jnp.copy(v.addressable_shards[shard].data)
        ls = None if ln is None else \
            jnp.copy(ln.addressable_shards[shard].data)
        out_cols.append(DeviceColumn(ds, vs, cnt, f.data_type, ls))
    return ColumnarBatch(out_cols, cnt,
                         [f.name for f in schema.fields])


def collective_hash_shuffle(ctx: MeshContext, cols, counts, pids):
    """The fused distributed shuffle.

    cols: [(data [n*B, ...], validity [n*B], lengths [n*B] | None)]
    counts: [n] per-device logical row counts
    pids: [n*B] destination device per row (int32, any value for padding)

    Returns (cols', counts') in the same layout: device d ends up with
    every row whose pid == d, bucket n*B per device.

    Chaos point ``parallel.collective`` fires here (a lost chip fails the
    whole SPMD step); the exchange catches the retryable failure and
    degrades to the host-staged per-partition path instead of failing
    the query.
    """
    from spark_rapids_tpu.aux.faults import maybe_fire
    maybe_fire("parallel.collective")
    import jax
    from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P
    jnp = _jx()
    n = ctx.num_devices
    total = int(cols[0][0].shape[0])
    B = total // n
    sig = tuple((str(d.dtype), tuple(d.shape), ln is not None)
                for d, v, ln in cols)
    mesh_key = tuple(d.id for d in ctx.mesh.devices.flat)
    key = (mesh_key, n, B, sig)

    def build():
        axis = ctx.data_axis

        def per_device(arrs, count, pids):
            # local shapes: arrs [B, ...], count [1], pids [B]
            count = count[0]
            rowpos = jnp.arange(B, dtype=np.int32)
            inrow = rowpos < count
            dest = jnp.where(inrow, jnp.clip(pids, 0, n - 1), n)
            # 1. destination-major stable order
            order = jnp.argsort(dest, stable=True)
            sdest = jnp.take(dest, order)
            dcount = jnp.bincount(sdest, length=n + 1)[:n]
            doff = jnp.cumsum(dcount) - dcount
            # 2. pack [n, B] send buffers (slot = rank within destination)
            slot = rowpos - jnp.take(doff, jnp.clip(sdest, 0, n - 1))
            flat = jnp.where(sdest < n,
                             jnp.clip(sdest, 0, n - 1) * B + slot, n * B)
            send_counts = dcount.astype(np.int64)

            def pack(x):
                shape = (n * B,) + x.shape[1:]
                buf = jnp.zeros(shape, dtype=x.dtype)
                xs = jnp.take(x, order, axis=0)
                return buf.at[flat].set(xs, mode="drop") \
                    .reshape((n, B) + x.shape[1:])

            # 3. exchange: block d of my send buffer -> device d
            recv_counts = jax.lax.all_to_all(
                send_counts.reshape(n, 1), axis, 0, 0, tiled=False
            ).reshape(n)
            outs = []
            for (d, v, ln) in arrs:
                rd = jax.lax.all_to_all(pack(d), axis, 0, 0, tiled=False)
                rv = jax.lax.all_to_all(pack(v), axis, 0, 0, tiled=False)
                rl = None if ln is None else jax.lax.all_to_all(
                    pack(ln), axis, 0, 0, tiled=False)
                outs.append((rd, rv, rl))
            # 4. compact received blocks to the front
            blockpos = jnp.arange(B, dtype=np.int64)
            live = blockpos[None, :] < recv_counts[:, None]   # [n, B]
            live_flat = live.reshape(n * B)
            corder = jnp.argsort(~live_flat, stable=True)
            new_count = jnp.sum(recv_counts)
            final = []
            for (rd, rv, rl) in outs:
                fd = jnp.take(rd.reshape((n * B,) + rd.shape[2:]), corder,
                              axis=0)
                fv = jnp.take(rv.reshape(n * B) & live_flat, corder, axis=0)
                fl = None if rl is None else jnp.take(rl.reshape(n * B),
                                                      corder, axis=0)
                final.append((fd, fv, fl))
            return final, new_count.reshape(1)

        def build_specs(template, spec):
            return jax.tree_util.tree_map(lambda _: spec, template)

        return shard_map(per_device, mesh=ctx.mesh,
                         in_specs=(build_specs([tuple(c) for c in cols],
                                               P(axis)),
                                   P(axis), P(axis)),
                         out_specs=(build_specs([tuple(c) for c in cols],
                                                P(axis)), P(axis)),
                         check_rep=False)

    # memoized by (mesh, devices, bucket, schema shapes) in the shared
    # executable cache: a fresh jax.jit here re-traced the whole SPMD
    # shuffle program on EVERY collective exchange
    from spark_rapids_tpu.exec.stage_compiler import get_or_build
    prog = get_or_build("parallel.collective_shuffle", key, build)
    arrs = [tuple(c) for c in cols]
    out, new_counts = prog(arrs, counts, pids)
    return [tuple(o) for o in out], new_counts
