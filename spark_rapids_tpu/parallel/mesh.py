"""Device mesh management (the GpuDeviceManager analog for multi-chip).

Reference: GpuDeviceManager.scala picks ONE device per executor process;
on TPU the executor instead owns a ``jax.sharding.Mesh`` slice and SPMD
programs span it.  The canonical SQL-engine mesh is 1-D over a ``data``
axis (partition data-parallelism, SURVEY.md §2.9); multi-host pods keep
the same mesh with devices spanning hosts — XLA routes collectives over
ICI within a slice and DCN across slices without code changes.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Sequence


@dataclasses.dataclass
class MeshContext:
    mesh: object                 # jax.sharding.Mesh
    data_axis: str = "data"

    @property
    def num_devices(self) -> int:
        return int(self.mesh.devices.size)

    def data_sharding(self, *extra_dims_replicated: int):
        """NamedSharding placing axis 0 on the data axis."""
        from jax.sharding import NamedSharding, PartitionSpec
        spec = PartitionSpec(self.data_axis)
        return NamedSharding(self.mesh, spec)

    def replicated(self):
        from jax.sharding import NamedSharding, PartitionSpec
        return NamedSharding(self.mesh, PartitionSpec())


_ACTIVE: Optional[MeshContext] = None
#: True when the ACTIVE mesh was built by sync_from_conf — conf-driven
#: disable tears down only what conf activated; a mesh installed
#: manually via set_active_mesh stays under manual control
_CONF_ACTIVATED = False


def data_mesh(n_devices: Optional[int] = None,
              devices: Optional[Sequence] = None) -> MeshContext:
    """Builds the 1-D data-parallel mesh over available devices."""
    import jax
    from jax.sharding import Mesh
    import numpy as np
    devs = list(devices) if devices is not None else jax.devices()
    if n_devices is not None:
        devs = devs[:n_devices]
    mesh = Mesh(np.asarray(devs), ("data",))
    return MeshContext(mesh)


def set_active_mesh(ctx: Optional[MeshContext]) -> None:
    global _ACTIVE, _CONF_ACTIVATED
    _ACTIVE = ctx
    _CONF_ACTIVATED = False


def active_mesh() -> Optional[MeshContext]:
    return _ACTIVE


# ---------------------------------------------------------------------------
# conf-driven lifecycle (spark.rapids.mesh.*)
# ---------------------------------------------------------------------------

def parse_mesh_shape(s: str) -> tuple:
    """'' -> () (all devices, 1-D); '2,4' -> (2, 4).  Raises ValueError
    on malformed input (the conf checker runs the same parse, so a bad
    shape fails at set_conf, never at the first collective)."""
    s = str(s).strip()
    if not s:
        return ()
    try:
        dims = tuple(int(p) for p in s.split(","))
    except ValueError:
        raise ValueError(f"spark.rapids.mesh.shape must be "
                         f"comma-separated ints, got {s!r}")
    if not dims or any(d <= 0 for d in dims):
        raise ValueError(f"spark.rapids.mesh.shape extents must be "
                         f"positive, got {s!r}")
    return dims


def parse_mesh_axes(s: str) -> tuple:
    names = tuple(p.strip() for p in str(s).split(","))
    if not all(names):
        raise ValueError(f"spark.rapids.mesh.axes names must be "
                         f"non-empty, got {s!r}")
    if len(set(names)) != len(names):
        raise ValueError(f"spark.rapids.mesh.axes names must be "
                         f"unique, got {s!r}")
    return names


def sync_from_conf(conf, allow_disable: bool = False
                   ) -> Optional[MeshContext]:
    """Validates ``spark.rapids.mesh.*`` and, when enabled, builds and
    activates the mesh (emitting a ``meshTopology`` event).  Validation
    always runs — a session carrying a malformed shape fails at
    set_conf/init even with the mesh disabled; the divides-device-count
    check needs the device list so it lives here rather than in the
    conf checker.

    Disable semantics: with ``enabled=false`` AND ``allow_disable``
    (the explicit ``set_conf`` path), a mesh THIS function activated is
    torn down — disabling the feature must not be a silent no-op.
    Session INIT passes ``allow_disable=False``: an interleaved
    default-conf session must not clobber another session's
    conf-activated mesh (the scan-cache/lockorder discipline).  A mesh
    installed manually via set_active_mesh is never touched."""
    global _CONF_ACTIVATED
    from spark_rapids_tpu import config as C
    shape = parse_mesh_shape(conf.get(C.MESH_SHAPE.key))
    axes = parse_mesh_axes(conf.get(C.MESH_AXES.key))
    if len(axes) != (len(shape) if shape else 1):
        raise ValueError(
            f"spark.rapids.mesh.axes has {len(axes)} name(s) for a "
            f"{len(shape) if shape else 1}-D spark.rapids.mesh.shape"
            + ("" if shape else " (empty shape means 1-D)"))
    if not conf.get(C.MESH_ENABLED.key):
        if allow_disable and _CONF_ACTIVATED:
            set_active_mesh(None)
        return active_mesh()
    import jax
    import numpy as np
    from jax.sharding import Mesh
    devs = list(jax.devices())
    if shape:
        want = 1
        for d in shape:
            want *= d
        if want > len(devs) or len(devs) % want:
            raise ValueError(
                f"spark.rapids.mesh.shape {shape} needs {want} "
                f"device(s) dividing the visible count "
                f"({len(devs)} available)")
        mesh = Mesh(np.asarray(devs[:want]).reshape(shape), axes)
    else:
        mesh = Mesh(np.asarray(devs), (axes[0],))
    ctx = MeshContext(mesh, data_axis=axes[0])
    set_active_mesh(ctx)
    _CONF_ACTIVATED = True
    from spark_rapids_tpu.aux.events import emit
    emit("meshTopology", devices=ctx.num_devices,
         shape=list(mesh.devices.shape), axes=list(mesh.axis_names),
         data_axis=ctx.data_axis,
         platform=str(getattr(mesh.devices.flat[0], "platform", "?")))
    return ctx
