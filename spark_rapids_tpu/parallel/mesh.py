"""Device mesh management (the GpuDeviceManager analog for multi-chip).

Reference: GpuDeviceManager.scala picks ONE device per executor process;
on TPU the executor instead owns a ``jax.sharding.Mesh`` slice and SPMD
programs span it.  The canonical SQL-engine mesh is 1-D over a ``data``
axis (partition data-parallelism, SURVEY.md §2.9); multi-host pods keep
the same mesh with devices spanning hosts — XLA routes collectives over
ICI within a slice and DCN across slices without code changes.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Sequence


@dataclasses.dataclass
class MeshContext:
    mesh: object                 # jax.sharding.Mesh
    data_axis: str = "data"

    @property
    def num_devices(self) -> int:
        return int(self.mesh.devices.size)

    def data_sharding(self, *extra_dims_replicated: int):
        """NamedSharding placing axis 0 on the data axis."""
        from jax.sharding import NamedSharding, PartitionSpec
        spec = PartitionSpec(self.data_axis)
        return NamedSharding(self.mesh, spec)

    def replicated(self):
        from jax.sharding import NamedSharding, PartitionSpec
        return NamedSharding(self.mesh, PartitionSpec())


_ACTIVE: Optional[MeshContext] = None


def data_mesh(n_devices: Optional[int] = None,
              devices: Optional[Sequence] = None) -> MeshContext:
    """Builds the 1-D data-parallel mesh over available devices."""
    import jax
    from jax.sharding import Mesh
    import numpy as np
    devs = list(devices) if devices is not None else jax.devices()
    if n_devices is not None:
        devs = devs[:n_devices]
    mesh = Mesh(np.asarray(devs), ("data",))
    return MeshContext(mesh)


def set_active_mesh(ctx: Optional[MeshContext]) -> None:
    global _ACTIVE
    _ACTIVE = ctx


def active_mesh() -> Optional[MeshContext]:
    return _ACTIVE
