"""In-mesh SPMD hash exchange: the device-resident shuffle path.

Replaces the host-staged writer/reader whenever producer and consumer
both live on the mesh: map output is placed onto the devices ONCE (the
single-controller input-pipeline step), the per-row destination ids are
computed by a compiled program, and ONE fused ``shard_map`` all-to-all
(parallel/collective.py) is the entire shuffle — no serialization, no
host copies, partition p of the result IS device p's shard (the
mesh-axis binding the planner's distribution pass records).

Every program here is compiled through ``exec/stage_compiler.py`` like
the rest of the engine, so collective shuffles are cached, trace-counted
and audit-ledgered programs, not ad-hoc jits.

Spill safety: the collective needs the whole sharded working set
resident per device (send buffer + receive buffer + compaction copies,
all at the padded bucket).  ``SpmdHbmExceeded`` is raised when that
estimate does not fit the per-device HBM headroom — a host-side
pre-check runs BEFORE any device allocation, and an exact padded-shape
check runs after sharding but before the collective; the exchange
catches it and degrades to the existing host-staged ShuffleClient
path, which spills — the per-stage ICI-vs-host decision the mesh-aware
AQE relies on.
"""

from __future__ import annotations

import time
from typing import List, Optional, Tuple

import numpy as np

from spark_rapids_tpu.parallel.mesh import MeshContext

__all__ = ["SpmdHbmExceeded", "spmd_hash_exchange",
           "estimate_shard_bytes", "check_hbm_budget"]

#: the collective's per-device working set as a multiple of the local
#: shard's padded bytes: input shard + packed [n, B] send buffer +
#: received [n, B] buffer + the compacted output copy
WORKING_SET_FACTOR = 4


class SpmdHbmExceeded(Exception):
    """The sharded working set does not fit per-device HBM; caller must
    fall back to the host-staged (spillable) shuffle path."""

    def __init__(self, need: int, budget: int):
        super().__init__(f"collective working set ~{need} bytes exceeds "
                         f"per-device budget {budget} bytes")
        self.need = need
        self.budget = budget


def estimate_shard_bytes(cols, n_devices: int) -> int:
    """Per-device padded bytes of a sharded batch (one local bucket of
    every plane), computable from shapes without a device sync."""
    total = 0
    for d, v, ln in cols:
        total += d.size * d.dtype.itemsize
        total += v.size * v.dtype.itemsize
        if ln is not None:
            total += ln.size * ln.dtype.itemsize
    return total // max(1, n_devices)


def _hbm_budget() -> Optional[int]:
    """Per-device headroom for the collective working set: half the free
    pool (the same policy point every out-of-core trigger uses), or
    None when no runtime is initialized (primitive-level tests)."""
    from spark_rapids_tpu.memory.device_manager import free_device_headroom
    return free_device_headroom(2)


def check_hbm_budget(per_device_bytes: int,
                     budget: Optional[int]) -> None:
    """THE working-set admission policy: raises ``SpmdHbmExceeded`` when
    ``per_device_bytes`` at WORKING_SET_FACTOR exceeds ``budget``.  Every
    check site (the exchange's incremental drain, the host pre-check,
    the exact post-shard check) routes here so the model cannot
    diverge between callers."""
    if budget is not None and \
            per_device_bytes * WORKING_SET_FACTOR > budget:
        raise SpmdHbmExceeded(per_device_bytes * WORKING_SET_FACTOR,
                              budget)


def _pid_program(ctx: MeshContext, partitioning, schema, cols):
    """The compiled per-row destination-id program, memoized by
    (partitioning, schema, plane shapes) — the hash evaluates over the
    GLOBAL sharded arrays so one dispatch covers every device."""
    from spark_rapids_tpu.exec.stage_compiler import get_or_build
    from spark_rapids_tpu.expressions.base import EvalContext, TCol

    total = int(cols[0][0].shape[0])

    def build():
        def pid_fn(arrs):
            tcols = [TCol(d, v, f.data_type, lengths=ln)
                     for (d, v, ln), f in zip(arrs, schema.fields)]
            ectx = EvalContext(tcols, "tpu", total)
            h = partitioning._hash_expr().eval_tpu(ectx)
            n = np.int32(partitioning.num_partitions)
            return (((h.data % n) + n) % n).astype(np.int32)
        return pid_fn

    key = (partitioning.desc(),
           tuple((f.name, str(f.data_type)) for f in schema.fields),
           tuple((str(d.dtype), tuple(d.shape), ln is not None)
                 for d, v, ln in cols))
    return get_or_build("spmd.pid", key, build)


def spmd_hash_exchange(ctx: MeshContext, batches, schema, partitioning
                       ) -> Tuple[List, object]:
    """The whole in-mesh shuffle: shard ``batches`` over the mesh,
    compute destinations, run the fused all-to-all, and report the
    result's per-shard row statistics (mesh-aware AQE's runtime input)
    in an ``iciExchange`` event.  Returns (cols, counts) in the sharded
    layout of parallel/collective.py.

    Raises ``SpmdHbmExceeded`` (before touching the collective) when
    the padded working set cannot fit per-device HBM — the caller's cue
    to take the host-staged spill-safe path instead."""
    from spark_rapids_tpu.aux.events import emit
    from spark_rapids_tpu.parallel import collective as C

    t0 = time.monotonic()
    budget = _hbm_budget()
    if budget is not None:
        # host-side pre-check BEFORE any device allocation: the logical
        # input bytes per device lower-bound the padded shard, so an
        # input that cannot possibly fit never pays the transfer (and
        # never risks dying in device_put with an unclassifiable
        # allocator error instead of the clean fallback)
        host_bytes = sum(getattr(b, "nbytes", lambda: 0)() or 0
                         for b in batches)
        check_hbm_budget(host_bytes // max(1, ctx.num_devices), budget)
    cols, counts = C.shard_engine_batches(ctx, batches, schema)
    # exact post-shard check: padding (pow2 buckets, string rectangles)
    # can inflate the working set well past the logical estimate
    shard_bytes = estimate_shard_bytes(cols, ctx.num_devices)
    check_hbm_budget(shard_bytes, budget)
    pids = _pid_program(ctx, partitioning, schema, cols)(
        [tuple(c) for c in cols])
    out_cols, out_counts = C.collective_hash_shuffle(ctx, cols, counts,
                                                     pids)
    # the per-shard totals are the only host sync of the whole exchange;
    # forcing them here makes the measured duration honest AND gives the
    # adaptive layer its runtime row statistics for free
    shard_rows = [int(c) for c in np.asarray(out_counts)]
    emit("iciExchange", devices=ctx.num_devices,
         rows=int(sum(shard_rows)), shard_rows=shard_rows,
         shard_bytes=shard_bytes,
         duration_s=round(time.monotonic() - t0, 6))
    return out_cols, out_counts
