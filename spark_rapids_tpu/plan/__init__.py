"""Plan layer: physical plans, the TPU plan-rewrite framework, transitions.

Reference counterparts (SURVEY.md §2.3):
- ``GpuOverrides.scala`` rule registries + applyWithContext  -> ``overrides``
- ``RapidsMeta.scala`` wrap/tag/convert                      -> ``meta``
- ``TypeChecks.scala`` TypeSig                               -> ``typechecks``
- ``GpuTransitionOverrides.scala`` transitions/coalesce      -> ``transitions``
- ``ExplainPlan.scala`` + explainOnly mode                   -> ``overrides.explain``

Architectural note: the reference plugs into Spark, whose CPU operators are
row-based; its transitions are row<->columnar AND host<->device.  This
framework ships its own columnar CPU engine (arrow-backed) as the fallback
tier, so transitions collapse to host<->device copies (``HostToDeviceExec`` /
``DeviceToHostExec`` mirroring GpuRowToColumnarExec/GpuColumnarToRowExec).
"""

from spark_rapids_tpu.plan.base import (  # noqa: F401
    Exec, LeafExec, UnaryExec, BinaryExec, is_device_exec)


def __getattr__(name):
    # lazy: overrides imports exec modules, which import plan.base; an eager
    # import here would make `import spark_rapids_tpu.exec.basic` circular
    if name in ("TpuOverrides",):
        from spark_rapids_tpu.plan.overrides import TpuOverrides
        return TpuOverrides
    if name in ("PlanMeta", "tag_and_convert"):
        from spark_rapids_tpu.plan import meta
        return getattr(meta, name)
    raise AttributeError(name)
