"""Physical plan node base classes.

Reference: ``GpuExec.scala`` (trait GpuExec :214 internalDoExecuteColumnar)
and Spark's SparkPlan.  Every exec produces an iterator of columnar batches
per partition:

- device execs ("Tpu*Exec") yield ``ColumnarBatch`` (jax arrays, padded)
- host execs (the CPU fallback engine) yield ``HostColumnarBatch`` (arrow)

Partitioning model: a plan executes as ``num_partitions`` independent
partitions (Spark task analog); sources define the count, narrow ops
preserve it, exchanges change it (shuffle layer).
"""

from __future__ import annotations

from typing import Iterator, List, Optional, Sequence

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.batch import ColumnarBatch, HostColumnarBatch


class Exec:
    """Physical operator."""

    #: True when this exec runs on the device and yields ColumnarBatch
    is_device = False

    def __init__(self, children: Sequence["Exec"] = ()):
        self.children: List[Exec] = list(children)
        self.metrics = {}

    # -- static shape -------------------------------------------------------
    @property
    def schema(self) -> T.StructType:
        raise NotImplementedError

    @property
    def num_partitions(self) -> int:
        if self.children:
            return self.children[0].num_partitions
        return 1

    @property
    def name(self) -> str:
        return type(self).__name__

    def node_desc(self) -> str:
        return self.name

    # -- execution ----------------------------------------------------------
    def execute_partition(self, pidx: int):
        """Yields batches for one partition (host or device per is_device)."""
        raise NotImplementedError

    def execute_all(self):
        for p in range(self.num_partitions):
            yield from run_task(self, p)

    def collect_host(self) -> HostColumnarBatch:
        """Gathers every partition to one host batch (driver collect)."""
        from spark_rapids_tpu.columnar.batch import (batch_from_pydict,
                                                     concat_host_batches)
        out = []
        for b in self.execute_all():
            if isinstance(b, ColumnarBatch):
                b = b.to_host()
            out.append(b)
        if not out:
            import pyarrow as pa
            empty = pa.table({f.name: pa.array([], type=T.to_arrow(f.data_type))
                              for f in self.schema})
            from spark_rapids_tpu.columnar.batch import batch_from_arrow
            return batch_from_arrow(empty)
        return concat_host_batches(out)

    # -- tree utilities -----------------------------------------------------
    def with_children(self, children: List["Exec"]) -> "Exec":
        import copy
        node = copy.copy(self)
        node.children = list(children)
        return node

    def transform_up(self, fn) -> "Exec":
        node = self.with_children([c.transform_up(fn) for c in self.children])
        return fn(node)

    def collect_nodes(self, pred=lambda n: True) -> List["Exec"]:
        out = []
        for c in self.children:
            out.extend(c.collect_nodes(pred))
        if pred(self):
            out.append(self)
        return out

    def tree_string(self, indent: int = 0) -> str:
        pad = "  " * indent
        mark = "*" if self.is_device else " "
        lines = [f"{pad}{mark}{self.node_desc()}"]
        for c in self.children:
            lines.append(c.tree_string(indent + 1))
        return "\n".join(lines)

    def __repr__(self):
        return self.node_desc()


def run_task(plan: "Exec", pidx: int):
    """Drives one partition as a task: the device semaphore (acquired by any
    device section during execution) is fully released at completion, like
    the reference's task-completion listener (GpuSemaphore.scala:51-120)."""
    try:
        yield from plan.execute_partition(pidx)
    finally:
        from spark_rapids_tpu.memory.device_manager import get_runtime
        rt = get_runtime()
        if rt is not None:
            rt.semaphore.release_all()


class LeafExec(Exec):
    def __init__(self):
        super().__init__([])


class UnaryExec(Exec):
    def __init__(self, child: Exec):
        super().__init__([child])

    @property
    def child(self) -> Exec:
        return self.children[0]

    @property
    def schema(self) -> T.StructType:
        return self.child.schema


class BinaryExec(Exec):
    def __init__(self, left: Exec, right: Exec):
        super().__init__([left, right])

    @property
    def left(self) -> Exec:
        return self.children[0]

    @property
    def right(self) -> Exec:
        return self.children[1]


def is_device_exec(node: Exec) -> bool:
    return node.is_device
