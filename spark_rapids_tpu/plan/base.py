"""Physical plan node base classes.

Reference: ``GpuExec.scala`` (trait GpuExec :214 internalDoExecuteColumnar)
and Spark's SparkPlan.  Every exec produces an iterator of columnar batches
per partition:

- device execs ("Tpu*Exec") yield ``ColumnarBatch`` (jax arrays, padded)
- host execs (the CPU fallback engine) yield ``HostColumnarBatch`` (arrow)

Partitioning model: a plan executes as ``num_partitions`` independent
partitions (Spark task analog); sources define the count, narrow ops
preserve it, exchanges change it (shuffle layer).
"""

from __future__ import annotations

import contextlib
import itertools
import threading
from typing import Iterator, List, Optional, Sequence

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.batch import ColumnarBatch, HostColumnarBatch

#: process-wide task-thread count for execute_all; set from
#: ``spark.rapids.tpu.taskParallelism`` each time TpuOverrides.apply prepares
#: a plan (execs carry no conf).  0 = auto (min(4, cpu_count)).
_task_parallelism = 0
#: unique task ids across the process — partition indexes would collide when
#: independent plans execute concurrently (semaphore/metrics key on this)
_task_ids = itertools.count(1)

#: monotone execution-epoch source: every prepared action (and every
#: speculation replay / plan-cache re-execution) draws a fresh epoch and
#: stamps it onto the plan's per-execution caches (CTE materialization),
#: so batches cached by a previous action are never replayed stale
_execution_epochs = itertools.count(1)


def next_execution_epoch() -> int:
    return next(_execution_epochs)


def set_task_parallelism(n: int) -> None:
    global _task_parallelism
    _task_parallelism = n


#: per-task OOM injection mode from spark.rapids.sql.test.injectRetryOOM:
#: 'false' | 'true' (first tracked alloc of each task) | '<n>' (n-th)
_task_oom_injection = "false"


def set_task_oom_injection(mode: str) -> None:
    global _task_oom_injection
    _task_oom_injection = (mode or "false").strip().lower()


def _arm_task_injection() -> None:
    from spark_rapids_tpu.memory.retry import force_retry_oom
    mode = _task_oom_injection
    if mode in ("", "false"):
        # disarm: an injection left unconsumed by the previous task on
        # this pooled thread must not fire in an unrelated query
        force_retry_oom(0)
        return
    if mode == "true":
        force_retry_oom(1, framed_only=True)
    else:
        try:
            nth = int(mode)
        except ValueError:
            force_retry_oom(0)
            return
        force_retry_oom(1, skip=max(0, nth - 1), framed_only=True)


def effective_task_parallelism() -> int:
    import os
    n = _task_parallelism
    if n <= 0:
        n = min(4, os.cpu_count() or 1)
    return n


#: task-retry policy from spark.rapids.task.* (set by TpuOverrides.apply,
#: same module-global pattern as _task_parallelism)
_task_max_failures = 2
_breaker_threshold = 3


def set_task_retry_policy(max_failures: int, breaker_threshold: int) -> None:
    global _task_max_failures, _breaker_threshold
    _task_max_failures = max(1, int(max_failures))
    _breaker_threshold = max(0, int(breaker_threshold))


def _is_retryable(exc: BaseException) -> bool:
    """Failures worth re-attempting: transient data-movement errors and
    injected chaos.  Logic errors (TypeError, AssertionError, ...) are
    not — re-running deterministic breakage just hides it."""
    from spark_rapids_tpu.aux.faults import InjectedFault
    return isinstance(exc, (InjectedFault, ConnectionError, TimeoutError))


def _should_retry_task(e: BaseException, produced: int, attempts: int,
                       p: int, breaker=None, stop_on_trip: bool = False,
                       stop=None):
    """THE task-retry decision (shared by the serial/degraded iterator and
    the pooled driver so classification, budget, breaker accounting and
    the taskRetry emit cannot drift apart).  Returns (retry, zero_yield_
    retryable); emits taskRetry when retry is granted."""
    retryable = _is_retryable(e) and produced == 0
    if retryable and breaker is not None:
        breaker.record_failure()
    retry = (retryable and attempts < _task_max_failures
             and not (stop_on_trip and breaker is not None
                      and breaker.tripped)
             and not (stop is not None and stop.is_set()))
    if retry:
        from spark_rapids_tpu.aux.events import emit
        from spark_rapids_tpu.aux.faults import note_recovery
        note_recovery("task_retries")
        emit("taskRetry", pidx=p, attempt=attempts,
             error=f"{type(e).__name__}: {e}"[:160])
    return retry, retryable


def close_iter(it) -> None:
    """Explicitly closes a generator/iterator if it supports close().

    Abandoning a suspended generator leaves its cleanup to GC; the
    pipelined chains (exec/pipeline.py spools, spillable-queueing retry
    generators) need DETERMINISTIC close propagation so early exit
    releases queued spillables and stops producer threads immediately."""
    close = getattr(it, "close", None)
    if close is None:
        return
    try:
        close()
    except Exception:   # noqa: BLE001 - cleanup must not mask the cause
        pass


@contextlib.contextmanager
def closing_source(it):
    """``with closing_source(child.execute_partition(p)) as it:`` — the
    generator-chain form of ``close_iter``: whatever exits the block
    (exhaustion, failure, or a downstream ``.close()`` arriving as
    GeneratorExit) closes the source deterministically."""
    try:
        yield it
    finally:
        close_iter(it)


def _task_attempts_iter(task_fn, p: int, breaker=None):
    """Drives ``task_fn(p)`` with task-level retry: a retryable failure
    that strikes BEFORE the first item is yielded re-runs the task (fresh
    task id, fresh injection arming) up to the attempt budget; a failure
    after partial output cannot re-run without duplicating rows and
    propagates.  Each retryable failure feeds the stage breaker.  Used
    for serial stages AND as the degraded inline runner after a breaker
    trip (hence no stop_on_trip: the degraded path must keep retrying)."""
    attempts = 0
    while True:
        produced = 0
        it = task_fn(p)
        try:
            for item in it:
                produced += 1
                yield item
            return
        except GeneratorExit:
            raise
        except BaseException as e:
            attempts += 1
            retry, _ = _should_retry_task(e, produced, attempts, p,
                                          breaker)
            if not retry:
                raise
        finally:
            # runs on exhaustion (no-op), on failure, and when the
            # consumer closes THIS generator at the yield (GeneratorExit):
            # the task's chain tears down deterministically either way
            close_iter(it)


class Exec:
    """Physical operator."""

    #: True when this exec runs on the device and yields ColumnarBatch
    is_device = False

    def __init__(self, children: Sequence["Exec"] = ()):
        self.children: List[Exec] = list(children)
        self.metrics = {}
        # guards lazily-materialized per-exec state (shuffle stores,
        # broadcast build sides) against concurrent partition tasks;
        # with_children's copy.copy shares it, which only over-serializes
        self._exec_lock = threading.Lock()

    # -- static shape -------------------------------------------------------
    @property
    def schema(self) -> T.StructType:
        raise NotImplementedError

    @property
    def num_partitions(self) -> int:
        if self.children:
            return self.children[0].num_partitions
        return 1

    @property
    def name(self) -> str:
        return type(self).__name__

    def node_desc(self) -> str:
        return self.name

    # -- execution ----------------------------------------------------------
    def execute_partition(self, pidx: int):
        """Yields batches for one partition (host or device per is_device)."""
        raise NotImplementedError

    def execute_all(self):
        """Drives every partition as a task.  With taskParallelism > 1 a
        bounded thread pool runs partitions concurrently — host work
        (shuffle ser/deser, I/O, arrow) overlaps device dispatch, and the
        TpuSemaphore bounds device admission (reference: the executor's
        task slots + GpuSemaphore, GpuSemaphore.scala:51-120;
        RapidsShuffleInternalManagerBase.scala:120-218 thread pools).
        Batches are yielded in partition order regardless of completion
        order, so results stay deterministic."""
        yield from iter_partition_tasks(
            lambda p: run_task(self, p), self.num_partitions)

    def collect_host(self) -> HostColumnarBatch:
        """Gathers every partition to one host batch (driver collect).
        ``dl_spec_rows`` is stamped on the executed root by
        ``TpuOverrides.apply`` (spark.rapids.sql.collect.speculativeRows)
        so a fully-device plan — no DeviceToHost boundary above it —
        still honors the conf on this final download."""
        from spark_rapids_tpu.columnar.batch import (batch_from_pydict,
                                                     concat_host_batches)
        spec_rows = getattr(self, "dl_spec_rows", None)
        out = []
        for b in self.execute_all():
            if isinstance(b, ColumnarBatch):
                b = b.to_host(spec_rows=spec_rows)
            out.append(b)
        if not out:
            import pyarrow as pa
            empty = pa.table({f.name: pa.array([], type=T.to_arrow(f.data_type))
                              for f in self.schema})
            from spark_rapids_tpu.columnar.batch import batch_from_arrow
            return batch_from_arrow(empty)
        return concat_host_batches(out)

    # -- tree utilities -----------------------------------------------------
    def with_children(self, children: List["Exec"]) -> "Exec":
        import copy
        node = copy.copy(self)
        node.children = list(children)
        return node

    def transform_up(self, fn) -> "Exec":
        node = self.with_children([c.transform_up(fn) for c in self.children])
        return fn(node)

    def collect_nodes(self, pred=lambda n: True) -> List["Exec"]:
        out = []
        for c in self.children:
            out.extend(c.collect_nodes(pred))
        if pred(self):
            out.append(self)
        return out

    def tree_string(self, indent: int = 0) -> str:
        pad = "  " * indent
        mark = "*" if self.is_device else " "
        lines = [f"{pad}{mark}{self.node_desc()}"]
        for c in self.children:
            lines.append(c.tree_string(indent + 1))
        return "\n".join(lines)

    def __repr__(self):
        return self.node_desc()


def run_task(plan: "Exec", pidx: int):
    """Drives one partition as a task: a fresh task id + metrics bind to the
    executing thread for the duration, and the device semaphore (acquired by
    any device section during execution) is fully released at completion,
    like the reference's task-completion listener (GpuSemaphore.scala:51-120
    + RmmSpark thread-to-task registration)."""
    yield from run_task_iter(plan.execute_partition, pidx)


def run_task_iter(gen_fn, pidx: int):
    """``run_task`` semantics over an arbitrary per-partition generator —
    exchange map sides run through this so each map partition is a real
    task (own id, metrics, semaphore release at completion).  The task
    registers with the resource arbiter for its duration (the thread-state
    registry behind blocking allocation and the hung-query watchdog) and
    heartbeats once per yielded batch — the watchdog's last-progress
    signal."""
    from spark_rapids_tpu.memory.arbiter import get_arbiter
    from spark_rapids_tpu.memory.device_manager import get_runtime
    from spark_rapids_tpu.memory.metrics import task_scope
    task_id = next(_task_ids)
    rt = get_runtime()
    arb = get_arbiter()
    with task_scope(task_id, rt.metrics if rt is not None else None):
        # conf-driven per-task fault injection
        # (spark.rapids.sql.test.injectRetryOOM; reference
        # RapidsConf.scala:1541 TEST_RETRY_OOM_INJECTION_MODE)
        _arm_task_injection()
        # chaos layer: spark.rapids.chaos.task.run faults the task at
        # start — before any output — so the retry path stays lossless
        from spark_rapids_tpu.aux.faults import maybe_fire
        maybe_fire("task.run")
        arb.register_task(task_id)
        it = gen_fn(pidx)
        try:
            for item in it:
                arb.note_progress(task_id)
                yield item
        finally:
            # explicit close replaces the `yield from` delegation so
            # GeneratorExit/teardown still propagates into the chain
            close_iter(it)
            arb.deregister_task(task_id)
            rt = get_runtime()
            if rt is not None:
                rt.semaphore.release_all(task_id)


def release_semaphore_for_wait() -> None:
    """Releases the current task's device admission before a blocking wait
    on other tasks' progress (exchange materialization, broadcast build) —
    otherwise tasks holding every permit can all block on workers that need
    one.  Device sections re-acquire lazily afterwards.  Reference: the
    semaphore is released while a task blocks on a shuffle fetch
    (GpuShuffleExchangeExecBase / RapidsCachingReader wait paths)."""
    from spark_rapids_tpu.memory.device_manager import get_runtime
    rt = get_runtime()
    if rt is not None:
        rt.semaphore.release_all()


class _PartitionError:
    __slots__ = ("exc", "can_rerun")

    def __init__(self, exc: BaseException, can_rerun: bool = False):
        self.exc = exc
        #: True when the task failed retryably with ZERO items delivered —
        #: the consumer may re-run it inline (degraded mode) without
        #: duplicating output
        self.can_rerun = can_rerun


_DONE = object()


def iter_partition_tasks(task_fn, n: int, workers: Optional[int] = None):
    """Runs ``task_fn(p) -> iterator`` for ``p in range(n)`` and yields every
    produced item in partition order.

    With effective parallelism > 1 this is a windowed producer/consumer:
    each partition's items drain into its own bounded queue (caps buffered
    batches per partition), so partition p's items are being yielded while
    partitions p+1..p+workers-1 are already producing.  A stop event
    unblocks producers if the consumer abandons the generator (e.g. a
    short-circuiting limit).  Used by ``Exec.execute_all`` and by exchange
    map sides (the reference's task slots / multithreaded shuffle writer
    pools, RapidsShuffleInternalManagerBase.scala:120-218)."""
    from spark_rapids_tpu.aux.faults import CircuitBreaker
    if workers is None:
        workers = effective_task_parallelism()
    workers = min(workers, n)
    if workers <= 1:
        for p in range(n):
            yield from _task_attempts_iter(task_fn, p)
        return

    import queue as qmod
    from concurrent.futures import ThreadPoolExecutor

    qs = [qmod.Queue(maxsize=4) for _ in range(n)]
    stop = threading.Event()
    #: stage-scoped: repeated retryable task failures trip it, degrading
    #: the remainder of the stage to single-threaded inline execution in
    #: the consumer thread instead of failing the query
    breaker = CircuitBreaker(_breaker_threshold, name=f"stage-{n}p")

    def put(q, item) -> bool:
        released = False
        while True:
            try:
                q.put(item, timeout=0.05)
                return True
            except qmod.Full:
                if stop.is_set():
                    return False
                if not released:
                    # waiting on backpressure must not hold device
                    # admission: tasks parked on full queues would
                    # otherwise starve the partition the consumer is
                    # draining (permits re-acquire lazily at the next
                    # device section)
                    release_semaphore_for_wait()
                    released = True

    def drive(p: int) -> None:
        q = qs[p]
        attempts = 0
        try:
            while True:
                produced = 0
                it = task_fn(p)
                try:
                    for b in it:
                        produced += 1
                        if stop.is_set() or not put(q, b):
                            return
                    return
                except BaseException as e:  # propagated to the consumer
                    attempts += 1
                    retry, retryable = _should_retry_task(
                        e, produced, attempts, p, breaker,
                        stop_on_trip=True, stop=stop)
                    if retry:
                        continue
                    put(q, _PartitionError(e, can_rerun=retryable))
                    return
                finally:
                    # a consumer that abandoned the stage (stop set) must
                    # not leave this task's chain to GC: close releases
                    # queued spillables / prefetch threads upstream NOW
                    close_iter(it)
        finally:
            put(q, _DONE)

    # each task runs inside a COPY of the submitting thread's context so
    # contextvars (the speculation scope of the owning collect) propagate
    # to pool threads — two concurrent collects must not mix their
    # overflow flags
    import contextvars
    ctx = contextvars.copy_context()
    pool = ThreadPoolExecutor(max_workers=workers,
                              thread_name_prefix="tpu-task")
    try:
        for p in range(n):
            pool.submit(ctx.copy().run, drive, p)
        for p in range(n):
            while True:
                item = qs[p].get()
                if item is _DONE:
                    break
                if isinstance(item, _PartitionError):
                    if item.can_rerun and breaker.tripped:
                        # degraded mode: the breaker tripped on repeated
                        # faults — run this partition inline on THIS
                        # thread (single-threaded, no pool) instead of
                        # failing the query; zero items were delivered,
                        # so the re-run cannot duplicate output
                        while qs[p].get() is not _DONE:
                            pass
                        from spark_rapids_tpu.aux.events import emit
                        from spark_rapids_tpu.aux.faults import \
                            note_recovery
                        note_recovery("tasks_degraded")
                        emit("taskDegraded", pidx=p,
                             error=f"{type(item.exc).__name__}: "
                                   f"{item.exc}"[:160])
                        yield from _task_attempts_iter(task_fn, p,
                                                       breaker)
                        break
                    raise item.exc
                yield item
    finally:
        stop.set()
        for q in qs:  # unblock producers stuck on a full queue
            try:
                while True:
                    q.get_nowait()
            except qmod.Empty:
                pass
        pool.shutdown(wait=True, cancel_futures=True)


class LeafExec(Exec):
    def __init__(self):
        super().__init__([])


class UnaryExec(Exec):
    def __init__(self, child: Exec):
        super().__init__([child])

    @property
    def child(self) -> Exec:
        return self.children[0]

    @property
    def schema(self) -> T.StructType:
        return self.child.schema


class BinaryExec(Exec):
    def __init__(self, left: Exec, right: Exec):
        super().__init__([left, right])

    @property
    def left(self) -> Exec:
        return self.children[0]

    @property
    def right(self) -> Exec:
        return self.children[1]


def is_device_exec(node: Exec) -> bool:
    return node.is_device
