"""Cost-based optimizer.

Reference: CostBasedOptimizer.scala (531 LoC, invoked at
GpuOverrides.scala:4372-4387; conf ``spark.rapids.sql.optimizer.enabled``,
default off) — avoids device placement when host<->device transitions cost
more than the device speedup for a plan section.

Model: per-node row estimates propagate bottom-up; every op carries a
host-cost and device-cost factor (cost = rows * factor); a CONVERTIBLE
REGION (maximal connected set of device-capable metas) pays one transfer
per boundary row crossing.  Regions whose device saving does not cover
their transfer cost are reverted to the host engine with an explain-visible
reason — exactly the reference's section-based decision."""

from __future__ import annotations

from typing import Dict, List, Optional

from spark_rapids_tpu.plan.base import Exec
from spark_rapids_tpu.plan.meta import PlanMeta

DEFAULT_ROWS = 1_000_000

#: relative per-row cost factors (host, device); ops not listed use (1, .25)
_FACTORS = {
    "CpuProjectExec": (1.0, 0.1),
    "CpuFilterExec": (1.0, 0.1),
    "CpuHashAggregateExec": (4.0, 0.5),
    "CpuSortExec": (6.0, 0.8),
    "CpuShuffledHashJoinExec": (6.0, 0.8),
    "CpuBroadcastHashJoinExec": (4.0, 0.5),
    "CpuWindowExec": (6.0, 0.8),
    "CpuShuffleExchangeExec": (2.0, 1.0),   # host staging either way
    "CpuInMemoryScanExec": (0.2, 0.6),      # upload makes device pricier
}

#: cost of moving one row across the host<->device boundary
_TRANSFER_FACTOR = 0.5

#: fixed per-region overhead in row-equivalents (kernel dispatch + compile
#: cache lookup; keeps trivial row counts off the device, where the
#: reference's per-exec overhead terms play the same role)
_REGION_FIXED = 10_000.0


def estimate_rows(plan: Exec) -> int:
    """Bottom-up row estimate (reference: RowCountPlanVisitor).  Suffix
    matching covers BOTH engines' node names (CpuFilterExec and
    TpuFilterExec alike) so the machine-profile predictor below can
    estimate the rewritten TPU plan, not just the CPU input."""
    name = type(plan).__name__
    kids = [estimate_rows(c) for c in plan.children]
    if name.endswith("InMemoryScanExec"):
        try:
            return sum(b.row_count for part in plan.partitions
                       for b in part)
        except Exception:    # noqa: BLE001
            return DEFAULT_ROWS
    if name.endswith("RangeExec"):
        try:
            return max(0, (plan.end - plan.start) // plan.step)
        except Exception:    # noqa: BLE001
            return DEFAULT_ROWS
    if name.endswith("FilterExec"):
        return max(1, (kids[0] if kids else DEFAULT_ROWS) // 2)
    if name.endswith(("LimitExec", "GlobalLimitExec")):
        return min(getattr(plan, "n", DEFAULT_ROWS),
                   kids[0] if kids else DEFAULT_ROWS)
    if name.endswith(("HashAggregateExec", "FusedAggExec")):
        return max(1, (kids[0] if kids else DEFAULT_ROWS) // 10)
    if kids:
        return max(kids)
    return DEFAULT_ROWS


class CostBasedOptimizer:
    """Reverts device regions whose transfer overhead beats their
    speedup."""

    def __init__(self, conf):
        self.conf = conf

    def optimize(self, meta: PlanMeta) -> List[str]:
        """Mutates the tagged meta tree; returns explain notes."""
        notes: List[str] = []
        self._visit(meta, notes)
        return notes

    def _visit(self, meta: PlanMeta, notes: List[str]) -> None:
        # find maximal convertible regions via DFS over the meta tree
        if meta.can_run_on_device:
            region: List[PlanMeta] = []
            self._collect_region(meta, region)
            self._decide(region, notes)
            # children below the region continue independently
            for m in region:
                for cm in m.child_metas:
                    if not cm.can_run_on_device:
                        self._visit_children(cm, notes)
        else:
            self._visit_children(meta, notes)

    def _visit_children(self, meta: PlanMeta, notes: List[str]) -> None:
        for cm in meta.child_metas:
            self._visit(cm, notes)

    def _collect_region(self, meta: PlanMeta, out: List[PlanMeta]) -> None:
        out.append(meta)
        for cm in meta.child_metas:
            if cm.can_run_on_device:
                self._collect_region(cm, out)

    def _decide(self, region: List[PlanMeta], notes: List[str]) -> None:
        saving = 0.0
        transfer = 0.0
        members = set(id(m) for m in region)
        for m in region:
            rows = estimate_rows(m.plan)
            host_f, dev_f = _FACTORS.get(type(m.plan).__name__, (1.0, 0.25))
            saving += rows * (host_f - dev_f)
            # boundary edges: child outside the region -> upload rows
            for cm in m.child_metas:
                if id(cm) not in members:
                    transfer += estimate_rows(cm.plan) * _TRANSFER_FACTOR
        # the region root downloads its output + fixed region overhead
        transfer += estimate_rows(region[0].plan) * _TRANSFER_FACTOR
        transfer += _REGION_FIXED
        if saving < transfer:
            reason = (f"cost-based optimizer: device saving "
                      f"{saving:.0f} < transfer cost {transfer:.0f}")
            for m in region:
                m.will_not_work(reason)
            notes.append(f"{region[0].plan.name}: {reason}")


# ---------------------------------------------------------------------------
# calibrated machine-profile prediction (tools/history calibrate artifact)
# ---------------------------------------------------------------------------
#
# The factors above are static guesses; this layer predicts from what the
# machine actually measured.  `tools history calibrate` fits, per
# stage-kind family, t ≈ fixed_s_per_batch·batches + per_row_s·rows over
# the warehouse's accumulated span observations (plus H2D/D2H bandwidth
# from the transition ledger), and this module applies that fit to an
# un-run plan: rows from estimate_rows, batches from the partition
# count, bytes from the schema row width.  Strictly REPORT-ONLY — the
# `== Cost ==` explain section and the post-run predicted-vs-measured
# cross-check (aux/tracing.py) read it; nothing about plan selection or
# results changes.

MACHINE_PROFILE_SCHEMA = "spark-rapids-tpu-machine-profile"

#: one-slot (path, mtime) -> MachineProfile memo: explain() and every
#: query-end cross-check reload the same artifact
_PROFILE_CACHE: Dict = {}


class MachineProfile:
    """A loaded calibration artifact."""

    def __init__(self, doc: Dict):
        if doc.get("schema") != MACHINE_PROFILE_SCHEMA:
            raise ValueError(
                f"not a machine profile (schema={doc.get('schema')!r})")
        self.doc = doc
        self.version = int(doc.get("version", 0))
        self.stage_kinds: Dict[str, Dict] = doc.get("stage_kinds", {})
        self.transfer: Dict[str, Dict] = doc.get("transfer", {}) or {}
        self.residual_bound = float(doc.get("residual_bound", 0.0))
        self.runs = int(doc.get("runs", 0))
        self.observations = int(doc.get("observations", 0))

    @staticmethod
    def load(path: str) -> "MachineProfile":
        import json
        import os
        mtime = os.path.getmtime(path)
        hit = _PROFILE_CACHE.get(path)
        if hit is not None and hit[0] == mtime:
            return hit[1]
        with open(path, encoding="utf-8") as f:
            prof = MachineProfile(json.load(f))
        _PROFILE_CACHE[path] = (mtime, prof)
        return prof

    def predict_stage(self, family: str, rows: int,
                      batches: int) -> Optional[float]:
        e = self.stage_kinds.get(family)
        if e is None:
            return None
        return (float(e.get("fixed_s_per_batch", 0.0)) * max(batches, 1)
                + float(e.get("per_row_s", 0.0)) * max(rows, 0))

    def predict_transfer(self, direction: str, nbytes: int,
                         batches: int) -> Optional[float]:
        fit = self.transfer.get(direction)
        if not fit:
            return None
        bps = fit.get("bytes_per_s")
        t = float(fit.get("fixed_s", 0.0)) * max(batches, 1)
        if bps:
            t += nbytes / float(bps)
        return t


def load_machine_profile(path: str) -> Optional[MachineProfile]:
    """The artifact at ``path``, or None when it is missing/invalid —
    the annotation layer is report-only and must never fail a query."""
    try:
        return MachineProfile.load(path)
    except (OSError, ValueError, KeyError, TypeError):
        return None


def node_family(node_name: str) -> Optional[str]:
    """Stage-kind family of an exec node name (the audit vocabulary)."""
    from spark_rapids_tpu.tools.history.calibrate import family_for_node
    return family_for_node(node_name)


def _row_width(plan: Exec) -> int:
    try:
        return max(1, sum(f.data_type.default_size
                          for f in plan.schema.fields))
    except Exception:    # noqa: BLE001 - sizing guess, never fatal
        return 8


def _lazy_partitions_pending(plan: Exec) -> bool:
    """True when the subtree holds an adaptive shuffle reader whose
    specs are not yet materialized: its ``num_partitions`` EXECUTES the
    child exchange under the node's exec lock, so a live-console scrape
    (which holds the query lock) must never reach it — the executing
    query holds that exec lock and needs the query lock to record
    metrics."""
    if plan.__dict__.get("_specs", False) is None:
        return True
    return any(_lazy_partitions_pending(c) for c in plan.children)


def _est_batches(plan: Exec, live: bool = False) -> int:
    try:
        if live and _lazy_partitions_pending(plan):
            return 1
        return max(1, int(plan.num_partitions))
    except Exception:    # noqa: BLE001
        return 1


def predict_plan_costs(plan: Exec, profile: MachineProfile,
                       live: bool = False) -> List[Dict]:
    """Pre-order rows: one per plan node, ``predicted_s`` None when the
    profile has no calibration for the node's family.  ``live=True``
    restricts the walk to non-blocking reads (cached partition specs
    only) so it is safe WHILE the plan executes."""
    out: List[Dict] = []

    def walk(node: Exec, depth: int) -> None:
        name = type(node).__name__
        rows = estimate_rows(node)
        batches = _est_batches(node, live)
        family = node_family(name)
        pred = None
        if family in ("transfer.pack", "transfer.unpack"):
            direction = "h2d" if family == "transfer.pack" else "d2h"
            pred = profile.predict_transfer(
                direction, rows * _row_width(node), batches)
        if pred is None and family is not None:
            pred = profile.predict_stage(family, rows, batches)
        out.append({"node": name, "depth": depth, "family": family,
                    "rows": rows, "batches": batches,
                    "predicted_s": (None if pred is None
                                    else round(pred, 6))})
        for c in node.children:
            walk(c, depth + 1)

    walk(plan, 0)
    return out


def render_cost_section(rows: List[Dict],
                        profile: MachineProfile) -> str:
    """The ``== Cost ==`` explain section (report-only)."""
    total = sum(r["predicted_s"] for r in rows
                if r["predicted_s"] is not None)
    covered = sum(1 for r in rows if r["predicted_s"] is not None)
    lines = ["== Cost ==",
             f"machine profile v{profile.version} "
             f"({profile.runs} run(s), {profile.observations} obs, "
             f"residual bound ±{profile.residual_bound * 100:.1f}%); "
             f"predicted total {total:.6f}s over {covered}/{len(rows)} "
             "node(s)"]
    for r in rows:
        pred = ("-" if r["predicted_s"] is None
                else f"{r['predicted_s']:.6f}s")
        fam = r["family"] or "-"
        lines.append("  " * r["depth"]
                     + f"{r['node']} [{fam}] rows~{r['rows']} "
                       f"cost~{pred}")
    return "\n".join(lines)
