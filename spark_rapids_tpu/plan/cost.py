"""Cost-based optimizer.

Reference: CostBasedOptimizer.scala (531 LoC, invoked at
GpuOverrides.scala:4372-4387; conf ``spark.rapids.sql.optimizer.enabled``,
default off) — avoids device placement when host<->device transitions cost
more than the device speedup for a plan section.

Model: per-node row estimates propagate bottom-up; every op carries a
host-cost and device-cost factor (cost = rows * factor); a CONVERTIBLE
REGION (maximal connected set of device-capable metas) pays one transfer
per boundary row crossing.  Regions whose device saving does not cover
their transfer cost are reverted to the host engine with an explain-visible
reason — exactly the reference's section-based decision."""

from __future__ import annotations

from typing import Dict, List, Optional

from spark_rapids_tpu.plan.base import Exec
from spark_rapids_tpu.plan.meta import PlanMeta

DEFAULT_ROWS = 1_000_000

#: relative per-row cost factors (host, device); ops not listed use (1, .25)
_FACTORS = {
    "CpuProjectExec": (1.0, 0.1),
    "CpuFilterExec": (1.0, 0.1),
    "CpuHashAggregateExec": (4.0, 0.5),
    "CpuSortExec": (6.0, 0.8),
    "CpuShuffledHashJoinExec": (6.0, 0.8),
    "CpuBroadcastHashJoinExec": (4.0, 0.5),
    "CpuWindowExec": (6.0, 0.8),
    "CpuShuffleExchangeExec": (2.0, 1.0),   # host staging either way
    "CpuInMemoryScanExec": (0.2, 0.6),      # upload makes device pricier
}

#: cost of moving one row across the host<->device boundary
_TRANSFER_FACTOR = 0.5

#: fixed per-region overhead in row-equivalents (kernel dispatch + compile
#: cache lookup; keeps trivial row counts off the device, where the
#: reference's per-exec overhead terms play the same role)
_REGION_FIXED = 10_000.0


def estimate_rows(plan: Exec) -> int:
    """Bottom-up row estimate (reference: RowCountPlanVisitor)."""
    name = type(plan).__name__
    kids = [estimate_rows(c) for c in plan.children]
    if name == "CpuInMemoryScanExec":
        try:
            return sum(b.row_count for part in plan.partitions
                       for b in part)
        except Exception:    # noqa: BLE001
            return DEFAULT_ROWS
    if name == "CpuRangeExec":
        try:
            return max(0, (plan.end - plan.start) // plan.step)
        except Exception:    # noqa: BLE001
            return DEFAULT_ROWS
    if name == "CpuFilterExec":
        return max(1, (kids[0] if kids else DEFAULT_ROWS) // 2)
    if name in ("CpuLimitExec", "CpuGlobalLimitExec"):
        return min(getattr(plan, "n", DEFAULT_ROWS),
                   kids[0] if kids else DEFAULT_ROWS)
    if name == "CpuHashAggregateExec":
        return max(1, (kids[0] if kids else DEFAULT_ROWS) // 10)
    if kids:
        return max(kids)
    return DEFAULT_ROWS


class CostBasedOptimizer:
    """Reverts device regions whose transfer overhead beats their
    speedup."""

    def __init__(self, conf):
        self.conf = conf

    def optimize(self, meta: PlanMeta) -> List[str]:
        """Mutates the tagged meta tree; returns explain notes."""
        notes: List[str] = []
        self._visit(meta, notes)
        return notes

    def _visit(self, meta: PlanMeta, notes: List[str]) -> None:
        # find maximal convertible regions via DFS over the meta tree
        if meta.can_run_on_device:
            region: List[PlanMeta] = []
            self._collect_region(meta, region)
            self._decide(region, notes)
            # children below the region continue independently
            for m in region:
                for cm in m.child_metas:
                    if not cm.can_run_on_device:
                        self._visit_children(cm, notes)
        else:
            self._visit_children(meta, notes)

    def _visit_children(self, meta: PlanMeta, notes: List[str]) -> None:
        for cm in meta.child_metas:
            self._visit(cm, notes)

    def _collect_region(self, meta: PlanMeta, out: List[PlanMeta]) -> None:
        out.append(meta)
        for cm in meta.child_metas:
            if cm.can_run_on_device:
                self._collect_region(cm, out)

    def _decide(self, region: List[PlanMeta], notes: List[str]) -> None:
        saving = 0.0
        transfer = 0.0
        members = set(id(m) for m in region)
        for m in region:
            rows = estimate_rows(m.plan)
            host_f, dev_f = _FACTORS.get(type(m.plan).__name__, (1.0, 0.25))
            saving += rows * (host_f - dev_f)
            # boundary edges: child outside the region -> upload rows
            for cm in m.child_metas:
                if id(cm) not in members:
                    transfer += estimate_rows(cm.plan) * _TRANSFER_FACTOR
        # the region root downloads its output + fixed region overhead
        transfer += estimate_rows(region[0].plan) * _TRANSFER_FACTOR
        transfer += _REGION_FIXED
        if saving < transfer:
            reason = (f"cost-based optimizer: device saving "
                      f"{saving:.0f} < transfer cost {transfer:.0f}")
            for m in region:
                m.will_not_work(reason)
            notes.append(f"{region[0].plan.name}: {reason}")
