"""Partition-distribution analysis + redundant-exchange elision.

Spark's EnsureRequirements inserts an exchange wherever a node's
required distribution is not already delivered by its child; our
DataFrame/SQL layers instead insert exchanges EAGERLY (every join and
two-stage aggregate shuffles), so the dual pass lives here: propagate
the *delivered* distribution bottom-up through every exec and DELETE
the exchanges whose requirement the child already satisfies.  That is
where the distributed deficit lives (ROADMAP item 3 / Theseus,
PAPERS.md: data movement, not compute, dominates) — a co-partitioned
join re-shuffled both sides, and an aggregate above it re-shuffled the
join output over the very same keys.

The lattice (GpuPartitioning / Spark Distribution analog):

- ``UnknownDist``   — nothing known (scans, unions, round-robin).
- ``SingleDist``    — all rows in one partition.
- ``HashDist(keys, n)``  — row r lives in partition
  ``pmod(murmur3(keys(r)), n)`` (bit-exact Spark placement, so two
  sides delivering the same ``HashDist`` are co-partitioned pairwise).
- ``RangeDist(specs, n)`` — partitions hold consecutive key ranges in
  sort order (bounds may differ between producers; consumers of a
  range exchange only rely on the ordering property).

``mesh_axis`` is the NamedSharding analog: when the active mesh has
exactly ``n`` devices a hash distribution is additionally *bound* to
the mesh's data axis — partition p IS device p's shard, which is what
lets the in-mesh exchange (parallel/spmd.py) keep shuffled data
device-resident and lets downstream stages run on their shard without
any transfer.

Key expressions are compared by a canonical structural form over bound
ordinals (``canon``), remapped through projections/aggregate keys as
the distribution flows up, so renames and Alias wrappers cannot break
(or spuriously allow) a match.

The pass is gated by ``spark.rapids.sql.distribution.enabled``; when
off the plan is returned untouched (bit-for-bit today's trees — pinned
by tests/test_distribution.py).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, FrozenSet, List, Optional, Tuple

from spark_rapids_tpu.plan.base import Exec

__all__ = ["canon", "HashDist", "RangeDist", "SingleDist",
           "delivered_dists", "required_dist",
           "eliminate_redundant_exchanges", "Elision"]


# ---------------------------------------------------------------------------
# the lattice
# ---------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class SingleDist:
    def desc(self) -> str:
        return "single"


@dataclasses.dataclass(frozen=True)
class HashDist:
    keys: Tuple            # tuple of canonical key forms, in hash order
    n: int
    #: mesh data-axis name when partition i is device i's shard (the
    #: NamedSharding-style binding); purely descriptive for matching —
    #: two hash distributions co-partition regardless of residency
    mesh_axis: Optional[str] = None

    def desc(self) -> str:
        ax = f"@{self.mesh_axis}" if self.mesh_axis else ""
        return f"hash[{len(self.keys)}k,{self.n}]{ax}"

    def matches(self, other: "HashDist") -> bool:
        return self.keys == other.keys and self.n == other.n


@dataclasses.dataclass(frozen=True)
class RangeDist:
    specs: Tuple           # ((canon key, ascending, nulls_first), ...)
    n: int

    def desc(self) -> str:
        return f"range[{len(self.specs)}k,{self.n}]"


# ---------------------------------------------------------------------------
# canonical key forms
# ---------------------------------------------------------------------------

def canon(e) -> Tuple:
    """Canonical structural form of a bound expression: Alias-transparent,
    ordinals for references, literals by (type, value).  Two expressions
    with equal canon forms evaluate identically over the same input
    batch — the equivalence hash-partition matching needs."""
    from spark_rapids_tpu.expressions.base import (Alias, BoundReference,
                                                   Literal)
    if isinstance(e, Alias):
        return canon(e.children[0])
    if isinstance(e, BoundReference):
        return ("ref", e.ordinal)
    if isinstance(e, Literal):
        return ("lit", str(e.data_type), repr(e.value))
    return (type(e).__name__,) + tuple(canon(c) for c in e.children)


def _shift_refs(form: Tuple, by: int) -> Tuple:
    if not isinstance(form, tuple):
        return form
    if form and form[0] == "ref":
        return ("ref", form[1] + by)
    return tuple(_shift_refs(f, by) if isinstance(f, tuple) else f
                 for f in form)


def _remap(form: Tuple, out_map: Dict[Tuple, Tuple]) -> Optional[Tuple]:
    """Re-expresses a canonical key over a node's OUTPUT ordinals given
    ``out_map`` (canonical child-space expression -> ("ref", j)).  An
    exact projected column wins; otherwise the form survives only if
    every reference inside it is itself projected through.  Returns
    None when the key's inputs do not survive the node."""
    if form in out_map:
        return out_map[form]
    if not isinstance(form, tuple) or not form:
        return form
    if form[0] == "ref":
        return None            # bare reference not passed through
    if form[0] == "lit":
        return form
    head, rest = form[0], form[1:]
    mapped = []
    for f in rest:
        m = _remap(f, out_map) if isinstance(f, tuple) else f
        if m is None:
            return None
        mapped.append(m)
    return (head,) + tuple(mapped)


def _remap_dists(dists, out_exprs) -> FrozenSet:
    """Pushes a delivered-distribution set through a projection-like node
    whose output column j computes ``out_exprs[j]`` over the child."""
    return _remap_by_map(dists, {canon(e): ("ref", j)
                                 for j, e in enumerate(out_exprs)})


# ---------------------------------------------------------------------------
# delivered distributions, bottom-up
# ---------------------------------------------------------------------------

def _mesh_axis_for(n: int) -> Optional[str]:
    from spark_rapids_tpu.parallel.mesh import active_mesh
    ctx = active_mesh()
    if ctx is not None and ctx.num_devices == n:
        return ctx.data_axis
    return None


def required_dist(partitioning):
    """The distribution an exchange with ``partitioning`` delivers —
    equally, what its consumer requires of it."""
    from spark_rapids_tpu.plan.partitioning import (HashPartitioning,
                                                    RangePartitioning,
                                                    SinglePartitioning)
    if isinstance(partitioning, SinglePartitioning):
        return SingleDist()
    if isinstance(partitioning, HashPartitioning):
        return HashDist(tuple(canon(k) for k in partitioning.key_exprs),
                        partitioning.num_partitions,
                        _mesh_axis_for(partitioning.num_partitions))
    if isinstance(partitioning, RangePartitioning):
        return RangeDist(tuple((canon(s.expr), s.ascending,
                                s.effective_nulls_first)
                               for s in partitioning.specs),
                         partitioning.num_partitions)
    return None      # round-robin: placement is positional, never reusable


def delivered_dists(node: Exec,
                    memo: Optional[Dict[int, FrozenSet]] = None
                    ) -> FrozenSet:
    """The set of distributions ``node``'s output provably satisfies.
    Handles BOTH the pre-convert Cpu tree (where the elision pass runs)
    and the final mixed Cpu/Tpu tree (where plan/verify.py re-checks),
    by duck-typing the few structural shapes that matter and treating
    everything else as unknown."""
    if memo is None:
        memo = {}
    key = id(node)
    if key in memo:
        return memo[key]
    memo[key] = frozenset()     # cycle guard (plans are DAGs, not cycles)
    out = _delivered(node, memo)
    if node.num_partitions == 1:
        out = out | {SingleDist()}
    memo[key] = out
    return out


def _child_dists(node: Exec, memo) -> FrozenSet:
    return delivered_dists(node.children[0], memo) if node.children \
        else frozenset()


def _delivered(node: Exec, memo) -> FrozenSet:    # noqa: C901 - dispatch
    import spark_rapids_tpu.ops.join_ops as J
    from spark_rapids_tpu.exec import basic as XB
    from spark_rapids_tpu.exec.aggregate import (FINAL,
                                                 CpuHashAggregateExec)
    from spark_rapids_tpu.exec.exchange import CpuShuffleExchangeExec
    from spark_rapids_tpu.exec.joins import (CpuBroadcastHashJoinExec,
                                             CpuShuffledHashJoinExec,
                                             TpuBroadcastHashJoinExec,
                                             TpuShuffledHashJoinExec)

    # -- exchanges: the distribution producers --------------------------
    if isinstance(node, CpuShuffleExchangeExec):
        d = required_dist(node.partitioning)
        return frozenset([d]) if d is not None else frozenset()

    # -- aggregates: keys become output columns 0..nk-1 -----------------
    if isinstance(node, CpuHashAggregateExec):
        child = _child_dists(node, memo)
        if node.mode == FINAL:
            # child is the buffer layout: keys already sit at 0..nk-1
            # and pass through to the result schema positionally
            out_map = {("ref", i): ("ref", i)
                       for i in range(node.layout.num_keys)}
            return _remap_by_map(child, out_map)
        return _remap_dists(child, node.layout.grouping)

    # -- joins: partition i pairs with partition i ----------------------
    if isinstance(node, (CpuShuffledHashJoinExec, TpuShuffledHashJoinExec,
                         CpuBroadcastHashJoinExec,
                         TpuBroadcastHashJoinExec)):
        jt = node.join_type
        left = delivered_dists(node.children[0], memo)
        out = set()
        if jt in (J.INNER, J.LEFT_OUTER, J.LEFT_SEMI, J.LEFT_ANTI):
            out |= {d for d in left if not isinstance(d, SingleDist)}
        if jt in (J.LEFT_SEMI, J.LEFT_ANTI) or \
                isinstance(node, (CpuBroadcastHashJoinExec,
                                  TpuBroadcastHashJoinExec)):
            # semi/anti emit the left schema only; broadcast replicates
            # the build side, so only the stream side's placement holds
            return frozenset(out)
        if jt in (J.INNER, J.RIGHT_OUTER):
            nl = len(node.children[0].schema.fields)
            for d in delivered_dists(node.children[1], memo):
                if isinstance(d, HashDist):
                    out.add(HashDist(tuple(_shift_refs(k, nl)
                                           for k in d.keys),
                                     d.n, d.mesh_axis))
        return frozenset(out)

    # -- projections (both tiers) ---------------------------------------
    if isinstance(node, XB.CpuProjectExec) or \
            isinstance(node, XB.TpuProjectExec):
        return _remap_dists(_child_dists(node, memo), node.exprs)
    if isinstance(node, XB.TpuFilterProjectExec):
        return _remap_dists(_child_dists(node, memo), node.exprs)

    # -- fused stages: fold the op chain in execution order -------------
    from spark_rapids_tpu.exec.fused import (TpuFusedAggExec,
                                             TpuFusedStageExec)
    if isinstance(node, TpuFusedStageExec):
        return _fold_ops(_child_dists(node, memo), node.ops)
    if isinstance(node, TpuFusedAggExec):
        dists = _fold_ops(_child_dists(node, memo), node.ops)
        return _remap_dists(dists, node.layout.grouping)

    # -- row/partition-preserving unary nodes ---------------------------
    if _is_transparent(node):
        return _child_dists(node, memo)

    return frozenset()


def _remap_by_map(dists, out_map) -> FrozenSet:
    out = set()
    for d in dists:
        if isinstance(d, SingleDist):
            out.add(d)
        elif isinstance(d, HashDist):
            keys = tuple(_remap(k, out_map) for k in d.keys)
            if all(k is not None for k in keys):
                out.add(HashDist(keys, d.n, d.mesh_axis))
        elif isinstance(d, RangeDist):
            specs = tuple((_remap(k, out_map), a, nf)
                          for k, a, nf in d.specs)
            if all(k is not None for k, _a, _n in specs):
                out.add(RangeDist(specs, d.n))
    return frozenset(out)


def _fold_ops(dists: FrozenSet, ops) -> FrozenSet:
    """Delivered distributions through a fused filter/project chain (ops
    in execution order; filters preserve, projects remap)."""
    for kind, payload in ops:
        if kind == "project":
            dists = _remap_dists(dists, payload)
    return dists


def _is_transparent(node: Exec) -> bool:
    """Unary nodes that neither move rows across partitions nor change
    the ordinals of existing columns (appended columns are fine)."""
    from spark_rapids_tpu.exec import basic as XB
    from spark_rapids_tpu.exec.sort import CpuSortExec, TpuSortExec
    from spark_rapids_tpu.exec.window import CpuWindowExec
    transparent = (XB.CpuFilterExec, XB.TpuFilterExec, XB.CpuLimitExec,
                   XB.TpuLimitExec, XB.CpuGlobalLimitExec,
                   XB.CpuCteCacheExec, XB.CpuSampleExec, XB.TpuSampleExec,
                   XB.TpuCoalesceBatchesExec, XB.HostToDeviceExec,
                   XB.DeviceToHostExec, XB.TpuMaterializeEncodedExec,
                   CpuSortExec, TpuSortExec, CpuWindowExec)
    if isinstance(node, transparent):
        return True
    try:
        from spark_rapids_tpu.exec.pipeline import PrefetchExec
        if isinstance(node, PrefetchExec):
            return True
    except ImportError:           # pragma: no cover - pipeline always ships
        pass
    return False


# ---------------------------------------------------------------------------
# the elision pass
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class Elision:
    """One removed exchange, for events/EXPLAIN."""
    partitioning: str
    delivered: str

    def desc(self) -> str:
        return f"{self.partitioning} <= {self.delivered}"


def _satisfied(required, dists) -> Optional[str]:
    """Returns the delivered distribution's desc when ``required`` is
    already met, else None."""
    for d in dists:
        if isinstance(required, SingleDist) and isinstance(d, SingleDist):
            return d.desc()
        if isinstance(required, HashDist) and isinstance(d, HashDist) \
                and required.matches(d):
            return d.desc()
        if isinstance(required, RangeDist) and isinstance(d, RangeDist) \
                and required.specs == d.specs and required.n == d.n:
            return d.desc()
    return None


def eliminate_redundant_exchanges(plan: Exec
                                  ) -> Tuple[Exec, List[Elision]]:
    """Removes every shuffle exchange whose child already delivers the
    required distribution (same hash keys AND partition count — the
    murmur3-pmod placement is deterministic, so equal distributions mean
    equal partition assignment, not merely co-grouping).  Runs on the
    pre-convert Cpu tree; identity-memoized so DAG-shared subtrees
    (CTE reuse) stay shared."""
    from spark_rapids_tpu.exec.exchange import CpuShuffleExchangeExec
    from spark_rapids_tpu.plan.partitioning import RoundRobinPartitioning

    elided: List[Elision] = []
    memo: Dict[int, Exec] = {}
    dist_memo: Dict[int, FrozenSet] = {}

    def visit(node: Exec) -> Exec:
        key = id(node)
        if key in memo:
            return memo[key]
        new_children = [visit(c) for c in node.children]
        out = node if all(a is b for a, b in zip(new_children,
                                                 node.children)) \
            else node.with_children(new_children)
        if isinstance(out, CpuShuffleExchangeExec) and \
                not isinstance(out.partitioning, RoundRobinPartitioning):
            required = required_dist(out.partitioning)
            child = out.children[0]
            if required is not None and \
                    child.num_partitions == out.num_partitions:
                got = _satisfied(required, delivered_dists(child,
                                                           dist_memo))
                if got is not None:
                    elided.append(Elision(out.partitioning.desc(), got))
                    out = child
        memo[key] = out
        return out

    return visit(plan), elided
