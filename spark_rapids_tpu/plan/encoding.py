"""Encoded-execution planner pass (late-materialization placement).

With ``spark.rapids.sql.encoding.enabled`` on, device scans keep parquet
dictionary pages (and opted-in RLE runs) encoded and the operator layer
defers decode per column (columnar/encoding.py).  This pass controls
WHERE the decode boundary sits:

- ``lateMaterialization=true`` (default): no node is inserted — encoded
  columns flow through fused filter chains as compacted code planes and
  materialize only where values are genuinely needed.
- ``lateMaterialization=false``: an explicit ``TpuMaterializeEncoded``
  node lands directly above every encoded-capable device scan, so the
  H2D transfer still ships codes but every operator sees plain columns
  (the conservative mode the AutoTuner recommends when dictionary
  fallbacks dominate).

With encoding disabled the pass is an exact no-op, reproducing the
pre-encoding plans.
"""

from __future__ import annotations

from spark_rapids_tpu.plan.base import Exec


def insert_materialize_boundaries(plan: Exec, conf) -> Exec:
    from spark_rapids_tpu import config as C
    if not conf.get(C.ENCODING_ENABLED.key) or \
            conf.get(C.ENCODING_LATE_MAT.key):
        return plan
    from spark_rapids_tpu.exec.basic import TpuMaterializeEncodedExec
    from spark_rapids_tpu.io.multifile import MultiFileScanBase

    def fix(node: Exec) -> Exec:
        new_children = []
        for c in node.children:
            if isinstance(c, MultiFileScanBase) and \
                    getattr(c, "is_device", False) and \
                    not isinstance(node, TpuMaterializeEncodedExec):
                c = TpuMaterializeEncodedExec(c)
            new_children.append(c)
        return node.with_children(new_children)

    out = plan.transform_up(fix)
    if isinstance(out, MultiFileScanBase) and \
            getattr(out, "is_device", False):
        out = TpuMaterializeEncodedExec(out)
    return out
