"""Meta wrappers: wrap -> tag -> convert (reference: RapidsMeta.scala:83
RapidsMeta[INPUT,BASE,OUTPUT], SparkPlanMeta :598, BaseExprMeta :1058;
tagging API willNotWorkOnGpu / tagForGpu / convertIfNeeded).

Every CPU plan node is wrapped in a ``PlanMeta``; its expressions in
``ExprMeta``s.  ``tag()`` records every reason the node cannot run on the
device; ``convert_if_needed()`` emits the Tpu exec when clean, else keeps the
CPU node (partial plans are the point — reference README "transparent CPU
fallback").
"""

from __future__ import annotations

from typing import Callable, List, Optional

from spark_rapids_tpu import types as T
from spark_rapids_tpu.config import TpuConf
from spark_rapids_tpu.expressions.base import Expression
from spark_rapids_tpu.plan import typechecks as TS
from spark_rapids_tpu.plan.base import Exec


class BaseMeta:
    def __init__(self):
        self.reasons: List[str] = []

    def will_not_work(self, reason: str) -> None:
        """reference: RapidsMeta.willNotWorkOnGpu"""
        if reason not in self.reasons:
            self.reasons.append(reason)

    @property
    def can_run_on_device(self) -> bool:
        return not self.reasons


class ExprMeta(BaseMeta):
    def __init__(self, expr: Expression, conf: TpuConf,
                 sig: Optional[TS.TypeSig] = None):
        super().__init__()
        self.expr = expr
        self.conf = conf
        self.sig = sig
        self.child_metas = [ExprMeta(c, conf, sig) for c in expr.children]

    def tag(self) -> None:
        from spark_rapids_tpu.plan.overrides import expr_rule_for
        for cm in self.child_metas:
            cm.tag()
            for r in cm.reasons:
                self.will_not_work(r)
        rule = expr_rule_for(type(self.expr))
        if rule is None:
            self.will_not_work(
                f"expression {self.expr.name} has no TPU implementation")
            return
        ekey = getattr(rule, "enable_key", None)
        if ekey is not None and not self.conf.get(ekey, True):
            self.will_not_work(
                f"expression {self.expr.name} disabled by {ekey}")
        sig = rule.sig or self.sig or TS.ALL_BASIC
        try:
            dt = self.expr.data_type
        except Exception as e:  # unresolved attribute etc.
            self.will_not_work(f"{self.expr.name}: {e}")
            return
        r = sig.check(dt)
        if r is not None:
            self.will_not_work(f"expression {self.expr.name}: {r}")
        if rule.checks is not None:
            # per-parameter matrix (ExprChecks analog): per-slot reasons
            rule.checks.check_expr(self.expr, self.will_not_work)
        reason = self.expr.tpu_supported(self.conf)
        if reason is not None:
            self.will_not_work(f"expression {self.expr.name}: {reason}")
        if rule.extra_tag is not None:
            rule.extra_tag(self)


class PlanMeta(BaseMeta):
    def __init__(self, plan: Exec, conf: TpuConf):
        super().__init__()
        self.plan = plan
        self.conf = conf
        self.child_metas = [PlanMeta(c, conf) for c in plan.children]
        self.rule = None
        self.expr_metas: List[ExprMeta] = []
        self.converted: Optional[Exec] = None

    def tag(self) -> None:
        from spark_rapids_tpu.plan.overrides import exec_rule_for
        for cm in self.child_metas:
            cm.tag()
        if not self.conf.is_sql_enabled:
            self.will_not_work("spark.rapids.sql.enabled is false")
            return
        self.rule = exec_rule_for(type(self.plan))
        if self.rule is None:
            self.will_not_work(
                f"exec {self.plan.name} has no TPU implementation")
            return
        ekey = getattr(self.rule, "enable_key", None)
        if ekey is not None and not self.conf.get(ekey, True):
            self.will_not_work(f"exec {self.plan.name} disabled by {ekey}")
        sig = self.rule.sig or TS.ALL_BASIC
        r = TS.check_output_types(self.plan.schema, sig)
        if r is not None:
            self.will_not_work(f"{self.plan.name}: {r}")
        for expr in self.rule.exprs_of(self.plan):
            em = ExprMeta(expr, self.conf, self.rule.expr_sig)
            em.tag()
            self.expr_metas.append(em)
            for reason in em.reasons:
                self.will_not_work(reason)
        if self.rule.extra_tag is not None:
            self.rule.extra_tag(self)

    def convert_if_needed(self) -> Exec:
        """reference: RapidsMeta.convertIfNeeded — device exec when tagging
        passed, original CPU exec otherwise; children converted first."""
        new_children = [cm.convert_if_needed() for cm in self.child_metas]
        base = self.plan.with_children(new_children)
        if self.can_run_on_device and self.rule is not None:
            out = self.rule.convert(base, self)
            self.converted = out
            return out
        self.converted = base
        return base

    # -- explain ------------------------------------------------------------
    def explain(self, all_nodes: bool = False, indent: int = 0) -> str:
        """reference: GpuOverrides explain output / ExplainPlan API."""
        pad = "  " * indent
        lines = []
        if self.can_run_on_device:
            if all_nodes:
                lines.append(f"{pad}*{self.plan.name} will run on TPU")
        else:
            why = "; ".join(self.reasons)
            lines.append(f"{pad}!{self.plan.name} cannot run on TPU: {why}")
        for cm in self.child_metas:
            sub = cm.explain(all_nodes, indent + 1)
            if sub:
                lines.append(sub)
        return "\n".join(l for l in lines if l)


def tag_and_convert(plan: Exec, conf: TpuConf):
    meta = PlanMeta(plan, conf)
    meta.tag()
    converted = meta.convert_if_needed()
    return meta, converted
