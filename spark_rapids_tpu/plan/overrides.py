"""Rule registries + the main plan-rewrite entry point.

Reference: ``GpuOverrides.scala`` — ExprRule :222 / ExecRule :278 registries,
``applyWithContext`` :4562 (wrap -> tag -> convert), explain-only mode
:4578, and ``GpuTransitionOverrides.scala`` for transition insertion.
"""

from __future__ import annotations

import dataclasses
import logging
from typing import Callable, Dict, List, Optional, Type

from spark_rapids_tpu import config as C
from spark_rapids_tpu import types as T
from spark_rapids_tpu.config import TpuConf
from spark_rapids_tpu.expressions import (arithmetic as A, bitwise as B,
                                          cast as CA, conditional as K,
                                          datetime_exprs as D, hashing as H,
                                          mathexprs as M, predicates as P,
                                          strings as S)
from spark_rapids_tpu.expressions.base import (Alias, BoundReference,
                                               Expression, Literal)
from spark_rapids_tpu.plan import typechecks as TS
from spark_rapids_tpu.plan.base import Exec
from spark_rapids_tpu.plan.meta import PlanMeta, tag_and_convert

log = logging.getLogger(__name__)


@dataclasses.dataclass
class ExprRule:
    """reference: GpuOverrides.ExprRule — here expressions are dual-backend,
    so the rule carries support metadata rather than a conversion."""
    cls: Type[Expression]
    sig: Optional[TS.TypeSig] = None
    desc: str = ""
    extra_tag: Optional[Callable] = None
    #: per-op input/output matrix (ExprChecks analog); when present it
    #: refines ``sig`` with per-parameter signatures
    checks: Optional[TS.OpChecks] = None


@dataclasses.dataclass
class ExecRule:
    cls: Type[Exec]
    convert: Callable[[Exec, PlanMeta], Exec]
    sig: Optional[TS.TypeSig] = None
    expr_sig: Optional[TS.TypeSig] = None
    desc: str = ""
    exprs_of: Callable[[Exec], List[Expression]] = lambda p: []
    extra_tag: Optional[Callable] = None
    #: deliberately host-tier (identity convert + honest fallback tag);
    #: api_validation skips the Tpu-twin naming contract for these
    host_only: bool = False


_EXPR_RULES: Dict[type, ExprRule] = {}
_EXEC_RULES: Dict[type, ExecRule] = {}


def register_expr(cls, sig=None, desc="", extra_tag=None, checks=None):
    rule = ExprRule(cls, sig, desc, extra_tag, checks)
    rule.enable_key = _register_op_enable("expression", cls, desc)
    _EXPR_RULES[cls] = rule


def _op_enable_key(kind: str, cls) -> str:
    name = cls.__name__
    if name.startswith("Cpu"):
        name = name[3:]
    return f"spark.rapids.sql.{kind}.{name}"


def _register_op_enable(kind: str, cls, desc: str) -> str:
    """Every registered operator gets its own enable conf (reference:
    GpuOverrides registers spark.rapids.sql.exec.* /
    spark.rapids.sql.expression.* per rule; RapidsConf.isOperatorEnabled).
    Setting it false tags the op off the device — a real planner gate,
    surfaced by docgen."""
    from spark_rapids_tpu import config as C
    key = _op_enable_key(kind, cls)
    if key not in C.registry():
        C.conf_bool(key,
                    f"Enable the device {kind} {cls.__name__}"
                    + (f" ({desc})" if desc else "") + ".",
                    True, C.ConfLevel.COMMONLY_USED)
    return key


def register_exec(cls, convert, sig=None, expr_sig=None, desc="",
                  exprs_of=lambda p: [], extra_tag=None, host_only=False):
    rule = ExecRule(cls, convert, sig, expr_sig, desc, exprs_of,
                    extra_tag, host_only)
    rule.enable_key = _register_op_enable("exec", cls, desc)
    _EXEC_RULES[cls] = rule


def expr_rule_for(cls) -> Optional[ExprRule]:
    for k in cls.__mro__:
        if k in _EXPR_RULES:
            return _EXPR_RULES[k]
    return None


def exec_rule_for(cls) -> Optional[ExecRule]:
    return _EXEC_RULES.get(cls)


def expr_registry() -> Dict[type, ExprRule]:
    return dict(_EXPR_RULES)


def exec_registry() -> Dict[type, ExecRule]:
    return dict(_EXEC_RULES)


# ---------------------------------------------------------------------------
# Expression registrations (reference: commonExpressions, GpuOverrides.scala:904
# — 219 registrations; ours grows with each expression milestone)
# ---------------------------------------------------------------------------

for _cls in (Literal, BoundReference, Alias):
    register_expr(_cls, TS.BASIC_WITH_ARRAYS)

_ARITH_CHECKS = TS.OpChecks(
    TS.NUMERIC_128,
    [TS.ParamCheck("lhs", TS.NUMERIC_128), TS.ParamCheck("rhs",
                                                         TS.NUMERIC_128)])
for _cls in (A.Add, A.Subtract, A.Multiply, A.Divide, A.IntegralDivide,
             A.Remainder, A.Pmod, A.UnaryMinus, A.Abs):
    register_expr(_cls, TS.NUMERIC_128, checks=_ARITH_CHECKS)

_CMP_CHECKS = TS.OpChecks(
    TS.BOOLEAN,
    [TS.ParamCheck("lhs", TS.COMPARABLE), TS.ParamCheck("rhs",
                                                        TS.COMPARABLE)])
for _cls in (P.EqualTo, P.NotEqual, P.LessThan, P.LessThanOrEqual,
             P.GreaterThan, P.GreaterThanOrEqual, P.EqualNullSafe):
    register_expr(_cls, TS.COMPARABLE, checks=_CMP_CHECKS)

for _cls in (P.And, P.Or, P.Not):
    register_expr(_cls, TS.BOOLEAN)

for _cls in (P.IsNull, P.IsNotNull, P.IsNan, P.In):
    register_expr(_cls, TS.ALL_BASIC)

for _cls in (K.If, K.CaseWhen, K.Coalesce, K.NaNvl, K.Greatest, K.Least,
             K.AtLeastNNonNulls):
    register_expr(_cls, TS.ALL_BASIC)

for _cls in (M.UnaryMath, M.Floor, M.Ceil, M.Round, M.BRound, M.Pow,
             M.Atan2, M.Hypot, M.Signum):
    register_expr(_cls, TS.NUMERIC)

for _cls in (B.BitwiseAnd, B.BitwiseOr, B.BitwiseXor, B.BitwiseNot,
             B.ShiftLeft, B.ShiftRight, B.ShiftRightUnsigned):
    register_expr(_cls, TS.INTEGRAL)

register_expr(CA.Cast, TS.ALL_BASIC)

_STR_IN = TS.TypeSig([T.StringType])
for _cls in (S.Upper, S.Lower, S.Trim, S.LTrim, S.RTrim, S.Reverse,
             S.InitCap):
    register_expr(_cls, TS.ALL_BASIC, checks=TS.OpChecks(
        _STR_IN, [TS.ParamCheck("str", _STR_IN)]))
register_expr(S.Length, TS.ALL_BASIC, checks=TS.OpChecks(
    TS.INTEGRAL, [TS.ParamCheck("str", TS.TypeSig([T.StringType,
                                                   T.BinaryType]))]))
for _cls in (S.StartsWith, S.EndsWith, S.Contains):
    register_expr(_cls, TS.ALL_BASIC, checks=TS.OpChecks(
        TS.BOOLEAN, [TS.ParamCheck("str", _STR_IN),
                     TS.ParamCheck("search", _STR_IN)]))
register_expr(S.Substring, TS.ALL_BASIC, checks=TS.OpChecks(
    _STR_IN, [TS.ParamCheck("str", _STR_IN),
              TS.ParamCheck("pos", TS.INTEGRAL),
              TS.ParamCheck("len", TS.INTEGRAL)]))
for _cls in (S.Concat, S.Like, S.RLike, S.RegExpReplace, S.RegExpExtract,
             S.StringRepeat, S.LPad, S.RPad, S.StringLocate,
             S.StringTranslate, S.ConcatWs):
    register_expr(_cls, TS.ALL_BASIC)

register_expr(S.StringSplit, TS.BASIC_WITH_ARRAYS)

for _cls in (D._DateField, D._TimeField, D.DateAdd, D.DateSub, D.DateDiff,
             D.LastDay, D.UnixTimestampFromTs, D.AddMonths,
             D.MonthsBetween, D.NextDay, D.TruncDate, D.DateFormat):
    register_expr(_cls, TS.ALL_BASIC)

register_expr(H.Murmur3Hash, TS.ALL_BASIC)
register_expr(H.XxHash64, TS.ALL_BASIC,
              extra_tag=lambda m: None)

# collection / complex-type expressions (reference: GpuOverrides
# registrations for Size/ElementAt/ArrayContains/SortArray/CreateArray/
# transform/exists/filter/aggregate + complexTypeExtractors)
from spark_rapids_tpu.expressions import collections as CO  # noqa: E402

for _cls in (CO.Size, CO.GetArrayItem, CO.ElementAt, CO.ArrayContains,
             CO.ArrayMin, CO.ArrayMax, CO.SortArray, CO.Slice,
             CO.CreateArray, CO.ArrayRepeat, CO.LambdaVariable,
             CO.ArrayTransform, CO.ArrayExists, CO.ArrayForAll,
             CO.ArrayFilter, CO.ArrayAggregate):
    register_expr(_cls, TS.BASIC_WITH_ARRAYS)

# struct/map expressions exist as host-tier components (their
# tpu_supported() tags the honest fallback reason)
for _cls in (CO.GetStructField, CO.CreateNamedStruct, CO.CreateMap,
             CO.MapKeys, CO.MapValues):
    register_expr(_cls, TS.BASIC_WITH_ARRAYS)

# aggregate functions (reference: GpuOverrides aggExprs — Sum/Count/Min/Max/
# Average/First/Last/StddevSamp/... registrations)
from spark_rapids_tpu.expressions import aggregates as AG  # noqa: E402

# per-op input matrices (ExprChecks analog, TypeChecks.scala:1057):
# Sum/Average take numeric inputs (decimal64 buffers; decimal128 buffers
# rejected at the exec's buffer tag), Min/Max exclude strings/binary (no
# device min/max string buffers yet — the runtime gap supported_ops.md
# previously could not express), Count/First/Last take anything basic.
_MINMAX_IN = TS.TypeSig(
    [T.ByteType, T.ShortType, T.IntegerType, T.LongType, T.FloatType,
     T.DoubleType, T.BooleanType, T.DateType, T.TimestampType,
     T.DecimalType], True)
register_expr(AG.Sum, TS.ALL_BASIC, checks=TS.OpChecks(
    TS.NUMERIC_128, [TS.ParamCheck("value", TS.NUMERIC_128)]))
register_expr(AG.Average, TS.ALL_BASIC, checks=TS.OpChecks(
    TS.NUMERIC_128, [TS.ParamCheck("value", TS.NUMERIC_128)]))
for _cls in (AG.Min, AG.Max):
    register_expr(_cls, TS.ALL_BASIC, checks=TS.OpChecks(
        _MINMAX_IN, [TS.ParamCheck("value", _MINMAX_IN)]))
for _cls in (AG.Count, AG.First, AG.Last):
    register_expr(_cls, TS.ALL_BASIC)
_VAR_IN = TS.TypeSig([T.ByteType, T.ShortType, T.IntegerType, T.LongType,
                      T.FloatType, T.DoubleType])
for _cls in (AG.VarianceSamp, AG.VariancePop, AG.StddevSamp,
             AG.StddevPop):
    register_expr(_cls, TS.ALL_BASIC, checks=TS.OpChecks(
        TS.TypeSig([T.DoubleType]), [TS.ParamCheck("value", _VAR_IN)]))

# variable-length-state aggregates: host tier (COMPLETE-mode planning)
for _cls in (AG.CollectList, AG.CollectSet, AG.CountDistinct,
             AG.Percentile, AG.ApproximatePercentile,
             AG._PercentileFromList):
    register_expr(_cls, TS.BASIC_WITH_ARRAYS)


# ---------------------------------------------------------------------------
# Exec registrations (reference: commonExecs GpuOverrides.scala:3999-4311)
# ---------------------------------------------------------------------------

def _register_basic_execs():
    from spark_rapids_tpu.exec import basic as X

    register_exec(X.CpuProjectExec,
                  convert=lambda p, m: X.TpuProjectExec(p.exprs, p.children[0]),
                  sig=TS.BASIC_WITH_ARRAYS,
                  exprs_of=lambda p: p.exprs,
                  desc="columnar projection")
    register_exec(X.CpuFilterExec,
                  convert=lambda p, m: X.TpuFilterExec(p.condition,
                                                       p.children[0]),
                  sig=TS.BASIC_WITH_ARRAYS,
                  exprs_of=lambda p: [p.condition],
                  desc="columnar filter")
    register_exec(X.CpuRangeExec,
                  convert=lambda p, m: X.TpuRangeExec(p),
                  desc="range source")
    register_exec(X.CpuInMemoryScanExec,
                  convert=lambda p, m: X.TpuInMemoryScanExec(p),
                  sig=TS.BASIC_WITH_ARRAYS,
                  desc="in-memory scan")
    def _limit_conf(out, m):
        # round-5 knob rides the instance (set from meta.conf at convert
        # time): per-query conf travels with the plan, not the process
        out.deferred_force_interval = int(
            m.conf.get(C.LIMIT_DEFERRED_FORCE_INTERVAL.key))
        return out

    register_exec(X.CpuLimitExec,
                  convert=lambda p, m: _limit_conf(
                      X.TpuLimitExec(p.n, p.children[0]), m),
                  sig=TS.BASIC_WITH_ARRAYS,
                  desc="limit")
    register_exec(X.CpuCteCacheExec,
                  convert=lambda p, m: X.TpuCteCacheExec(p.children[0],
                                                         p.origin),
                  sig=TS.BASIC_WITH_ARRAYS,
                  desc="CTE materialization reuse")
    register_exec(X.CpuCoalescePartitionsExec,
                  convert=lambda p, m: X.TpuCoalescePartitionsExec(
                      p.n, p.children[0]),
                  sig=TS.BASIC_WITH_ARRAYS,
                  desc="shuffle-free partition merge")
    register_exec(X.CpuGlobalLimitExec,
                  convert=lambda p, m: _limit_conf(
                      X.TpuGlobalLimitExec(p.n, p.children[0]), m),
                  sig=TS.BASIC_WITH_ARRAYS,
                  desc="global limit")
    register_exec(X.CpuUnionExec,
                  convert=lambda p, m: X.TpuUnionExec(p.children),
                  sig=TS.BASIC_WITH_ARRAYS,
                  desc="union")
    register_exec(X.CpuSampleExec,
                  convert=lambda p, m: X.TpuSampleExec(p.fraction, p.seed,
                                                       p.children[0]),
                  desc="bernoulli sample",
                  extra_tag=lambda m: m.will_not_work(
                      "TPU sample uses a different RNG than CPU")
                  if m.conf.get(C.TEST_ENABLED.key) else None)


_register_basic_execs()


# ---------------------------------------------------------------------------
# Transition insertion (reference: GpuTransitionOverrides.scala:46)
# ---------------------------------------------------------------------------

def insert_transitions(plan: Exec, conf: TpuConf) -> Exec:
    from spark_rapids_tpu.exec.basic import (DeviceToHostExec,
                                             HostToDeviceExec,
                                             TpuCoalesceBatchesExec)
    dl_spec_rows = int(conf.get(C.DOWNLOAD_SPECULATIVE_ROWS.key))

    def fix(node: Exec) -> Exec:
        new_children = []
        for c in node.children:
            if node.is_device and not c.is_device:
                c = HostToDeviceExec(c)
            elif not node.is_device and c.is_device:
                c = DeviceToHostExec(c)
                # per-query conf rides the boundary instance
                c.dl_spec_rows = dl_spec_rows
            new_children.append(c)
        return node.with_children(new_children)

    out = plan.transform_up(fix)
    return out


# whole-stage fusion moved to its own planner module (plan/stages.py);
# re-exported here for existing callers
from spark_rapids_tpu.plan.stages import fuse_device_stages  # noqa: E402,F401


def push_scan_predicates(plan: Exec) -> Exec:
    """Filter-over-scan predicate pushdown (reference: the rapids file
    scans receive Spark's pushed filters and prune row groups / stripes
    with them — GpuParquetScan.scala footer filter, GpuOrcScan.scala host
    stripe filter).  The Filter node STAYS above the scan: pushdown is
    allowed to be conservative (stats-based pruning keeps false
    positives), so exactness lives in the filter."""
    from spark_rapids_tpu.exec.basic import CpuFilterExec
    from spark_rapids_tpu.io.orc import CpuOrcScanExec
    from spark_rapids_tpu.io.parquet import CpuParquetScanExec

    def fix(node: Exec) -> Exec:
        if isinstance(node, CpuFilterExec) and node.children:
            child = node.children[0]
            if isinstance(child, (CpuParquetScanExec, CpuOrcScanExec)) and \
                    child.predicate is None:
                import copy
                scan = copy.copy(child)
                scan.predicate = node.condition
                return node.with_children([scan])
        return node

    return plan.transform_up(fix)


def _reuse_node_key(node: Exec):
    """DEFAULT-DENY signature: a node type participates only when its
    key provably captures ALL result-affecting state — anything else
    keys by object identity and blocks reuse of its subtree (a lossy
    node_desc would otherwise merge differing pipelines: the fused
    execs compress their op chain to 'F'/'P' letters).

    Module-level (not nested in ``reuse_exchanges``) because the runtime
    plan verifier (plan/verify.py, ``spark.rapids.debug.planCheck``)
    re-derives the same signatures over the FINAL tree to assert the
    pass left no two distinct exchange instances with equal keys — the
    pass and its verifier must share one definition or the cross-check
    checks nothing."""
    from spark_rapids_tpu.exec import basic as XB
    from spark_rapids_tpu.exec.basic import CpuInMemoryScanExec
    from spark_rapids_tpu.exec.exchange import CpuShuffleExchangeExec
    from spark_rapids_tpu.exec.fused import (TpuFusedAggExec,
                                             TpuFusedStageExec,
                                             _ops_signature)
    from spark_rapids_tpu.io.multifile import MultiFileScanBase
    if isinstance(node, CpuInMemoryScanExec):
        # the device-column cache is shared by every copy of one
        # source DataFrame and distinct across sources
        return ("mem", id(node._dev_cache),
                tuple(node.col_indices or ()))
    if isinstance(node, MultiFileScanBase):
        # the scan-cache key already solves this exact problem:
        # format + files+mtimes + columns + predicate + per-format
        # decode options (schema/serde/parse flags)
        return ("file", type(node).__name__,
                node._scan_cache_key(-1, "reuse"))
    if isinstance(node, TpuFusedStageExec):
        # literal promotion makes _ops_signature value-independent;
        # plan identity must still include the VALUES or an exchange
        # over "d_year = 1998" would merge with one over 1999
        return ("fstage", _ops_signature(node.ops), node.lit_key())
    if isinstance(node, TpuFusedAggExec):
        lay = node.layout
        return ("fagg", _ops_signature(node.ops), node.lit_key(),
                node.mode,
                tuple((e.sql(), str(e.data_type))
                      for e in lay.update_input_exprs()),
                tuple((o, k, cv, str(dt))
                      for o, k, cv, dt in lay.update_specs()),
                tuple(e.sql() for e in lay.final_exprs()))
    if isinstance(node, CpuShuffleExchangeExec):
        # RangePartitioning.desc() omits sort direction/null order —
        # spell the full specs out (an asc and a desc range exchange
        # must never merge)
        from spark_rapids_tpu.plan.partitioning import RangePartitioning
        part = node.partitioning
        pkey = part.desc()
        if isinstance(part, RangePartitioning):
            pkey = ("range", part.num_partitions,
                    tuple((s.expr.sql(), s.ascending,
                           s.effective_nulls_first)
                          for s in part.specs))
        return ("x", type(node).__name__, pkey)
    if isinstance(node, (XB.CpuProjectExec, XB.CpuFilterExec,
                         XB.TpuCoalesceBatchesExec,
                         XB.HostToDeviceExec, XB.DeviceToHostExec)):
        # descs of these spell out their expressions
        return ("d", type(node).__name__, node.node_desc())
    return ("opaque", id(node))    # unvetted: never reuse through it


def exchange_reuse_signature(node: Exec):
    """Structural subtree signature the reuse pass merges by (and the
    plan verifier re-checks)."""
    return _reuse_node_key(node) + tuple(exchange_reuse_signature(c)
                                         for c in node.children)


def reuse_exchanges(plan: Exec) -> Exec:
    """Spark's ReuseExchange rule (reference: the reference keeps it
    active and re-tags reused exchanges in updateForAdaptivePlan,
    GpuOverrides.scala:4589-4607): structurally identical exchange
    subtrees collapse to ONE exec instance, so the shuffle materializes
    once and every reader hits its store — TPC-DS repeats whole subquery
    pipelines (q2's year-split, q1's customer_total_return) that
    otherwise shuffle twice."""
    from spark_rapids_tpu.exec.exchange import CpuShuffleExchangeExec

    sig = exchange_reuse_signature
    seen = {}

    def fix(node: Exec) -> Exec:
        from spark_rapids_tpu.exec.basic import CpuCteCacheExec
        if isinstance(node, CpuCteCacheExec):
            # the rewrite passes shallow-copy a DAG-shared CTE node apart
            # per parent; collapse the copies back onto ONE caching
            # instance so the CTE executes once.  Keyed on the logical
            # node's identity + output schema (column pruning may have
            # narrowed references differently — only identical shapes
            # merge)
            k = ("cte", node.origin, node.is_device,
                 tuple((f.name, str(f.data_type))
                       for f in node.schema.fields))
            if k in seen:
                return seen[k]
            seen[k] = node
            return node
        if isinstance(node, CpuShuffleExchangeExec):
            k = sig(node)
            if k in seen:
                return seen[k]
            seen[k] = node
        return node

    return plan.transform_up(fix)


def validate_all_on_device(plan: Exec, conf: TpuConf) -> None:
    """Test-mode assertion (reference: GpuTransitionOverrides
    assertIsOnTheGpu :616 + spark.rapids.sql.test.enabled)."""
    from spark_rapids_tpu.exec.basic import DeviceToHostExec, HostToDeviceExec
    allowed = {s.strip() for s in
               conf.get(C.TEST_ALLOWED_NONGPU.key).split(",") if s.strip()}
    bad = [n for n in plan.collect_nodes()
           if not n.is_device
           and not isinstance(n, DeviceToHostExec)
           and n.name not in allowed]
    # the root DeviceToHost is always fine; host leaves feeding H2D are not
    if bad:
        names = ", ".join(sorted({n.name for n in bad}))
        raise AssertionError(
            f"Part of the plan is not columnar/TPU: {names}\n{plan.tree_string()}")


class TpuOverrides:
    """The ColumnarRule analog: applies wrap->tag->convert + transitions.

    reference: GpuOverrides.applyWithContext (GpuOverrides.scala:4562) wired
    through ColumnarOverrideRules (Plugin.scala:52).
    """

    def __init__(self, conf: TpuConf):
        self.conf = conf
        self.last_meta: Optional[PlanMeta] = None
        #: exchanges removed by the distribution pass on the last apply
        #: (plan/distribution.py Elision records; EXPLAIN renders them)
        self.last_elided: List = []

    def apply(self, plan: Exec, for_explain: bool = False,
              skip_pruning: bool = False) -> Exec:
        """``for_explain`` produces the would-be plan without the test-mode
        all-on-device assertion (introspection must not raise on fallback).
        ``skip_pruning`` is set by callers that already pruned (count())."""
        from spark_rapids_tpu.plan.base import (set_task_oom_injection,
                                                set_task_parallelism,
                                                set_task_retry_policy)
        from spark_rapids_tpu.plan.meta import PlanMeta
        conf = self.conf
        set_task_parallelism(conf.get(C.TASK_PARALLELISM.key))
        set_task_oom_injection(conf.get(C.OOM_INJECTION_MODE.key))
        set_task_retry_policy(conf.get(C.TASK_MAX_FAILURES.key),
                              conf.get(C.TASK_BREAKER_THRESHOLD.key))
        # chaos layer: sync armed fault points with spark.rapids.chaos.*
        # (each action re-arms, so every query sees its conf's fault
        # budget and a pooled thread never inherits stale chaos)
        from spark_rapids_tpu.aux.faults import arm_from_conf
        arm_from_conf(conf)
        # conf-driven out-of-core test hooks (spark.rapids.sql.test.*)
        import spark_rapids_tpu.exec.aggregate as _AG
        import spark_rapids_tpu.exec.sort as _SO
        import spark_rapids_tpu.exec.window as _WI
        from spark_rapids_tpu.io.multifile import enable_scan_cache
        _AG.FORCE_REPARTITION_BELOW_DEPTH = conf.get(
            C.FORCE_MERGE_REPARTITION_DEPTH.key)
        _SO.FORCE_OUT_OF_CORE_SORT = conf.get(C.FORCE_OOC_SORT.key)
        _WI.FORCE_RUNNING_WINDOW = conf.get(C.FORCE_RUNNING_WINDOW.key)
        _WI.FORCE_BOUNDED_WINDOW = conf.get(C.FORCE_BOUNDED_WINDOW.key)
        _WI.BOUNDED_WINDOW_MAX_SPAN = conf.get(
            C.BOUNDED_WINDOW_MAX_SPAN.key)
        # (the round-5 behavior knobs — build-side swap, shuffle shrink
        # threshold, range-bounds sample rows, collective enable, D2H
        # speculative rows, limit force interval — ride plan/exec
        # INSTANCES set from meta.conf at convert/transition time, never
        # module globals: per-query conf must travel with the plan so
        # concurrent sessions with different confs don't race.  The
        # conf-module-global lint rule pins the remaining legacy set.)
        # pipelined-execution knobs (exec/pipeline.py spools + the
        # shuffle-read next-partition warm in exec/exchange.py)
        import spark_rapids_tpu.exec.pipeline as _PL
        _PL.PIPELINE_ENABLED = conf.get(C.PIPELINE_ENABLED.key)
        _PL.PIPELINE_DEPTH = conf.get(C.PIPELINE_DEPTH.key)
        _PL.PIPELINE_MAX_BYTES = C.parse_bytes(
            conf.get(C.PIPELINE_MAX_IN_FLIGHT_BYTES.key))
        # cooperative memory arbitration (memory/arbiter.py): blocking
        # allocation + deadlock-break knobs per action
        import spark_rapids_tpu.memory.arbiter as _ARB
        _ARB.ARBITRATION_ENABLED = conf.get(
            C.MEMORY_ARBITRATION_ENABLED.key)
        _ARB.MAX_BLOCK_MS = conf.get(C.MEMORY_ARBITRATION_MAX_BLOCK_MS.key)
        # stage compiler (exec/stage_compiler.py + plan/stages.py):
        # executable-cache bound, persistent disk tier, background
        # compile, and the fusion/promotion planner knobs
        import spark_rapids_tpu.exec.stage_compiler as _SC
        import spark_rapids_tpu.plan.stages as _ST
        # async/maxPrograms are session-scoped (last apply wins — tested
        # in test_async_compile_bit_identical_and_warms): an interleaved
        # default-conf session reverting them costs at most latency or a
        # recompile.  cacheDir below is the exception (enable-only):
        # dropping the disk tier mid-process is expensive + irreversible.
        _SC.ASYNC_COMPILE = conf.get(C.COMPILE_ASYNC.key)
        _SC.AUDIT_LEDGER = conf.get(C.AUDIT_LEDGER.key)
        _SC.set_max_programs(conf.get(C.COMPILE_MAX_PROGRAMS.key))
        # ENABLE-only (scan-cache discipline): an interleaved default-conf
        # session must not drop another session's disk tier; explicit
        # disable is stage_compiler.set_persistent_cache_dir("")
        if conf.get(C.COMPILE_CACHE_DIR.key):
            _SC.set_persistent_cache_dir(conf.get(C.COMPILE_CACHE_DIR.key))
        _ST.LITERAL_PROMOTION = conf.get(C.COMPILE_LITERAL_PROMOTION.key)
        # encoded columnar execution (columnar/encoding.py) + the
        # compressed spill tier (memory/catalog.py)
        import spark_rapids_tpu.columnar.encoding as _ENC
        import spark_rapids_tpu.memory.catalog as _CAT
        _ENC.ENCODING_ENABLED = conf.get(C.ENCODING_ENABLED.key)
        _ENC.LATE_MATERIALIZATION = conf.get(C.ENCODING_LATE_MAT.key)
        _ENC.MAX_DICTIONARY_SIZE = conf.get(C.ENCODING_MAX_DICT_SIZE.key)
        _ENC.RLE_ENABLED = conf.get(C.ENCODING_RLE_ENABLED.key)
        _CAT.SPILL_CODEC = conf.get(C.SPILL_CODEC.key)
        # ENABLE-only: benchmark setups interleave an enabled session
        # with a default-conf sanity session, whose every plan compile
        # would otherwise wipe the cache mid-run; releasing the process-
        # global residency is an explicit enable_scan_cache(False)
        if conf.get(C.SCAN_CACHE_ENABLED.key):
            enable_scan_cache(True)
        plan = push_scan_predicates(plan)
        if not skip_pruning and conf.get(C.COLUMN_PRUNING_ENABLED.key, True):
            from spark_rapids_tpu.plan.pruning import prune_columns
            # test mode turns a pruning failure into an error instead of a
            # silent unpruned fallback (VERDICT r2: the q1/q3/q4/q7/q8
            # KeyErrors hid behind the warning for a whole round)
            plan = prune_columns(plan,
                                 strict=conf.get(C.TEST_ENABLED.key, False))
        if not conf.is_sql_enabled:
            if not for_explain:
                from spark_rapids_tpu.exec.basic import refresh_cte_epochs
                refresh_cte_epochs(plan)
            return plan
        # partition-aware planning: delete exchanges whose child already
        # delivers the required distribution (co-partitioned joins /
        # aggs-above-joins shuffle zero times).  Runs on the Cpu tree so
        # every later pass (fusion, reuse, AQE) sees the final exchange
        # set; disabled reproduces the eager-exchange plans exactly.
        self.last_elided = []
        if conf.get(C.DISTRIBUTION_ENABLED.key):
            from spark_rapids_tpu.plan.distribution import \
                eliminate_redundant_exchanges
            plan, self.last_elided = eliminate_redundant_exchanges(plan)
            if self.last_elided and not for_explain:
                from spark_rapids_tpu.aux.events import emit
                emit("exchangeElided", count=len(self.last_elided),
                     exchanges=[e.desc() for e in self.last_elided])
        meta = PlanMeta(plan, conf)
        meta.tag()
        if conf.get(C.CBO_ENABLED.key):
            # reference: optional CBO between tag and convert
            # (GpuOverrides.scala:4372-4387)
            from spark_rapids_tpu.plan.cost import CostBasedOptimizer
            for note in CostBasedOptimizer(conf).optimize(meta):
                log.info("CBO: %s", note)
        converted = meta.convert_if_needed()
        self.last_meta = meta
        explain_mode = conf.get(C.EXPLAIN.key, "NOT_ON_GPU").upper()
        if explain_mode != "NONE":
            text = meta.explain(all_nodes=(explain_mode == "ALL"))
            if text:
                log.info("TPU plan overview:\n%s", text)
        if conf.is_explain_only:
            # plan and log only; execute entirely on CPU
            if not for_explain:
                from spark_rapids_tpu.exec.basic import refresh_cte_epochs
                refresh_cte_epochs(plan)
            return plan
        out = insert_transitions(converted, conf)
        out = self._coalesce_after_device_sources(out)
        # eager-decode boundary above encoded scans when late
        # materialization is off (exact no-op otherwise / when disabled)
        from spark_rapids_tpu.plan.encoding import \
            insert_materialize_boundaries
        out = insert_materialize_boundaries(out, conf)
        if conf.get(C.STAGE_FUSION_ENABLED.key):
            out = fuse_device_stages(out)
        if conf.get(C.EXCHANGE_REUSE_ENABLED.key):
            out = reuse_exchanges(out)
        if conf.get(C.ADAPTIVE_COALESCE_ENABLED.key):
            # runs AFTER reuse and is identity-memoized, so shared
            # exchange instances stay shared (a plain transform_up would
            # shallow-copy every occurrence apart) and the coordinated
            # specs capture the exact in-tree exchanges
            from spark_rapids_tpu.exec.adaptive import \
                insert_adaptive_readers
            from spark_rapids_tpu.parallel.mesh import active_mesh
            mesh_ctx = active_mesh()
            align = mesh_ctx.num_devices \
                if mesh_ctx is not None and \
                conf.get(C.ADAPTIVE_MESH_ALIGN.key) else 1
            out = insert_adaptive_readers(
                out, C.parse_bytes(conf.get(C.ADVISORY_PARTITION_BYTES.key)),
                align=align)
        if conf.is_test_enabled and not for_explain:
            validate_all_on_device(out, conf)
        from spark_rapids_tpu.aux.capture import ExecutionPlanCaptureCallback
        ExecutionPlanCaptureCallback.capture_if_needed(plan, out, meta)
        if conf.get(C.PIPELINE_ENABLED.key):
            # LAST structural pass (after validate/capture: the prefetch
            # boundary is transparent to placement assertions and plan-
            # shape tests): overlap decode / transfer / compute / download
            from spark_rapids_tpu.exec.pipeline import \
                insert_pipeline_prefetch
            out = insert_pipeline_prefetch(out)
        if not for_explain and conf.get(C.DEBUG_PLAN_CHECK.key):
            # runtime plan-invariant verifier: walks the FINAL tree
            # (after every in-place pass) against the contracts the
            # passes establish; observes + emits, never raises
            from spark_rapids_tpu.plan.verify import verify_plan
            verify_plan(out, conf)
        if not for_explain:
            # arm every CTE materialization cache for ONE execution: a
            # fresh epoch per prepared action means batches cached by a
            # previous action / speculation replay never replay stale
            # (the serving plan cache re-arms its cached plans the same
            # way before each re-execution)
            from spark_rapids_tpu.exec.basic import refresh_cte_epochs
            refresh_cte_epochs(out)
        # a fully-device plan has no DeviceToHost boundary: the final
        # download happens in collect_host on the ROOT, which reads this
        # instance knob (same conf insert_transitions threads onto D2H
        # boundaries)
        out.dl_spec_rows = int(conf.get(C.DOWNLOAD_SPECULATIVE_ROWS.key))
        if not for_explain:
            # never on the explain path: instrument_plan resets the shared
            # per-node counters, and introspection must not zero the
            # metrics of a query that ran (or is running) the same nodes
            from spark_rapids_tpu.aux.metrics import (MetricLevel,
                                                      instrument_plan)
            level = MetricLevel.parse(
                conf.get(C.METRICS_LEVEL.key, "MODERATE"))
            instrument_plan(out, level)
        from spark_rapids_tpu.aux import profiler as _prof
        _prof.set_ranges_enabled(bool(conf.get(C.RANGES_ENABLED.key)))
        return out

    def _coalesce_after_device_sources(self, plan: Exec) -> Exec:
        """Insert batch coalescing where ops want bigger batches
        (reference: GpuTransitionOverrides insertCoalesce per CoalesceGoal;
        post-shuffle coalesce = GpuShuffleCoalesceExec :519)."""
        from spark_rapids_tpu.exec.basic import (HostToDeviceExec,
                                                 TpuCoalesceBatchesExec)
        from spark_rapids_tpu.exec.exchange import TpuShuffleExchangeExec
        target = self.conf.batch_size_bytes

        def fix(node: Exec) -> Exec:
            # put a coalesce above any host->device boundary feeding compute
            new_children = []
            for c in node.children:
                if isinstance(c, (HostToDeviceExec, TpuShuffleExchangeExec)) \
                        and node.is_device and \
                        not isinstance(node, TpuCoalesceBatchesExec):
                    c = TpuCoalesceBatchesExec(c, target)
                new_children.append(c)
            return node.with_children(new_children)

        return plan.transform_up(fix)

    def explain(self) -> str:
        if self.last_meta is None:
            return ""
        return self.last_meta.explain(all_nodes=True)
