"""Output partitioning strategies.

Reference: GpuPartitioning.scala:37 (device slice), GpuHashPartitioningBase
(cudf hash partition; Spark-murmur3 pmod numPartitions), GpuRangePartitioner
(sample + sort bounds), GpuRoundRobinPartitioning, GpuSinglePartitioning —
registered in the PartRule map (GpuOverrides.scala:3875).

TPU-first: a partitioning only computes a per-row partition-id column; the
exchange then sorts by pid (fused lax.sort, stable) and slices — one device
pass regardless of fan-out, instead of cuDF's table split.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Sequence

import numpy as np

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.batch import ColumnarBatch, HostColumnarBatch
from spark_rapids_tpu.expressions.base import EvalContext, Expression


class Partitioning:
    num_partitions: int = 1

    #: expressions the planner must type-check (keys)
    @property
    def exprs(self) -> List[Expression]:
        return []

    def partition_ids_tpu(self, batch: ColumnarBatch):
        """int32[bucket] pid per row (padding rows get num_partitions)."""
        raise NotImplementedError

    def partition_ids_cpu(self, batch: HostColumnarBatch) -> np.ndarray:
        raise NotImplementedError

    def desc(self) -> str:
        return type(self).__name__


@dataclasses.dataclass
class SinglePartitioning(Partitioning):
    num_partitions: int = 1

    def partition_ids_tpu(self, batch):
        from spark_rapids_tpu.columnar.column import _jnp
        jnp = _jnp()
        pos = jnp.arange(batch.bucket, dtype=np.int32)
        return jnp.where(pos < batch.row_count, 0, 1).astype(np.int32)

    def partition_ids_cpu(self, batch):
        return np.zeros(batch.row_count, dtype=np.int32)

    def desc(self):
        return "SinglePartition"


class HashPartitioning(Partitioning):
    """pid = pmod(murmur3(keys, seed=42), n) — bit-exact Spark placement
    (reference: GpuHashPartitioningBase + HashFunctions murmur3)."""

    def __init__(self, key_exprs: Sequence[Expression], n: int):
        self.key_exprs = list(key_exprs)
        self.num_partitions = n

    @property
    def exprs(self):
        return self.key_exprs

    def _hash_expr(self):
        from spark_rapids_tpu.expressions.hashing import Murmur3Hash
        return Murmur3Hash(*self.key_exprs)

    def partition_ids_tpu(self, batch):
        from spark_rapids_tpu.columnar.column import _jnp
        from spark_rapids_tpu.expressions.evaluator import device_batch_tcols
        jnp = _jnp()
        ctx = EvalContext(device_batch_tcols(batch), "tpu", batch.bucket)
        h = self._hash_expr().eval_tpu(ctx)
        n = np.int32(self.num_partitions)
        pid = ((h.data % n) + n) % n
        pos = jnp.arange(batch.bucket, dtype=np.int32)
        return jnp.where(pos < batch.row_count, pid,
                         self.num_partitions).astype(np.int32)

    def partition_ids_cpu(self, batch):
        from spark_rapids_tpu.expressions.evaluator import (host_batch_tcols,
                                                            tcol_to_host_column)
        ctx = EvalContext(host_batch_tcols(batch), "cpu", batch.row_count)
        h = self._hash_expr().eval_cpu(ctx)
        hv = np.asarray(tcol_to_host_column(h, batch.row_count).arrow)
        n = np.int32(self.num_partitions)
        return (((hv.astype(np.int32) % n) + n) % n).astype(np.int32)

    def desc(self):
        ks = ", ".join(e.sql() for e in self.key_exprs)
        return f"HashPartitioning({ks}, {self.num_partitions})"


class RoundRobinPartitioning(Partitioning):
    def __init__(self, n: int, start: int = 0):
        self.num_partitions = n
        self.start = start

    def partition_ids_tpu(self, batch):
        from spark_rapids_tpu.columnar.column import _jnp
        jnp = _jnp()
        pos = jnp.arange(batch.bucket, dtype=np.int32)
        pid = (pos + np.int32(self.start)) % np.int32(self.num_partitions)
        return jnp.where(pos < batch.row_count, pid,
                         self.num_partitions).astype(np.int32)

    def partition_ids_cpu(self, batch):
        pos = np.arange(batch.row_count, dtype=np.int32)
        return ((pos + self.start) % self.num_partitions).astype(np.int32)

    def desc(self):
        return f"RoundRobinPartitioning({self.num_partitions})"


class RangePartitioning(Partitioning):
    """Range partitioning over sort keys; ``bounds`` (a host batch of key
    columns, n-1 rows) is produced by the exchange from a sample
    (reference: GpuRangePartitioner.sketch/createRangeBounds)."""

    def __init__(self, specs, n: int,
                 bounds: Optional[HostColumnarBatch] = None):
        from spark_rapids_tpu.exec.sort import SortSpec  # noqa: F401
        self.specs = list(specs)
        self.num_partitions = n
        self.bounds = bounds

    @property
    def exprs(self):
        return [s.expr for s in self.specs]

    # -- key normalization (shared with the device sort) --------------------
    def _key_batch_tpu(self, batch: ColumnarBatch) -> ColumnarBatch:
        from spark_rapids_tpu.expressions.base import Alias
        from spark_rapids_tpu.expressions.evaluator import eval_exprs_tpu
        return eval_exprs_tpu(
            [Alias(s.expr, f"k{i}") for i, s in enumerate(self.specs)], batch)

    def _key_batch_cpu(self, batch: HostColumnarBatch) -> HostColumnarBatch:
        from spark_rapids_tpu.expressions.evaluator import (eval_exprs_cpu,)
        from spark_rapids_tpu.expressions.base import Alias
        return eval_exprs_cpu(
            [Alias(s.expr, f"k{i}") for i, s in enumerate(self.specs)], batch)

    def _norm_words(self, key_batch: ColumnarBatch, jnp):
        """Per-row list of order words (same normalization as sort_ops, so
        bound comparison == sort order)."""
        from spark_rapids_tpu.ops.sort_ops import SortOrder, _order_words
        words = []
        for i, s in enumerate(self.specs):
            o = SortOrder(i, s.ascending, s.effective_nulls_first)
            words.extend(_order_words(key_batch.columns[i], o, jnp))
        return words

    @staticmethod
    def _align_widths(a: ColumnarBatch, b: ColumnarBatch, jnp):
        """Pads string key columns to a common width so both sides produce
        the same number of sortable words."""
        from spark_rapids_tpu.columnar.column import DeviceColumn

        def pad(col, w):
            if col.lengths is None or col.data.shape[1] >= w:
                return col
            d = jnp.pad(col.data, ((0, 0), (0, w - col.data.shape[1])))
            return DeviceColumn(d, col.validity, col.row_count,
                                col.data_type, col.lengths)

        ac, bc = [], []
        for ca, cb in zip(a.columns, b.columns):
            if ca.lengths is not None:
                w = max(ca.data.shape[1], cb.data.shape[1])
                ca, cb = pad(ca, w), pad(cb, w)
            ac.append(ca)
            bc.append(cb)
        return (ColumnarBatch(ac, a.row_count, a.names),
                ColumnarBatch(bc, b.row_count, b.names))

    def partition_ids_tpu(self, batch):
        from spark_rapids_tpu.columnar.column import _jnp
        jnp = _jnp()
        assert self.bounds is not None, "bounds not computed"
        keys = self._key_batch_tpu(batch)
        pos = jnp.arange(batch.bucket, dtype=np.int32)
        if self.bounds.row_count == 0:
            return jnp.where(pos < batch.row_count, 0,
                             self.num_partitions).astype(np.int32)
        keys, bnd = self._align_widths(keys, self.bounds.to_device(), jnp)
        row_words = self._norm_words(keys, jnp)
        bound_words = self._norm_words(bnd, jnp)
        pid = jnp.zeros(batch.bucket, dtype=np.int32)
        for j in range(self.bounds.row_count):
            # lexicographic row > bound_j
            gt = jnp.zeros(batch.bucket, dtype=bool)
            eq = jnp.ones(batch.bucket, dtype=bool)
            for rw, bw in zip(row_words, bound_words):
                bj = bw[j]
                gt = gt | (eq & (rw > bj))
                eq = eq & (rw == bj)
            pid = pid + gt.astype(np.int32)
        return jnp.where(pos < batch.row_count, pid,
                         self.num_partitions).astype(np.int32)

    def partition_ids_cpu(self, batch):
        # genuinely host-side: numpy twin of the device word normalization
        # (the CPU oracle must never touch the accelerator)
        from spark_rapids_tpu.ops.sort_ops import host_order_words
        assert self.bounds is not None, "bounds not computed"
        n = batch.row_count
        if self.bounds.row_count == 0:
            return np.zeros(n, dtype=np.int32)
        keys = self._key_batch_cpu(batch)
        # agree on string rectangle widths across rows and bounds; keep the
        # probed rectangles so the scatter isn't done twice per column
        widths, kpairs, bpairs = [], [], []
        for kc, bc in zip(keys.columns, self.bounds.columns):
            if isinstance(kc.data_type, (T.StringType, T.BinaryType)):
                kp, bp = kc.string_np(), bc.string_np()
                widths.append(max(kp[0].shape[1], bp[0].shape[1], 1))
                kpairs.append(kp)
                bpairs.append(bp)
            else:
                widths.append(None)
                kpairs.append(None)
                bpairs.append(None)
        row_words: List[np.ndarray] = []
        bound_words: List[np.ndarray] = []
        for i, s in enumerate(self.specs):
            from spark_rapids_tpu.ops.sort_ops import SortOrder
            o = SortOrder(i, s.ascending, s.effective_nulls_first)
            row_words.extend(host_order_words(keys.columns[i], o, widths[i],
                                              kpairs[i]))
            bound_words.extend(
                host_order_words(self.bounds.columns[i], o, widths[i],
                                 bpairs[i]))
        pid = np.zeros(n, dtype=np.int32)
        for j in range(self.bounds.row_count):
            gt = np.zeros(n, dtype=bool)
            eq = np.ones(n, dtype=bool)
            for rw, bw in zip(row_words, bound_words):
                bj = bw[j]
                gt = gt | (eq & (rw > bj))
                eq = eq & (rw == bj)
            pid += gt.astype(np.int32)
        return pid

    def desc(self):
        ks = ", ".join(s.expr.sql() for s in self.specs)
        return f"RangePartitioning({ks}, {self.num_partitions})"
