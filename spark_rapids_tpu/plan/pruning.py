"""Column pruning (scan-level projection pushdown).

Walks the physical plan top-down computing the set of child output ordinals
each node actually consumes, rebuilds bottom-up remapping BoundReference
ordinals, and asks leaf scans to drop unused columns.  On TPU this is a
first-order win: every pruned column is a host->device transfer that never
happens (the transfer's fixed cost dominates at batch sizes, see
columnar/transfer.py).

Reference analog: Spark performs column pruning in the logical optimizer
before the plan ever reaches GpuOverrides; since this engine builds physical
plans directly from the DataFrame/SQL API, the pass lives here.  The
reference's scan-side nested-schema pruning lives in
sql-plugin/.../GpuParquetScan.scala (clipped schemas).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Set, Tuple

from spark_rapids_tpu.expressions.base import BoundReference, Expression
from spark_rapids_tpu.plan.base import Exec


def _refs(e: Optional[Expression], into: Set[int]):
    if e is None:
        return
    for n in e.collect(lambda x: isinstance(x, BoundReference)):
        into.add(n.ordinal)


def _remap(e: Expression, mapping: Dict[int, int]) -> Expression:
    def fix(n):
        if isinstance(n, BoundReference):
            return BoundReference(mapping[n.ordinal], n._dtype, n._nullable,
                                  n.ref_name)
        return n
    return e.transform_up(fix)


def _identity(n: int) -> Dict[int, int]:
    return {i: i for i in range(n)}


class _Pruner:
    """One pruning rewrite over a plan tree."""

    def prune(self, node: Exec,
              required: Optional[Set[int]]) -> Tuple[Exec, Dict[int, int]]:
        """Returns (new_node, mapping old output ordinal -> new ordinal).

        ``required`` is the set of this node's output ordinals the parent
        consumes (None = all).  The mapping's key set always covers at least
        ``required``.
        """
        from spark_rapids_tpu.exec import basic as B
        from spark_rapids_tpu.exec import joins as JX
        from spark_rapids_tpu.exec import sort as S
        from spark_rapids_tpu.exec import aggregate as AG
        from spark_rapids_tpu.exec import exchange as EX

        ncols = len(node.schema.fields)
        if required is not None and len(required) >= ncols:
            required = None

        if isinstance(node, B.CpuProjectExec):
            exprs = node.exprs
            keep = sorted(required) if required is not None \
                else list(range(len(exprs)))
            kept = [exprs[i] for i in keep]
            child_req: Set[int] = set()
            for e in kept:
                _refs(e, child_req)
            child, cmap = self.prune(node.child, child_req)
            new = B.CpuProjectExec([_remap(e, cmap) for e in kept], child)
            return new, {o: i for i, o in enumerate(keep)}

        if isinstance(node, B.CpuFilterExec):
            child_req = set(required) if required is not None else None
            if child_req is not None:
                _refs(node.condition, child_req)
            child, cmap = self.prune(node.child, child_req)
            new = B.CpuFilterExec(_remap(node.condition, cmap), child)
            return new, cmap

        if isinstance(node, S.CpuSortExec):
            child_req = set(required) if required is not None else None
            if child_req is not None:
                for sp in node.specs:
                    _refs(sp.expr, child_req)
            child, cmap = self.prune(node.child, child_req)
            specs = [dataclasses_replace_spec(sp, _remap(sp.expr, cmap))
                     for sp in node.specs]
            new = S.CpuSortExec(specs, child, node.global_sort)
            return new, cmap

        if isinstance(node, EX.CpuShuffleExchangeExec):
            part = node.partitioning
            pexprs = getattr(part, "key_exprs", None)
            pspecs = getattr(part, "specs", None)
            child_req = set(required) if required is not None else None
            if child_req is not None:
                for e in (pexprs or []):
                    _refs(e, child_req)
                for sp in (pspecs or []):
                    _refs(sp.expr, child_req)
            child, cmap = self.prune(node.child, child_req)
            import copy
            npart = copy.copy(part)
            if pexprs is not None:
                npart.key_exprs = [_remap(e, cmap) for e in pexprs]
            if pspecs is not None:
                npart.specs = [dataclasses_replace_spec(sp,
                                                        _remap(sp.expr, cmap))
                               for sp in pspecs]
            new = EX.CpuShuffleExchangeExec(npart, child, node.shuffle_env)
            return new, cmap

        if isinstance(node, AG.CpuHashAggregateExec) and \
                type(node) is AG.CpuHashAggregateExec:
            layout = node.layout
            if node.mode not in (AG.PARTIAL, AG.COMPLETE):
                # FINAL/merge mode consumes the positional BUFFER schema of
                # its child (keys ++ agg buffers) — the layout's func/
                # grouping ordinals are bound against the pre-partial raw
                # input, a different schema space, so remapping them with
                # the child's map would corrupt them (and every buffer
                # column is required anyway).  Recurse keeping all child
                # columns; pruning continues below the exchange.
                child, _ = self.prune(node.child, None)
                return node.with_children([child]), _identity(ncols)
            child_req = set()
            for e in layout.grouping:
                _refs(e, child_req)
            for a in layout.aggs:
                _refs(a.func, child_req)
            child, cmap = self.prune(node.child, child_req)
            import dataclasses as dc
            grouping = [_remap(e, cmap) for e in layout.grouping]
            aggs = [dc.replace(a, func=_remap(a.func, cmap))
                    for a in layout.aggs]
            new = AG.CpuHashAggregateExec(grouping, aggs, node.mode, child)
            return new, _identity(ncols)

        if isinstance(node, JX._CpuJoinCore) and type(node) in (
                JX.CpuShuffledHashJoinExec, JX.CpuBroadcastHashJoinExec,
                JX.CpuBroadcastNestedLoopJoinExec):
            return self._prune_join(node, required)

        # pass-through nodes: schema == child schema, rows subset/identical
        if type(node) in (B.CpuLimitExec, B.CpuGlobalLimitExec,
                          B.CpuCoalescePartitionsExec, B.CpuSampleExec):
            child, cmap = self.prune(node.children[0], required)
            return node.with_children([child]), cmap

        # leaf scans that support pruning
        if not node.children:
            if required is not None:
                pruned = prune_scan(node, sorted(required))
                if pruned is not None:
                    return pruned, {o: i for i, o in
                                    enumerate(sorted(required))}
            return node, _identity(ncols)

        # barrier: unknown node — recurse requiring everything
        children = [self.prune(c, None)[0] for c in node.children]
        return node.with_children(children), _identity(ncols)

    def _prune_join(self, node, required: Optional[Set[int]]):
        from spark_rapids_tpu.exec import joins as JX
        import spark_rapids_tpu.ops.join_ops as J
        nl = len(node.left.schema.fields)
        nr = len(node.right.schema.fields)
        semi = node.join_type in (J.LEFT_SEMI, J.LEFT_ANTI)

        lreq: Set[int] = set()
        rreq: Set[int] = set()
        if required is None:
            lreq = set(range(nl))
            rreq = set(range(nr))
        else:
            for o in required:
                if o < nl:
                    lreq.add(o)
                elif not semi:
                    rreq.add(o - nl)
        for e in node.left_keys:
            _refs(e, lreq)
        for e in node.right_keys:
            _refs(e, rreq)
        cond_refs: Set[int] = set()
        _refs(node.condition, cond_refs)
        for o in cond_refs:
            if o < nl:
                lreq.add(o)
            else:
                rreq.add(o - nl)
        if semi:
            # right side still feeds keys/condition even though its columns
            # never reach the output
            pass

        left, lmap = self.prune(node.left, lreq)
        right, rmap = self.prune(node.right, rreq)
        nl_new = len(left.schema.fields)

        def pair_map(o: int) -> int:
            return lmap[o] if o < nl else nl_new + rmap[o - nl]

        cond = None if node.condition is None else \
            _remap(node.condition, {o: pair_map(o) for o in cond_refs})
        new = type(node)(
            [_remap(e, lmap) for e in node.left_keys],
            [_remap(e, rmap) for e in node.right_keys],
            node.join_type, cond, left, right, node.null_safe)
        out_map: Dict[int, int] = {}
        for o in lmap:
            out_map[o] = lmap[o]
        if not semi:
            for o in rmap:
                out_map[nl + o] = nl_new + rmap[o]
        return new, out_map


def dataclasses_replace_spec(sp, new_expr):
    import dataclasses as dc
    return dc.replace(sp, expr=new_expr)


def prune_scan(scan: Exec, indices: List[int]) -> Optional[Exec]:
    """Asks a leaf node for a column-subset clone; None if unsupported."""
    fn = getattr(scan, "with_pruned_columns", None)
    if fn is None:
        return None
    return fn(indices)


def prune_columns(plan: Exec, required: Optional[Set[int]] = None,
                  strict: bool = False) -> Exec:
    """Entry point: prunes unused columns below the root.

    ``required=None`` keeps the root's full output; an explicit set narrows
    it (count() passes an empty set: only row counts survive).  ``strict``
    (test mode) re-raises instead of silently executing unpruned — a
    pruning crash is a modeling bug, not an acceptable steady state.
    """
    import logging
    try:
        new, _ = _Pruner().prune(plan, required)
        return new
    except Exception:
        if strict:
            raise
        # pruning is an optimization; never let it break planning
        logging.getLogger(__name__).warning(
            "column pruning failed; executing unpruned plan", exc_info=True)
        return plan
