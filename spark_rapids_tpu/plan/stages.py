"""Whole-stage compilation planner pass.

Walks maximal device-side operator pipelines between exchange /
materialization boundaries — filter/project chains, the hash aggregate's
update pass (and, in the exec, its merge+final pass), sort-key prep —
and lowers each stage to ONE compiled XLA program (exec/fused.py,
programs cached process-wide by exec/stage_compiler).  This is the
engine's analog of Spark's whole-stage codegen and of Flare's
whole-query native compilation (PAPERS.md): the reference dispatches one
cuDF kernel per operator and cannot fuse across them; a tracing compiler
makes cross-operator fusion a plan rewrite.

**Literal promotion** (conf ``spark.rapids.sql.compile.literalPromotion``):
scalar literals in fused chains are promoted to RUNTIME ARGUMENTS of the
compiled program, so ``d_year = 1998`` and ``d_year = 1999`` — or a
dashboard's parameterized date ranges — share one executable instead of
compiling per value.  Program cache keys stay bounded by plan SHAPE, not
by literal cardinality.  Promotion is deliberately conservative: only
literals sitting directly under comparison / +,-,* arithmetic whose
sibling operand has the SAME data type are promoted (same-dtype operands
make the strong-typed runtime scalar bit-identical to the weak-typed
baked constant; mixed-dtype promotions could shift XLA's promotion rules
and break the bit-identical-vs-CPU contract).
"""

from __future__ import annotations

from typing import List, Tuple

from spark_rapids_tpu import types as T
from spark_rapids_tpu.expressions.base import Expression, Literal, TCol
from spark_rapids_tpu.plan.base import Exec

#: synced from spark.rapids.sql.compile.literalPromotion by
#: TpuOverrides.apply (stage fusion itself is gated in the planner on the
#: session conf directly)
LITERAL_PROMOTION = True


class PromotedLiteral(Literal):
    """A literal hoisted out of a fused stage's compiled program: its
    ``sql()`` renders a slot placeholder (so the program cache key is
    value-independent) and ``eval_tpu`` reads the value from the trace's
    runtime-argument list.  Outside a parameterized trace (CPU oracle,
    unfused re-planning) it degrades to a plain literal."""

    def __init__(self, value, dtype, slot: int):
        super().__init__(value, dtype)
        self.slot = slot

    def sql(self):
        return f"$lit{self.slot}:{self._dtype}"

    def eval_tpu(self, ctx):
        vals = getattr(ctx, "literal_args", None)
        if vals is None:
            return self._as_tcol()
        return TCol(vals[self.slot], True, self._dtype, is_scalar=True)


def physical_literal(value, dtype):
    """The runtime-argument form of a promoted literal: a strongly-typed
    numpy scalar in the column's physical representation (date -> days,
    timestamp -> micros) — exactly what ``materialize`` bakes for the
    constant form (one shared conversion), so the compiled math is
    identical."""
    import numpy as np
    from spark_rapids_tpu.expressions.base import to_physical_scalar
    return np.asarray(to_physical_scalar(value), dtype=dtype.np_dtype)


def _promotable_parents():
    from spark_rapids_tpu.expressions import arithmetic as A
    from spark_rapids_tpu.expressions import predicates as P
    return (P.EqualTo, P.NotEqual, P.LessThan, P.LessThanOrEqual,
            P.GreaterThan, P.GreaterThanOrEqual, P.EqualNullSafe,
            A.Add, A.Subtract, A.Multiply)


_PROMOTABLE_TYPES = (T.ByteType, T.ShortType, T.IntegerType, T.LongType,
                     T.FloatType, T.DoubleType, T.DateType, T.TimestampType)


def promote_stage_literals(ops) -> Tuple[list, List[PromotedLiteral]]:
    """Rewrites a fused stage's op chain, swapping eligible literals for
    ``PromotedLiteral`` slots.  Returns (new ops, promoted literals in
    slot order).  Idempotent over already-promoted chains (re-fusion
    renumbers the slots from the carried values)."""
    parents = _promotable_parents()
    promoted: List[PromotedLiteral] = []

    def has_input(e: Expression) -> bool:
        """The subtree evaluates per-row (carries a column / lambda-var
        reference), not to a python scalar."""
        if type(e) in (Literal, PromotedLiteral):
            return False
        if not e.children:
            return True     # column ref / bound ref / lambda variable
        return any(has_input(c) for c in e.children)

    def eligible(lit: Expression, sibling: Expression) -> bool:
        if type(lit) not in (Literal, PromotedLiteral) or lit.value is None:
            return False
        dt = lit.data_type
        if not isinstance(dt, _PROMOTABLE_TYPES) or \
                getattr(dt, "np_dtype", None) is None:
            return False
        if not has_input(sibling):
            # literal-vs-literal: the scalar-scalar eval branches run
            # python-level ops (bool()/np.asarray()) that a traced 0-d
            # runtime arg would crash; leave pure-constant math baked
            return False
        try:
            return str(sibling.data_type) == str(dt)
        except Exception:  # noqa: BLE001 — unresolved sibling: skip
            return False

    def walk(e: Expression) -> Expression:
        kids = [walk(c) for c in e.children]
        if isinstance(e, parents) and len(kids) == 2:
            for i in (0, 1):
                if eligible(kids[i], kids[1 - i]):
                    pl = PromotedLiteral(kids[i].value, kids[i].data_type,
                                         len(promoted))
                    promoted.append(pl)
                    kids[i] = pl
        return e.with_children(kids)

    new_ops = []
    for kind, payload in ops:
        if kind == "filter":
            new_ops.append(("filter", walk(payload)))
        else:
            new_ops.append(("project", [walk(p) for p in payload]))
    return new_ops, promoted


def fuse_device_stages(plan: Exec) -> Exec:
    """Whole-stage fusion pass: collapse maximal chains of device narrow
    ops (Filter/Project) — and, when they feed a hash aggregate, the
    aggregate's update pass — into ONE compiled XLA program
    (exec/fused.py).  The reference cannot do this — cuDF dispatches one
    kernel per operator; XLA's tracing model makes cross-operator fusion
    a plan rewrite."""
    from spark_rapids_tpu.exec.aggregate import (FINAL, TpuHashAggregateExec)
    from spark_rapids_tpu.exec.basic import (TpuFilterExec,
                                             TpuFilterProjectExec,
                                             TpuProjectExec)
    from spark_rapids_tpu.exec.fused import (TpuFusedAggExec,
                                             TpuFusedStageExec)

    def promote(ops):
        if not LITERAL_PROMOTION:
            return ops, []
        return promote_stage_literals(ops)

    def chain_of(node: Exec):
        """Descends through fusable narrow ops; returns (ops top-down ->
        bottom-up reversed, base child)."""
        ops = []
        cur = node
        while True:
            if isinstance(cur, TpuFilterExec):
                ops.append(("filter", cur.condition))
                cur = cur.children[0]
            elif isinstance(cur, TpuProjectExec):
                ops.append(("project", cur.exprs))
                cur = cur.children[0]
            elif isinstance(cur, TpuFilterProjectExec):
                ops.append(("project", cur.exprs))
                ops.append(("filter", cur.condition))
                cur = cur.children[0]
            elif isinstance(cur, TpuFusedStageExec):
                ops.extend(reversed(cur.ops))
                cur = cur.children[0]
            else:
                return list(reversed(ops)), cur

    def fix(node: Exec) -> Exec:
        if isinstance(node, TpuHashAggregateExec) and node.mode != FINAL \
                and not node._has_collect():
            # variable-length (collect) buffers run the dedicated
            # segmented_collect path in the exec, not the fused kernel
            ops, base = chain_of(node.children[0])
            ops, lits = promote(ops)
            lay = node.layout
            return TpuFusedAggExec(ops, lay, node.mode, base, promoted=lits)
        if isinstance(node, (TpuFilterExec, TpuProjectExec,
                             TpuFilterProjectExec)):
            ops, base = chain_of(node)
            # fuse whenever it saves a dispatch: any filter (eager predicate
            # + separate compact otherwise) or a multi-op chain
            if len(ops) >= 2 or any(k == "filter" for k, _ in ops):
                ops, lits = promote(ops)
                return TpuFusedStageExec(ops, base, promoted=lits)
        return node

    return plan.transform_up(fix)
