"""Type signature checks (reference: TypeChecks.scala — TypeSig bitmask
:138, ExecChecks/ExprChecks :932/:1057, and the generated supported_ops.md).

A ``TypeSig`` names which DataTypes an operator/expression supports on the
device; tagging produces human-readable reasons for fallback, and the same
tables generate ``docs/supported_ops.md`` (see docsgen)."""

from __future__ import annotations

from typing import Iterable, Optional, Set, Type

from spark_rapids_tpu import types as T


class TypeSig:
    def __init__(self, classes: Iterable[type], allow_decimal128: bool = False,
                 note: str = "", allow_device_arrays: bool = False):
        self.classes = tuple(classes)
        self.allow_decimal128 = allow_decimal128
        self.note = note
        #: arrays of fixed-width scalars ride the device as padded
        #: rectangular planes; only layout-agnostic ops opt in
        self.allow_device_arrays = allow_device_arrays

    def check(self, dt: T.DataType) -> Optional[str]:
        """None when supported, reason string otherwise."""
        if isinstance(dt, T.ArrayType):
            from spark_rapids_tpu.columnar.column import is_device_array_type
            if self.allow_device_arrays and is_device_array_type(dt):
                return None
            return (f"{dt.simple_name} is not supported here (device "
                    "arrays need fixed-width elements)")
        if isinstance(dt, T.DecimalType):
            if T.DecimalType not in self.classes:
                return f"{dt.simple_name} is not supported"
            if dt.is_decimal128 and not self.allow_decimal128:
                return f"{dt.simple_name}: precision > 18 not supported here"
            return None
        if isinstance(dt, tuple(c for c in self.classes
                                if c is not T.DecimalType)):
            return None
        return f"{dt.simple_name} is not supported" + \
            (f" ({self.note})" if self.note else "")

    def __add__(self, other: "TypeSig") -> "TypeSig":
        return TypeSig(set(self.classes) | set(other.classes),
                       self.allow_decimal128 or other.allow_decimal128,
                       allow_device_arrays=(self.allow_device_arrays
                                            or other.allow_device_arrays))

    def names(self) -> str:
        return ", ".join(sorted(c.__name__.replace("Type", "")
                                for c in self.classes))


_INTEGRAL = [T.ByteType, T.ShortType, T.IntegerType, T.LongType]
_FRACTIONAL = [T.FloatType, T.DoubleType]

INTEGRAL = TypeSig(_INTEGRAL)
NUMERIC = TypeSig(_INTEGRAL + _FRACTIONAL + [T.DecimalType])
NUMERIC_128 = TypeSig(_INTEGRAL + _FRACTIONAL + [T.DecimalType], True)
BOOLEAN = TypeSig([T.BooleanType])
STRING = TypeSig([T.StringType])
BINARY = TypeSig([T.BinaryType])
DATETIME = TypeSig([T.DateType, T.TimestampType])
NULL = TypeSig([T.NullType])

#: everything the device data plane can represent today (nested types are
#: host-only until the nested milestone — reference grew these over years)
ALL_BASIC = TypeSig(_INTEGRAL + _FRACTIONAL +
                    [T.BooleanType, T.StringType, T.BinaryType, T.DateType,
                     T.TimestampType, T.NullType, T.DecimalType], True)

COMPARABLE = TypeSig(_INTEGRAL + _FRACTIONAL +
                     [T.BooleanType, T.StringType, T.DateType,
                      T.TimestampType, T.DecimalType], True)

ORDERABLE = COMPARABLE
NESTED = TypeSig([T.ArrayType, T.MapType, T.StructType])

#: basics + device-resident arrays (padded rectangular plane).  Arrays are
#: PAYLOAD-only for sort/join/exchange: their registrations pair this sig
#: with ``no_array_keys`` so array-typed sort keys / join keys /
#: partitioning expressions still fall back (the key kernels are 1-D).
BASIC_WITH_ARRAYS = TypeSig(ALL_BASIC.classes, True,
                            allow_device_arrays=True)


class ParamCheck:
    """One named input slot of an operator with its own TypeSig
    (reference: TypeChecks.scala ParamCheck inside ExprChecks :1057)."""

    def __init__(self, name: str, sig: TypeSig):
        self.name = name
        self.sig = sig


class OpChecks:
    """Per-operator input/output type matrix (reference:
    ExecChecks :932 / ExprChecks :1057 in TypeChecks.scala).

    ``params`` match an expression's children positionally; when the op
    is variadic the LAST param repeats (``repeat_last``).  ``output``
    checks the expression's own data type.  Tagging produces per-slot
    reasons ("param 'value' of Sum: binary is not supported"), and
    docsgen renders one matrix row per slot — the per-op depth the
    single-sig registration couldn't express."""

    def __init__(self, output: TypeSig, params: Iterable[ParamCheck] = (),
                 repeat_last: bool = True, note: str = ""):
        self.output = output
        self.params = list(params)
        self.repeat_last = repeat_last
        self.note = note

    def param_for(self, i: int) -> Optional[ParamCheck]:
        if i < len(self.params):
            return self.params[i]
        if self.params and self.repeat_last:
            return self.params[-1]
        return None

    def check_expr(self, expr, add_reason) -> None:
        """Tags per-slot + output violations via ``add_reason(str)``."""
        name = type(expr).__name__
        for i, c in enumerate(expr.children):
            pc = self.param_for(i)
            if pc is None:
                continue
            try:
                dt = c.data_type
            except Exception:      # unresolved children tag elsewhere
                continue
            r = pc.sig.check(dt)
            if r is not None:
                add_reason(f"param {pc.name!r} of {name}: {r}")
        try:
            out_dt = expr.data_type
        except Exception:
            return
        r = self.output.check(out_dt)
        if r is not None:
            add_reason(f"result of {name}: {r}")


def no_array_keys(exprs, meta, what: str) -> None:
    """extra_tag helper: array-typed KEY expressions reject the device
    path (payload arrays are fine; the key word kernels are 1-D)."""
    for e in exprs:
        try:
            dt = e.data_type
        except Exception:    # noqa: BLE001 - unresolved exprs tag elsewhere
            continue
        if isinstance(dt, T.ArrayType):
            meta.will_not_work(
                f"{what} of type {dt.simple_name} is not supported on "
                "the device (arrays ride as payload only)")


def check_output_types(schema: T.StructType, sig: TypeSig) -> Optional[str]:
    for f in schema.fields:
        r = sig.check(f.data_type)
        if r is not None:
            return f"column {f.name!r}: {r}"
    return None
