"""Runtime plan-invariant verifier (conf ``spark.rapids.debug.planCheck``).

The planner passes in ``TpuOverrides.apply`` establish structural
contracts the execution layer silently depends on — and nothing used to
re-check the FINAL tree after every pass (several of which mutate in
place) had run.  This module is the runtime companion of the static
linter, in the exact mold of ``aux/lockorder``: armed by a debug conf,
it walks every post-optimization physical plan, emits a
``planInvariantViolation`` event per breach and counts them in a
process-wide counter surfaced by ``render_prometheus()``.

Checks (ids are the ``check`` field of the event):

- ``materialize-boundary``: with encoding on and late materialization
  OFF, every encoded-capable device scan sits directly under a
  ``TpuMaterializeEncodedExec``; with late materialization on (or
  encoding off) no materialize node exists at all
  (plan/encoding.insert_materialize_boundaries's contract).
- ``prefetch-placement``: no stacked spools (PrefetchExec directly
  wrapping PrefetchExec), the boundary label is one the planner pass
  knows, the node mirrors its child's device tier, the batch
  coalescer / adaptive reader never has a spool INSIDE it, and a
  pipeline-disabled plan carries no prefetch nodes
  (exec/pipeline.insert_pipeline_prefetch's contract).
- ``spillable-registration``: the spool implementation declares that
  queued device batches register with the spill framework
  (``PrefetchSpool.QUEUED_DEVICE_BATCHES_SPILLABLE``), and every
  device-side spool has a positive depth and in-flight-byte budget —
  an unbounded or unregistered queue holds device memory the catalog
  cannot evict.
- ``exchange-reuse``: no two DISTINCT shuffle-exchange instances in the
  final tree share an ``exchange_reuse_signature`` (plan/overrides.py —
  the verifier and the reuse pass share the one definition).  A pass
  that shallow-copies a shared exchange apart re-materializes the
  shuffle per parent; this is the bug class the in-place passes exist
  to avoid.
- ``distribution-consistency``: every shuffled hash join's sides agree
  on partition count, and a side with NO exchange/adaptive-reader
  boundary between the join and its sources (the distribution pass
  elided it) provably DELIVERS a hash distribution over that side's
  join keys at the join's partition count — re-derived on the final
  tree with the same plan/distribution.py analysis the elision pass
  used, so a pass that broke co-partitioning after elision is caught
  at plan time, not as silently wrong rows.
"""

from __future__ import annotations

import dataclasses
import threading
from typing import List

__all__ = ["PlanViolation", "verify_plan", "violations_total",
           "reset_observations"]

#: boundary labels exec/pipeline.insert_pipeline_prefetch may assign
KNOWN_PREFETCH_BOUNDARIES = frozenset(
    {"decode", "transfer", "shuffle", "upload", "d2h"})

_LOCK = threading.Lock()
_VIOLATIONS_TOTAL = 0


@dataclasses.dataclass
class PlanViolation:
    check: str
    node: str       # node name (class-level, stable across runs)
    detail: str


def violations_total() -> int:
    with _LOCK:
        return _VIOLATIONS_TOTAL


def reset_observations() -> None:
    global _VIOLATIONS_TOTAL
    with _LOCK:
        _VIOLATIONS_TOTAL = 0


def _walk_with_parent(plan):
    """(parent, node) pairs by IDENTITY, each shared instance once —
    reuse/CTE collapse makes the plan a DAG, and re-walking a shared
    exchange per parent would double-count (or double-report) it."""
    seen = set()
    out = []

    def visit(node, parent):
        out.append((parent, node))
        if id(node) in seen:
            return
        seen.add(id(node))
        for c in node.children:
            visit(c, node)

    visit(plan, None)
    return out


def verify_plan(plan, conf, emit_events: bool = True
                ) -> List[PlanViolation]:
    """Walks one post-optimization physical plan against the structural
    contracts above.  Observes and reports — it never raises, so an
    armed verifier cannot turn a benign drift into a query failure."""
    from spark_rapids_tpu import config as C
    from spark_rapids_tpu.exec.adaptive import AdaptiveShuffleReaderExec
    from spark_rapids_tpu.exec.basic import (TpuCoalesceBatchesExec,
                                             TpuMaterializeEncodedExec)
    from spark_rapids_tpu.exec.exchange import CpuShuffleExchangeExec
    from spark_rapids_tpu.exec.pipeline import (PIPELINE_DEPTH,
                                                PIPELINE_MAX_BYTES,
                                                PrefetchExec, PrefetchSpool)
    from spark_rapids_tpu.io.multifile import MultiFileScanBase
    from spark_rapids_tpu.plan.overrides import exchange_reuse_signature

    violations: List[PlanViolation] = []

    def report(check: str, node, detail: str) -> None:
        violations.append(PlanViolation(check, node.name, detail))

    pairs = _walk_with_parent(plan)
    enc_on = bool(conf.get(C.ENCODING_ENABLED.key))
    late_mat = bool(conf.get(C.ENCODING_LATE_MAT.key))
    pipeline_on = bool(conf.get(C.PIPELINE_ENABLED.key))
    reuse_on = bool(conf.get(C.EXCHANGE_REUSE_ENABLED.key))

    for parent, node in pairs:
        # -- materialize boundaries ------------------------------------
        if isinstance(node, MultiFileScanBase) and \
                getattr(node, "is_device", False) and \
                enc_on and not late_mat and \
                not isinstance(parent, TpuMaterializeEncodedExec):
            report("materialize-boundary", node,
                   "encoded-capable device scan without a "
                   "TpuMaterializeEncoded parent while "
                   "lateMaterialization=false — operators would see "
                   "encoded columns the plan promised to decode eagerly")
        if isinstance(node, TpuMaterializeEncodedExec) and \
                (not enc_on or late_mat):
            report("materialize-boundary", node,
                   "eager materialize node present although the conf "
                   "asks for " +
                   ("late materialization" if enc_on else
                    "encoding disabled") +
                   " — the planner pass must be an exact no-op here")
        # -- prefetch placement ----------------------------------------
        if isinstance(node, PrefetchExec):
            if not pipeline_on:
                report("prefetch-placement", node,
                       "prefetch node in a pipeline-disabled plan")
            if node.children and isinstance(node.children[0],
                                            PrefetchExec):
                report("prefetch-placement", node,
                       "stacked prefetch spools (spool directly wraps "
                       "a spool): double buffering, double threads, "
                       "zero extra overlap")
            if node.boundary not in KNOWN_PREFETCH_BOUNDARIES:
                report("prefetch-placement", node,
                       f"unknown boundary {node.boundary!r} (planner "
                       "inserts only "
                       f"{sorted(KNOWN_PREFETCH_BOUNDARIES)})")
            if node.children and \
                    node.is_device != node.children[0].is_device:
                report("prefetch-placement", node,
                       "prefetch node's device tier does not mirror "
                       "its child — transitions/markers above it see "
                       "the wrong tier")
            # -- spillable registration of queued batches --------------
            if not getattr(PrefetchSpool,
                           "QUEUED_DEVICE_BATCHES_SPILLABLE", False):
                report("spillable-registration", node,
                       "PrefetchSpool no longer declares queued device "
                       "batches spillable — in-flight prefetch would "
                       "pin device memory the catalog cannot evict")
            depth = node.depth if node.depth is not None else \
                PIPELINE_DEPTH
            max_bytes = node.max_bytes if node.max_bytes is not None \
                else PIPELINE_MAX_BYTES
            if getattr(node, "is_device", False) and \
                    (depth < 1 or max_bytes <= 0):
                report("spillable-registration", node,
                       f"device-side spool with depth={depth} "
                       f"max_bytes={max_bytes}: queued device batches "
                       "must be bounded (and thereby catalog-budgeted)")
        if isinstance(node, (TpuCoalesceBatchesExec,
                             AdaptiveShuffleReaderExec)) and \
                node.children and \
                isinstance(node.children[0], PrefetchExec):
            report("prefetch-placement", node,
                   f"{node.name} introspects its direct child; the "
                   "spool belongs ABOVE it, never inside")

    # -- distribution consistency (post-elision co-partitioning) -------
    if conf.get(C.DISTRIBUTION_ENABLED.key):
        _check_distribution(pairs, report)

    # -- exchange-reuse key consistency --------------------------------
    if reuse_on:
        # dedupe by IDENTITY first: a correctly-reused exchange appears
        # once per parent edge in the walk, and counting those edges
        # would flag reuse WORKING as reuse broken
        by_sig: dict = {}
        seen_ids: set = set()
        for _parent, node in pairs:
            if isinstance(node, CpuShuffleExchangeExec) and \
                    id(node) not in seen_ids:
                seen_ids.add(id(node))
                by_sig.setdefault(exchange_reuse_signature(node),
                                  []).append(node)
        for sig, nodes in by_sig.items():
            if len(nodes) > 1:
                report("exchange-reuse", nodes[0],
                       f"{len(nodes)} distinct exchange instances share "
                       "one reuse signature — a pass split a shared "
                       "exchange apart (or reuse never merged them); "
                       "the shuffle materializes once per copy")

    if violations:
        global _VIOLATIONS_TOTAL
        with _LOCK:
            _VIOLATIONS_TOTAL += len(violations)
        if emit_events:
            from spark_rapids_tpu.aux.events import emit
            for v in violations:
                emit("planInvariantViolation", check=v.check,
                     node=v.node, detail=v.detail)
    return violations


def _has_partition_boundary(node) -> bool:
    """True when the subtree rooted at ``node`` establishes its own
    partitioning before any source: an exchange or adaptive reader
    reached through partition-count-preserving unary nodes."""
    from spark_rapids_tpu.exec.adaptive import AdaptiveShuffleReaderExec
    from spark_rapids_tpu.exec.exchange import CpuShuffleExchangeExec
    from spark_rapids_tpu.plan.base import UnaryExec
    while True:
        if isinstance(node, (CpuShuffleExchangeExec,
                             AdaptiveShuffleReaderExec)):
            return True
        if isinstance(node, UnaryExec) and node.children and \
                node.num_partitions == node.children[0].num_partitions:
            node = node.children[0]
            continue
        return False


def _count_is_static(node) -> bool:
    """False when the subtree's partition count depends on an adaptive
    reader whose specs have not been computed yet — touching
    ``num_partitions`` there would MATERIALIZE the exchange during plan
    verification (the verifier must observe, never execute)."""
    from spark_rapids_tpu.exec.adaptive import AdaptiveShuffleReaderExec
    for n in node.collect_nodes():
        if isinstance(n, AdaptiveShuffleReaderExec) and \
                n._specs is None and \
                (n._shared is None or n._shared._specs is None):
            return False
    return True


def _check_distribution(pairs, report) -> None:
    """The ``distribution-consistency`` invariant over the final tree."""
    from spark_rapids_tpu.exec.joins import (CpuShuffledHashJoinExec,
                                             TpuShuffledHashJoinExec)
    from spark_rapids_tpu.plan.distribution import (HashDist, canon,
                                                    delivered_dists)
    dist_memo: dict = {}
    seen = set()
    for _parent, node in pairs:
        if not isinstance(node, (CpuShuffledHashJoinExec,
                                 TpuShuffledHashJoinExec)):
            continue
        if id(node) in seen:
            continue
        seen.add(id(node))
        left, right = node.children
        if not (_count_is_static(left) and _count_is_static(right)):
            # pending adaptive specs: the runtime co-partitioning guard
            # (exec/joins._check_copartitioned) covers this join once
            # the specs exist
            continue
        if left.num_partitions != right.num_partitions:
            report("distribution-consistency", node,
                   f"shuffled join sides have {left.num_partitions} vs "
                   f"{right.num_partitions} partitions — partition i "
                   "no longer pairs with partition i")
            continue
        n = node.num_partitions
        if n <= 1:
            continue
        for side, keys, label in ((left, node.left_keys, "left"),
                                  (right, node.right_keys, "right")):
            if _has_partition_boundary(side):
                continue
            want = tuple(canon(k) for k in keys)
            ok = any(isinstance(d, HashDist) and d.keys == want
                     and d.n == n
                     for d in delivered_dists(side, dist_memo))
            if not ok:
                report("distribution-consistency", node,
                       f"{label} side has no exchange boundary and does "
                       "not provably deliver "
                       f"hash(join keys, {n}) — an elided (or never "
                       "inserted) exchange left the join "
                       "mis-partitioned")
