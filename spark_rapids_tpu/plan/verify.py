"""Runtime plan-invariant verifier (conf ``spark.rapids.debug.planCheck``).

The planner passes in ``TpuOverrides.apply`` establish structural
contracts the execution layer silently depends on — and nothing used to
re-check the FINAL tree after every pass (several of which mutate in
place) had run.  This module is the runtime companion of the static
linter, in the exact mold of ``aux/lockorder``: armed by a debug conf,
it walks every post-optimization physical plan, emits a
``planInvariantViolation`` event per breach and counts them in a
process-wide counter surfaced by ``render_prometheus()``.

Checks (ids are the ``check`` field of the event):

- ``materialize-boundary``: with encoding on and late materialization
  OFF, every encoded-capable device scan sits directly under a
  ``TpuMaterializeEncodedExec``; with late materialization on (or
  encoding off) no materialize node exists at all
  (plan/encoding.insert_materialize_boundaries's contract).
- ``prefetch-placement``: no stacked spools (PrefetchExec directly
  wrapping PrefetchExec), the boundary label is one the planner pass
  knows, the node mirrors its child's device tier, the batch
  coalescer / adaptive reader never has a spool INSIDE it, and a
  pipeline-disabled plan carries no prefetch nodes
  (exec/pipeline.insert_pipeline_prefetch's contract).
- ``spillable-registration``: the spool implementation declares that
  queued device batches register with the spill framework
  (``PrefetchSpool.QUEUED_DEVICE_BATCHES_SPILLABLE``), and every
  device-side spool has a positive depth and in-flight-byte budget —
  an unbounded or unregistered queue holds device memory the catalog
  cannot evict.
- ``exchange-reuse``: no two DISTINCT shuffle-exchange instances in the
  final tree share an ``exchange_reuse_signature`` (plan/overrides.py —
  the verifier and the reuse pass share the one definition).  A pass
  that shallow-copies a shared exchange apart re-materializes the
  shuffle per parent; this is the bug class the in-place passes exist
  to avoid.
"""

from __future__ import annotations

import dataclasses
import threading
from typing import List

__all__ = ["PlanViolation", "verify_plan", "violations_total",
           "reset_observations"]

#: boundary labels exec/pipeline.insert_pipeline_prefetch may assign
KNOWN_PREFETCH_BOUNDARIES = frozenset(
    {"decode", "transfer", "shuffle", "upload", "d2h"})

_LOCK = threading.Lock()
_VIOLATIONS_TOTAL = 0


@dataclasses.dataclass
class PlanViolation:
    check: str
    node: str       # node name (class-level, stable across runs)
    detail: str


def violations_total() -> int:
    with _LOCK:
        return _VIOLATIONS_TOTAL


def reset_observations() -> None:
    global _VIOLATIONS_TOTAL
    with _LOCK:
        _VIOLATIONS_TOTAL = 0


def _walk_with_parent(plan):
    """(parent, node) pairs by IDENTITY, each shared instance once —
    reuse/CTE collapse makes the plan a DAG, and re-walking a shared
    exchange per parent would double-count (or double-report) it."""
    seen = set()
    out = []

    def visit(node, parent):
        out.append((parent, node))
        if id(node) in seen:
            return
        seen.add(id(node))
        for c in node.children:
            visit(c, node)

    visit(plan, None)
    return out


def verify_plan(plan, conf, emit_events: bool = True
                ) -> List[PlanViolation]:
    """Walks one post-optimization physical plan against the structural
    contracts above.  Observes and reports — it never raises, so an
    armed verifier cannot turn a benign drift into a query failure."""
    from spark_rapids_tpu import config as C
    from spark_rapids_tpu.exec.adaptive import AdaptiveShuffleReaderExec
    from spark_rapids_tpu.exec.basic import (TpuCoalesceBatchesExec,
                                             TpuMaterializeEncodedExec)
    from spark_rapids_tpu.exec.exchange import CpuShuffleExchangeExec
    from spark_rapids_tpu.exec.pipeline import (PIPELINE_DEPTH,
                                                PIPELINE_MAX_BYTES,
                                                PrefetchExec, PrefetchSpool)
    from spark_rapids_tpu.io.multifile import MultiFileScanBase
    from spark_rapids_tpu.plan.overrides import exchange_reuse_signature

    violations: List[PlanViolation] = []

    def report(check: str, node, detail: str) -> None:
        violations.append(PlanViolation(check, node.name, detail))

    pairs = _walk_with_parent(plan)
    enc_on = bool(conf.get(C.ENCODING_ENABLED.key))
    late_mat = bool(conf.get(C.ENCODING_LATE_MAT.key))
    pipeline_on = bool(conf.get(C.PIPELINE_ENABLED.key))
    reuse_on = bool(conf.get(C.EXCHANGE_REUSE_ENABLED.key))

    for parent, node in pairs:
        # -- materialize boundaries ------------------------------------
        if isinstance(node, MultiFileScanBase) and \
                getattr(node, "is_device", False) and \
                enc_on and not late_mat and \
                not isinstance(parent, TpuMaterializeEncodedExec):
            report("materialize-boundary", node,
                   "encoded-capable device scan without a "
                   "TpuMaterializeEncoded parent while "
                   "lateMaterialization=false — operators would see "
                   "encoded columns the plan promised to decode eagerly")
        if isinstance(node, TpuMaterializeEncodedExec) and \
                (not enc_on or late_mat):
            report("materialize-boundary", node,
                   "eager materialize node present although the conf "
                   "asks for " +
                   ("late materialization" if enc_on else
                    "encoding disabled") +
                   " — the planner pass must be an exact no-op here")
        # -- prefetch placement ----------------------------------------
        if isinstance(node, PrefetchExec):
            if not pipeline_on:
                report("prefetch-placement", node,
                       "prefetch node in a pipeline-disabled plan")
            if node.children and isinstance(node.children[0],
                                            PrefetchExec):
                report("prefetch-placement", node,
                       "stacked prefetch spools (spool directly wraps "
                       "a spool): double buffering, double threads, "
                       "zero extra overlap")
            if node.boundary not in KNOWN_PREFETCH_BOUNDARIES:
                report("prefetch-placement", node,
                       f"unknown boundary {node.boundary!r} (planner "
                       "inserts only "
                       f"{sorted(KNOWN_PREFETCH_BOUNDARIES)})")
            if node.children and \
                    node.is_device != node.children[0].is_device:
                report("prefetch-placement", node,
                       "prefetch node's device tier does not mirror "
                       "its child — transitions/markers above it see "
                       "the wrong tier")
            # -- spillable registration of queued batches --------------
            if not getattr(PrefetchSpool,
                           "QUEUED_DEVICE_BATCHES_SPILLABLE", False):
                report("spillable-registration", node,
                       "PrefetchSpool no longer declares queued device "
                       "batches spillable — in-flight prefetch would "
                       "pin device memory the catalog cannot evict")
            depth = node.depth if node.depth is not None else \
                PIPELINE_DEPTH
            max_bytes = node.max_bytes if node.max_bytes is not None \
                else PIPELINE_MAX_BYTES
            if getattr(node, "is_device", False) and \
                    (depth < 1 or max_bytes <= 0):
                report("spillable-registration", node,
                       f"device-side spool with depth={depth} "
                       f"max_bytes={max_bytes}: queued device batches "
                       "must be bounded (and thereby catalog-budgeted)")
        if isinstance(node, (TpuCoalesceBatchesExec,
                             AdaptiveShuffleReaderExec)) and \
                node.children and \
                isinstance(node.children[0], PrefetchExec):
            report("prefetch-placement", node,
                   f"{node.name} introspects its direct child; the "
                   "spool belongs ABOVE it, never inside")

    # -- exchange-reuse key consistency --------------------------------
    if reuse_on:
        # dedupe by IDENTITY first: a correctly-reused exchange appears
        # once per parent edge in the walk, and counting those edges
        # would flag reuse WORKING as reuse broken
        by_sig: dict = {}
        seen_ids: set = set()
        for _parent, node in pairs:
            if isinstance(node, CpuShuffleExchangeExec) and \
                    id(node) not in seen_ids:
                seen_ids.add(id(node))
                by_sig.setdefault(exchange_reuse_signature(node),
                                  []).append(node)
        for sig, nodes in by_sig.items():
            if len(nodes) > 1:
                report("exchange-reuse", nodes[0],
                       f"{len(nodes)} distinct exchange instances share "
                       "one reuse signature — a pass split a shared "
                       "exchange apart (or reuse never merged them); "
                       "the shuffle materializes once per copy")

    if violations:
        global _VIOLATIONS_TOTAL
        with _LOCK:
            _VIOLATIONS_TOTAL += len(violations)
        if emit_events:
            from spark_rapids_tpu.aux.events import emit
            for v in violations:
                emit("planInvariantViolation", check=v.check,
                     node=v.node, detail=v.detail)
    return violations
