"""Regex transpiler: Java-dialect patterns -> the engine's execution dialect.

Reference: ``RegexParser.scala`` (2183 LoC) parses Java regex into an AST and
transpiles to the cuDF dialect, rejecting constructs the device engine cannot
run faithfully; ``RegexComplexityEstimator.scala`` bounds device memory;
``RegexRewriteUtils`` (JNI) rewrites simple patterns into
startswith/endswith/contains kernels.

TPU stance: a backtracking byte-automaton is TPU-hostile, so general regex
runs on the host tier (honest fallback tagging, as the reference does for
unsupported ops).  This module plays all three reference roles:

1. parse: Spark expressions carry *Java* regex; we parse the Java dialect
   (with its escapes: \\uXXXX, \\0n octal, \\cX, \\p{Posix}, \\Q...\\E) and
   reject what cannot be translated faithfully (lookaround, backreferences,
   possessive quantifiers, atomic groups, inline flags, \\G, \\R, \\X).
2. transpile: emit an equivalent pattern in the host engine's dialect
   (Python ``re``), translating the divergent escapes.
3. rewrite: detect patterns that reduce to literal prefix/suffix/contains/
   equals and report the rewrite so the planner can run them as device
   kernels (the RegexRewriteUtils trick).

Modes mirror the reference's RegexMode: FIND (RLike), REPLACE
(regexp_replace), SPLIT (string split).
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Tuple

FIND = "FIND"
REPLACE = "REPLACE"
SPLIT = "SPLIT"


class RegexUnsupported(ValueError):
    """reference: RegexUnsupportedException — the pattern cannot run in the
    accelerated engine; callers fall back (or surface the reason)."""

    def __init__(self, msg: str, pos: Optional[int] = None):
        self.pos = pos
        super().__init__(msg if pos is None else f"{msg} near position {pos}")


# ---------------------------------------------------------------------------
# AST (reference: RegexAST sealed trait family in RegexParser.scala)
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class RegexNode:
    pass


@dataclasses.dataclass
class RLiteral(RegexNode):
    ch: str            # one literal character (unescaped)


@dataclasses.dataclass
class RSequence(RegexNode):
    parts: List[RegexNode]


@dataclasses.dataclass
class RAlternation(RegexNode):
    branches: List[RegexNode]


@dataclasses.dataclass
class RCharClass(RegexNode):
    body: str          # transpiled class body WITHOUT brackets
    negated: bool


@dataclasses.dataclass
class RPredef(RegexNode):
    cls: str           # one of d D w W s S .


@dataclasses.dataclass
class RAnchor(RegexNode):
    kind: str          # ^ $ \A \Z \z \b \B


@dataclasses.dataclass
class RGroup(RegexNode):
    child: RegexNode
    capturing: bool
    name: Optional[str] = None


@dataclasses.dataclass
class RRepeat(RegexNode):
    child: RegexNode
    min: int
    max: Optional[int]  # None = unbounded
    lazy: bool


_POSIX_CLASSES = {
    # Java \p{...} POSIX classes -> python class bodies (US-ASCII semantics,
    # matching Java's default; reference transpiles these the same way)
    "Lower": "a-z", "Upper": "A-Z", "Alpha": "a-zA-Z", "Digit": "0-9",
    "Alnum": "a-zA-Z0-9", "Punct": r"!-/:-@\[-`{-~", "Graph": "!-~",
    "Print": " -~", "Blank": r" \t", "Space": r" \t\n\x0b\f\r",
    "XDigit": "0-9a-fA-F", "Cntrl": r"\x00-\x1f\x7f", "ASCII": r"\x00-\x7f",
}

_UNSUPPORTED_GROUPS = {
    "=": "lookahead", "!": "negative lookahead",
    "<=": "lookbehind", "<!": "negative lookbehind",
    ">": "atomic group",
}


class _Parser:
    """Recursive-descent parser over the Java pattern string
    (reference: RegexParser.parse / parseInternal)."""

    def __init__(self, pattern: str):
        self.p = pattern
        self.i = 0
        self.group_count = 0

    # -- stream helpers ------------------------------------------------------
    def peek(self, off: int = 0) -> Optional[str]:
        j = self.i + off
        return self.p[j] if j < len(self.p) else None

    def take(self) -> str:
        ch = self.p[self.i]
        self.i += 1
        return ch

    def expect(self, ch: str):
        if self.peek() != ch:
            raise RegexUnsupported(f"expected {ch!r}", self.i)
        self.take()

    def fail(self, msg: str):
        raise RegexUnsupported(msg, self.i)

    # -- grammar -------------------------------------------------------------
    def parse(self) -> RegexNode:
        node = self.alternation()
        if self.i != len(self.p):
            self.fail(f"unexpected {self.peek()!r}")
        return node

    def alternation(self) -> RegexNode:
        branches = [self.sequence()]
        while self.peek() == "|":
            self.take()
            branches.append(self.sequence())
        return branches[0] if len(branches) == 1 else RAlternation(branches)

    def sequence(self) -> RegexNode:
        parts: List[RegexNode] = []
        while True:
            ch = self.peek()
            if ch is None or ch in "|)":
                break
            parts.append(self.quantified())
        return RSequence(parts)

    def quantified(self) -> RegexNode:
        atom = self.atom()
        ch = self.peek()
        rep: Optional[Tuple[int, Optional[int]]] = None
        if ch == "*":
            self.take()
            rep = (0, None)
        elif ch == "+":
            self.take()
            rep = (1, None)
        elif ch == "?":
            self.take()
            rep = (0, 1)
        elif ch == "{":
            rep = self.brace_quantifier()
        if rep is None:
            return atom
        if isinstance(atom, RAnchor):
            self.fail(f"quantifier on anchor {atom.kind!r} is not supported")
        lazy = False
        nxt = self.peek()
        if nxt == "?":
            self.take()
            lazy = True
        elif nxt == "+":
            self.fail("possessive quantifiers are not supported")
        return RRepeat(atom, rep[0], rep[1], lazy)

    def brace_quantifier(self) -> Optional[Tuple[int, Optional[int]]]:
        # {n} {n,} {n,m}; a non-matching '{' is a literal in Java
        start = self.i
        self.take()  # {
        digits = ""
        while self.peek() is not None and self.peek().isdigit():
            digits += self.take()
        if not digits:
            self.i = start
            return None  # literal '{' handled by atom on next call
        lo = int(digits)
        hi: Optional[int] = lo
        if self.peek() == ",":
            self.take()
            digits2 = ""
            while self.peek() is not None and self.peek().isdigit():
                digits2 += self.take()
            hi = int(digits2) if digits2 else None
        if self.peek() != "}":
            self.i = start
            return None
        self.take()
        if hi is not None and hi < lo:
            self.fail(f"bad quantifier range {{{lo},{hi}}}")
        return (lo, hi)

    def atom(self) -> RegexNode:
        ch = self.peek()
        if ch == "(":
            return self.group()
        if ch == "[":
            return self.char_class()
        if ch == "\\":
            return self.escape()
        if ch in "*+?":
            self.fail(f"dangling quantifier {ch!r}")
        if ch == "^":
            self.take()
            return RAnchor("^")
        if ch == "$":
            self.take()
            return RAnchor("$")
        if ch == ".":
            self.take()
            return RPredef(".")
        if ch == "{":
            # tried as quantifier by caller only after an atom; here literal
            self.take()
            return RLiteral("{")
        return RLiteral(self.take())

    def group(self) -> RegexNode:
        self.take()  # (
        capturing = True
        name = None
        if self.peek() == "?":
            self.take()
            nxt = self.peek()
            if nxt == ":":
                self.take()
                capturing = False
            elif nxt == "<" and self.peek(1) not in ("=", "!"):
                # named capturing group (?<name>...) -> python (?P<name>...)
                self.take()
                name = ""
                while self.peek() is not None and self.peek() != ">":
                    name += self.take()
                self.expect(">")
            else:
                two = (nxt or "") + (self.peek(1) or "")
                for key, what in _UNSUPPORTED_GROUPS.items():
                    if two.startswith(key):
                        self.fail(f"{what} is not supported")
                self.fail(f"inline flags/special group (?{nxt} not supported")
        if capturing:
            self.group_count += 1
        child = self.alternation()
        self.expect(")")
        return RGroup(child, capturing, name)

    # -- escapes -------------------------------------------------------------
    def escape(self) -> RegexNode:
        self.take()  # backslash
        ch = self.peek()
        if ch is None:
            self.fail("pattern ends with a bare backslash")
        if ch in "dDwWsS":
            self.take()
            return RPredef(ch)
        if ch in "bB":
            self.take()
            return RAnchor("\\" + ch)
        if ch in "AzZ":
            self.take()
            return RAnchor("\\" + ch)
        if ch == "G":
            self.fail("\\G (end of previous match) is not supported")
        if ch in ("R", "X"):
            self.fail(f"\\{ch} is not supported")
        if ch.isdigit() and ch != "0":
            self.fail("backreferences are not supported")
        if ch == "k":
            self.fail("named backreferences are not supported")
        if ch == "Q":
            # \Q ... \E literal quotation
            self.take()
            lits: List[RegexNode] = []
            while True:
                c = self.peek()
                if c is None:
                    break
                if c == "\\" and self.peek(1) == "E":
                    self.take()
                    self.take()
                    break
                lits.append(RLiteral(self.take()))
            return RSequence(lits)
        if ch == "E":
            self.fail("\\E without \\Q")
        if ch == "p" or ch == "P":
            return self.posix_class(ch == "P")
        if ch == "u":
            self.take()
            hexs = "".join(self.take() for _ in range(4)
                           if self.peek() is not None)
            if len(hexs) != 4:
                self.fail("\\u needs exactly four hex digits")
            try:
                return RLiteral(chr(int(hexs, 16)))
            except ValueError:
                self.fail("bad \\uXXXX escape")
        if ch == "x":
            self.take()
            if self.peek() == "{":
                self.take()
                hexs = ""
                while self.peek() not in (None, "}"):
                    hexs += self.take()
                self.expect("}")
            else:
                hexs = "".join(self.take() for _ in range(2)
                               if self.peek() is not None)
                if len(hexs) != 2:
                    self.fail("\\x needs two hex digits")
            try:
                return RLiteral(chr(int(hexs, 16)))
            except ValueError:
                self.fail("bad hex escape")
        if ch == "0":
            # Java octal \0n \0nn \0mnn
            self.take()
            digs = ""
            while len(digs) < 3 and self.peek() is not None \
                    and self.peek() in "01234567":
                digs += self.take()
            if not digs:
                self.fail("bad octal escape")
            return RLiteral(chr(int(digs, 8)))
        if ch == "c":
            self.take()
            c = self.take() if self.peek() is not None else None
            if c is None:
                self.fail("bad \\cX escape")
            # Java XORs the raw operand (no case folding): \cj -> 0x2a '*'
            return RLiteral(chr(ord(c) ^ 0x40))
        if ch == "a":
            self.take()
            return RLiteral("\x07")
        if ch == "e":
            self.take()
            return RLiteral("\x1b")
        if ch == "f":
            self.take()
            return RLiteral("\f")
        if ch == "n":
            self.take()
            return RLiteral("\n")
        if ch == "r":
            self.take()
            return RLiteral("\r")
        if ch == "t":
            self.take()
            return RLiteral("\t")
        if ch.isalpha():
            self.fail(f"unknown escape \\{ch}")
        return RLiteral(self.take())

    def posix_class(self, negated: bool) -> RegexNode:
        self.take()  # p or P
        if self.peek() != "{":
            self.fail("\\p requires {Name}")
        self.take()
        name = ""
        while self.peek() not in (None, "}"):
            name += self.take()
        self.expect("}")
        body = _POSIX_CLASSES.get(name)
        if body is None:
            self.fail(f"unsupported character property \\p{{{name}}}")
        return RCharClass(body, negated)

    # -- character classes ---------------------------------------------------
    def char_class(self) -> RegexNode:
        self.take()  # [
        negated = False
        if self.peek() == "^":
            self.take()
            negated = True
        body = ""
        first = True
        while True:
            ch = self.peek()
            if ch is None:
                self.fail("unterminated character class")
            if ch == "]" and not first:
                self.take()
                break
            first = False
            if ch == "[" and self.peek(1) == ":":
                self.fail("POSIX [:class:] syntax is not supported")
            if ch == "&" and self.peek(1) == "&":
                self.fail("character class intersection (&&) not supported")
            if ch == "[":
                self.fail("nested character classes are not supported")
            if ch == "\\":
                node = self.escape()
                if isinstance(node, RPredef):
                    body += "\\" + node.cls
                elif isinstance(node, RCharClass):
                    if node.negated:
                        self.fail("negated property inside a class")
                    body += node.body
                elif isinstance(node, RAnchor):
                    if node.kind == "\\b":
                        body += "\\x08"  # inside a class \b is backspace
                    else:
                        self.fail(f"{node.kind} inside a character class")
                elif isinstance(node, RSequence):  # \Q..\E inside class
                    for lit in node.parts:
                        body += _escape_class_char(lit.ch)
                else:
                    body += _escape_class_char(node.ch)
                continue
            if ch == "-" and self.peek(1) not in (None, "]") and body:
                # range: previous char - next char
                self.take()
                body += "-"
                continue
            taken = self.take()
            body += _escape_class_char(taken)
        if not body:
            self.fail("empty character class")
        return RCharClass(body, negated)


def _escape_class_char(ch: str) -> str:
    if ch in r"\^]-[":
        return "\\" + ch
    return ch


# ---------------------------------------------------------------------------
# Emission to the host dialect (python re)
# ---------------------------------------------------------------------------

_PY_SPECIAL = set(r"\.[]{}()*+?^$|")


def _emit(node: RegexNode) -> str:
    if isinstance(node, RLiteral):
        ch = node.ch
        if ch in _PY_SPECIAL:
            return "\\" + ch
        if ord(ch) < 0x20 or ord(ch) == 0x7f:
            return f"\\x{ord(ch):02x}"
        return ch
    if isinstance(node, RSequence):
        return "".join(_emit(p) for p in node.parts)
    if isinstance(node, RAlternation):
        return "|".join(_emit(b) for b in node.branches)
    if isinstance(node, RCharClass):
        return f"[{'^' if node.negated else ''}{node.body}]"
    if isinstance(node, RPredef):
        return "." if node.cls == "." else "\\" + node.cls
    if isinstance(node, RAnchor):
        if node.kind == "\\Z":
            # Java \Z = before final line terminator; python \Z is absolute
            return r"(?=\n?\Z)"
        if node.kind == "\\z":
            return r"\Z"
        return node.kind
    if isinstance(node, RGroup):
        inner = _emit(node.child)
        if node.name:
            return f"(?P<{node.name}>{inner})"
        return f"({inner})" if node.capturing else f"(?:{inner})"
    if isinstance(node, RRepeat):
        inner = _emit(node.child)
        if isinstance(node.child, (RSequence, RAlternation)):
            inner = f"(?:{inner})"
        if (node.min, node.max) == (0, None):
            q = "*"
        elif (node.min, node.max) == (1, None):
            q = "+"
        elif (node.min, node.max) == (0, 1):
            q = "?"
        elif node.max is None:
            q = f"{{{node.min},}}"
        elif node.min == node.max:
            q = f"{{{node.min}}}"
        else:
            q = f"{{{node.min},{node.max}}}"
        return inner + q + ("?" if node.lazy else "")
    raise AssertionError(f"unhandled node {node}")


# ---------------------------------------------------------------------------
# Complexity estimation (reference: RegexComplexityEstimator.scala — bounds
# device memory; here bounds backtracking blowup)
# ---------------------------------------------------------------------------

def complexity(node: RegexNode, depth_unbounded: int = 0) -> int:
    """Rough work estimate; nested unbounded repeats multiply
    (the catastrophic-backtracking shape)."""
    if isinstance(node, RRepeat):
        inner_depth = depth_unbounded + (1 if node.max is None else 0)
        # quadratic exponent: any two nested unbounded repeats (the
        # catastrophic-backtracking shape, e.g. (a+)+) exceed MAX_COMPLEXITY
        weight = 10 ** (2 * inner_depth) if node.max is None \
            else max(1, (node.max or 1))
        return weight * (1 + complexity(node.child, inner_depth))
    if isinstance(node, (RSequence,)):
        return sum(complexity(p, depth_unbounded) for p in node.parts) or 1
    if isinstance(node, RAlternation):
        return sum(complexity(b, depth_unbounded) for b in node.branches)
    if isinstance(node, RGroup):
        return complexity(node.child, depth_unbounded)
    return 1


MAX_COMPLEXITY = 10_000


# ---------------------------------------------------------------------------
# Simple-pattern rewrites (reference: RegexRewriteUtils JNI + the planner's
# GpuRegExpReplaceMeta literal detection)
# ---------------------------------------------------------------------------

def _as_literal(node: RegexNode) -> Optional[str]:
    """Returns the literal string when the node is a pure char sequence."""
    if isinstance(node, RLiteral):
        return node.ch
    if isinstance(node, RSequence):
        out = []
        for p in node.parts:
            s = _as_literal(p)
            if s is None:
                return None
            out.append(s)
        return "".join(out)
    if isinstance(node, RGroup) and not node.capturing:
        return _as_literal(node.child)
    return None


def simple_rewrite(node: RegexNode) -> Optional[Tuple[str, str]]:
    """('equals'|'prefix'|'suffix'|'contains', literal) when the whole
    pattern is anchors + a literal — device-executable as fixed-string
    kernels (StartsWith/EndsWith/Contains/EqualTo)."""
    seq = node.parts if isinstance(node, RSequence) else [node]
    if not seq:
        return ("contains", "")
    starts = isinstance(seq[0], RAnchor) and seq[0].kind in ("^", "\\A")
    # only \z (absolute end) is device-rewritable: Java '$'/'\Z' also match
    # before a final line terminator, which a fixed EndsWith kernel cannot
    # express — rewriting them would diverge from the CPU oracle
    ends = isinstance(seq[-1], RAnchor) and seq[-1].kind == "\\z"
    if not ends and isinstance(seq[-1], RAnchor) \
            and seq[-1].kind in ("$", "\\Z"):
        return None
    core = seq[1 if starts else 0:(-1 if ends else len(seq))]
    lit = _as_literal(RSequence(list(core)))
    if lit is None:
        return None
    if starts and ends:
        return ("equals", lit)
    if starts:
        return ("prefix", lit)
    if ends:
        return ("suffix", lit)
    return ("contains", lit)


# ---------------------------------------------------------------------------
# Public API
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class Transpiled:
    pattern: str                        # host-dialect (python re) pattern
    rewrite: Optional[Tuple[str, str]]  # simple device rewrite, if any
    num_groups: int
    est_complexity: int


def transpile(java_pattern: str, mode: str = FIND) -> Transpiled:
    """Parses the Java pattern and returns the host-dialect translation;
    raises RegexUnsupported for constructs that cannot run faithfully
    (reference: CudfRegexTranspiler.transpile)."""
    parser = _Parser(java_pattern)
    ast = parser.parse()
    if mode == SPLIT:
        for kind in _collect_anchors(ast):
            if kind in ("^", "$", "\\A", "\\Z", "\\z"):
                raise RegexUnsupported(
                    f"line/string anchor {kind!r} is not supported in "
                    "split mode")
    est = complexity(ast)
    if est > MAX_COMPLEXITY:
        raise RegexUnsupported(
            f"pattern too complex (estimated work {est} > {MAX_COMPLEXITY}; "
            "catastrophic backtracking risk)")
    return Transpiled(_emit(ast), simple_rewrite(ast), parser.group_count,
                      est)


def _collect_anchors(node: RegexNode) -> List[str]:
    out = []
    if isinstance(node, RAnchor):
        out.append(node.kind)
    for f in dataclasses.fields(node):
        v = getattr(node, f.name)
        if isinstance(v, RegexNode):
            out.extend(_collect_anchors(v))
        elif isinstance(v, list):
            for x in v:
                if isinstance(x, RegexNode):
                    out.extend(_collect_anchors(x))
    return out


def transpile_replacement(java_repl: str,
                          num_groups: Optional[int] = None) -> str:
    """Java replacement string ($1, \\$) -> python re (\\1, $)
    (reference: GpuRegExpUtils.backrefConversion).

    Java takes the longest digit run that names an EXISTING group ($10 with
    one group = group 1 then literal '0'); pass ``num_groups`` to replicate
    that; None keeps the full digit run (unknown group count)."""
    out = []
    i = 0
    while i < len(java_repl):
        ch = java_repl[i]
        if ch == "$" and i + 1 < len(java_repl) and java_repl[i + 1].isdigit():
            j = i + 1
            if num_groups is None:
                while j < len(java_repl) and java_repl[j].isdigit():
                    j += 1
            else:
                while (j < len(java_repl) and java_repl[j].isdigit()
                       and int(java_repl[i + 1:j + 1]) <= num_groups):
                    j += 1
                if j == i + 1:
                    raise RegexUnsupported(
                        f"replacement group ${java_repl[i + 1]} out of "
                        f"range (pattern has {num_groups} groups)")
            out.append(f"\\g<{java_repl[i + 1:j]}>")
            i = j
        elif ch == "\\" and i + 1 < len(java_repl):
            # Java: backslash makes the next char literal (incl. digits)
            nxt = java_repl[i + 1]
            if nxt == "$":
                out.append("$")
            elif nxt == "\\":
                out.append("\\\\")
            else:
                out.append(nxt)
            i += 2
        elif ch == "\\":
            raise RegexUnsupported("replacement ends with a bare backslash")
        else:
            out.append("\\\\" if ch == "\\" else ch)
            i += 1
    return "".join(out)
