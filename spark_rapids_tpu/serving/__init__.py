"""Multi-tenant query serving (ROADMAP item 4).

"Millions of users" means many small concurrent queries, not one big
one.  This package is the session-server layer over the engine:

- :mod:`spark_rapids_tpu.serving.server` — ``QueryServer``: admits N
  concurrent queries against the shared device pool + ``TpuSemaphore``
  budgets (admission controller with per-query memory reservations and
  a bounded queue with timeout/backoff, surfaced through the PR 7
  arbiter registry), executes them on a worker pool, and closes the
  PR 5 AutoTuner into an ONLINE loop (accepted conf deltas apply to the
  next admitted query).
- :mod:`spark_rapids_tpu.serving.signature` — normalized structural
  plan signatures + input-file fingerprints, the cache vocabulary.
- :mod:`spark_rapids_tpu.serving.caches` — the two cross-query caches:
  optimized-plan -> physical plan (+ its compiled-executable set, shared
  through the PR 8 stage compiler), and deterministic query/CTE ->
  result batches, both invalidated on input-file change and bounded /
  spillable under pressure.

Reference analogs: Spark's ThriftServer session layer + Sparkle's
memory-partitioning analysis for the admission split (PAPERS.md), and
Flare's compiled-query reuse extended from executables to whole plans.
"""

from spark_rapids_tpu.serving.server import (AdmissionTimeout,  # noqa: F401
                                             QueryServer)
