"""The two cross-query serving caches.

**Plan cache** — normalized-structure -> physical plan.  One entry per
(conf digest, normalized plan structure); literal-promoted queries SHARE
the entry, with one physical-plan variant per literal-value vector (the
compiled-executable set behind those variants is shared anyway: promoted
stages key value-independently in the PR 8 stage compiler, so the second
variant plans but does not compile).  An exact (structure + literals)
repeat skips planning AND compilation entirely.  Variants are LEASED:
one executor at a time may run a cached physical plan (exec nodes carry
per-execution state — CTE caches, shuffle stores); a concurrent
duplicate query simply bypasses the cache and plans fresh, which is
always correct.

**Result cache** — deterministic query/CTE subtree -> result batch,
keyed by (exact plan signature, conf digest) and guarded by the input
file fingerprints.  Bounded in memory; under pressure entries SPILL to
an on-disk arrow tier instead of being lost, and any fingerprint
mismatch (a changed/deleted input file) invalidates.

Both caches publish hit/miss/invalidation counters (the bench payload
reports the plan-cache hit rate) and emit ``planCache`` /
``resultCache`` events so the online tuner and the offline tools see
cache behavior in the same stream as everything else.
"""

from __future__ import annotations

import collections
import os
import tempfile
import threading
import time
from typing import Dict, Optional, Tuple

from spark_rapids_tpu.aux.events import emit
from spark_rapids_tpu.plan.base import Exec


class _PlanVariant:
    __slots__ = ("plan", "fingerprints", "lock", "last_used",
                 "lit_values", "key", "nbytes")

    def __init__(self, plan: Exec, fingerprints, lit_values, key=None):
        self.plan = plan
        self.fingerprints = fingerprints
        self.lit_values = lit_values
        self.key = key          # (conf_digest, norm) — discard needs it
        self.lock = threading.Lock()
        self.last_used = time.monotonic()
        self.nbytes = _estimate_plan_bytes(plan)


def _estimate_plan_bytes(plan: Exec) -> int:
    """Shallow retained-size estimate of a physical plan tree: node
    shells + their attribute dicts/values, NOT the data they reference
    (scan partitions / device caches are shared with the session, not
    retained by the cache).  Sizes the planCache.maxBytes bound."""
    import sys
    total = 0
    try:
        for node in plan.collect_nodes():
            total += sys.getsizeof(node)
            d = getattr(node, "__dict__", None)
            if d:
                total += sys.getsizeof(d)
                for v in d.values():
                    total += sys.getsizeof(v)
    except Exception:   # noqa: BLE001 - sizing guess, never fatal
        return 1024
    return max(1, total)


class PlanLease:
    """Checked-out plan-cache variant; release via context manager."""

    def __init__(self, variant: _PlanVariant, kind: str):
        self._variant = variant
        #: "hit" (exact repeat) | "insert" (fresh plan now cached)
        self.kind = kind

    @property
    def plan(self) -> Exec:
        return self._variant.plan

    def __enter__(self) -> "PlanLease":
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def release(self) -> None:
        v, self._variant = self._variant, None
        if v is not None:
            v.last_used = time.monotonic()
            v.lock.release()


class PlanCache:
    """norm-structure -> {literal vector -> leased physical plan}."""

    def __init__(self, max_plans: int = 64, max_bytes: int = 0):
        self.max_plans = int(max_plans)
        #: estimated-byte budget over retained variants, alongside the
        #: count bound — whichever trips first evicts.  0 = unbounded.
        self.max_bytes = int(max_bytes or 0)
        self._lock = threading.Lock()
        #: (conf_digest, norm) -> {lit_values: _PlanVariant}; LRU over
        #: VARIANTS (the leasable unit)
        self._entries: "collections.OrderedDict[Tuple[str, str], Dict]" = \
            collections.OrderedDict()
        #: estimated bytes across retained variants (gauge)
        self.total_bytes = 0
        self.stats = {"hits": 0, "norm_hits": 0, "misses": 0,
                      "busy_bypass": 0, "inserts": 0, "invalidations": 0,
                      "evictions": 0}

    def _variant_count(self) -> int:
        return sum(len(v) for v in self._entries.values())

    def leased_count(self) -> int:
        """Variants currently checked out to an executor (console
        /server)."""
        with self._lock:
            return sum(1 for e in self._entries.values()
                       for v in e.values() if v.lock.locked())

    def lookup(self, conf_digest: str, sig, fingerprints
               ) -> Optional[PlanLease]:
        """Exact-hit lease, or None (miss / busy / stale / disabled).
        A normalized-structure hit with different literal values counts
        as ``norm_hits`` — the caller plans (cheap) but shares the
        entry's compiled-executable set through literal promotion."""
        if self.max_plans <= 0 or sig is None:
            return None
        key = (conf_digest, sig.norm)
        with self._lock:
            entry = self._entries.get(key)
            if entry is None:
                self.stats["misses"] += 1
                emit("planCache", op="miss", norm=sig.norm[:12])
                return None
            self._entries.move_to_end(key)
            variant = entry.get(sig.lit_values)
            if variant is None:
                self.stats["norm_hits"] += 1
                self.stats["misses"] += 1
                emit("planCache", op="norm_hit", norm=sig.norm[:12],
                     variants=len(entry))
                return None
            if variant.fingerprints != fingerprints:
                # an input file changed under this plan: every variant
                # of the structure scanned the same files — drop them all
                self.stats["invalidations"] += len(entry)
                self.total_bytes -= sum(v.nbytes for v in entry.values())
                del self._entries[key]
                emit("planCache", op="invalidate", norm=sig.norm[:12],
                     variants=len(entry))
                return None
            if not variant.lock.acquire(blocking=False):
                # leased by a concurrent identical query: bypass (exec
                # nodes carry per-execution state; racing one instance
                # from two queries is never worth the risk)
                self.stats["busy_bypass"] += 1
                emit("planCache", op="busy", norm=sig.norm[:12])
                return None
            self.stats["hits"] += 1
            emit("planCache", op="hit", norm=sig.norm[:12])
            return PlanLease(variant, "hit")

    def insert(self, conf_digest: str, sig, fingerprints,
               plan: Exec) -> Optional[PlanLease]:
        """Caches a freshly-planned physical plan and returns it LEASED
        (the caller executes it immediately)."""
        if self.max_plans <= 0 or sig is None:
            return None
        key = (conf_digest, sig.norm)
        variant = _PlanVariant(plan, fingerprints, sig.lit_values, key)
        variant.lock.acquire()
        with self._lock:
            entry = self._entries.setdefault(key, {})
            old = entry.get(sig.lit_values)
            if old is not None:
                self.total_bytes -= old.nbytes
            entry[sig.lit_values] = variant
            self.total_bytes += variant.nbytes
            self._entries.move_to_end(key)
            self.stats["inserts"] += 1
            # evict least-recently-used UNLEASED variants past either
            # bound (variant count OR retained-byte estimate)
            while self._variant_count() > self.max_plans or \
                    (self.max_bytes > 0
                     and self.total_bytes > self.max_bytes):
                evicted = False
                for k in list(self._entries):
                    ent = self._entries[k]
                    for lv, v in list(ent.items()):
                        if v is variant or v.lock.locked():
                            continue
                        del ent[lv]
                        self.total_bytes -= v.nbytes
                        self.stats["evictions"] += 1
                        evicted = True
                        break
                    if not ent and k in self._entries:
                        del self._entries[k]
                    if evicted:
                        break
                if not evicted:
                    break       # everything live is leased: over-budget
        emit("planCache", op="insert", norm=sig.norm[:12])
        return PlanLease(variant, "insert")

    def discard(self, lease: PlanLease) -> None:
        """Drops the leased variant from the cache AND releases the
        lease.  Called when an execution of the variant's plan FAILED:
        exec instances memoize per-execution state (exchange stores,
        join build caches) that a half-run — e.g. a speculative pass
        that died before its overflow check — may have left poisoned,
        so the instance must never be handed to a later exact hit."""
        v = lease._variant
        if v is None:
            return
        with self._lock:
            entry = self._entries.get(v.key)
            if entry is not None and entry.get(v.lit_values) is v:
                del entry[v.lit_values]
                self.total_bytes -= v.nbytes
                if not entry:
                    del self._entries[v.key]
                self.stats["invalidations"] += 1
                emit("planCache", op="discard",
                     norm=v.key[1][:12] if v.key else "")
        lease.release()

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()
            self.total_bytes = 0


class _ResultEntry:
    __slots__ = ("batch", "spill_path", "nbytes", "fingerprints", "pins")

    def __init__(self, batch, nbytes: int, fingerprints, pins=()):
        self.batch = batch            # HostColumnarBatch | None (spilled)
        self.spill_path: Optional[str] = None
        self.nbytes = nbytes
        self.fingerprints = fingerprints
        #: strong refs to the objects the key's signature identifies by
        #: id() (in-memory scan device caches) — keeps a recycled address
        #: from colliding with a live entry (signature.plan_pins)
        self.pins = pins


class ResultCache:
    """Deterministic (exact plan signature, conf) -> result batches,
    spillable under pressure, invalidated on file change."""

    def __init__(self, max_bytes: int = 256 << 20, spill: bool = True,
                 spill_dir: Optional[str] = None):
        self.max_bytes = int(max_bytes)
        self.spill_enabled = bool(spill)
        self._spill_dir = spill_dir
        self._lock = threading.Lock()
        self._entries: "collections.OrderedDict[str, _ResultEntry]" = \
            collections.OrderedDict()
        self.mem_bytes = 0
        self.disk_bytes = 0
        self.stats = {"hits": 0, "misses": 0, "inserts": 0,
                      "invalidations": 0, "spills": 0, "unspills": 0,
                      "evictions": 0}

    def _ensure_spill_dir(self) -> str:
        if self._spill_dir is None:
            self._spill_dir = tempfile.mkdtemp(prefix="srt-result-cache-")
        return self._spill_dir

    # -- arrow IPC spill tier -----------------------------------------------
    def _write_spill(self, key: str, batch) -> str:
        """Serializes one batch to the arrow tier — called OUTSIDE the
        cache lock (the write is the expensive part; peers keep
        hitting).  Uses the shuffle serializer's codec frame with the
        catalog's spill codec (``spark.rapids.memory.spill.codec``), so
        result-cache spill files compress through the same lz4/zlib
        path every other host->disk spill does."""
        from spark_rapids_tpu.memory import catalog as CAT
        from spark_rapids_tpu.shuffle.serializer import serialize_batch
        path = os.path.join(self._ensure_spill_dir(), f"{key}.arrow")
        frame = serialize_batch(batch, CAT.SPILL_CODEC)
        with open(path, "wb") as fh:
            fh.write(frame)
        return path

    def _spill_victims(self, victims) -> None:
        """(key, entry, batch snapshot) list from ``_collect_victims``:
        serialize each outside the lock, then COMMIT (or discard, if the
        entry was dropped/invalidated meanwhile) under it."""
        for key, e, batch in victims:
            try:
                path = self._write_spill(key, batch)
            except OSError:
                continue        # disk trouble: entry simply stays in memory
            committed = False
            with self._lock:
                if self._entries.get(key) is e and e.batch is not None:
                    e.spill_path = path
                    e.batch = None
                    self.mem_bytes -= e.nbytes
                    self.disk_bytes += e.nbytes
                    self.stats["spills"] += 1
                    committed = True
            if committed:
                emit("resultCache", op="spill", bytes=e.nbytes)
            else:
                try:
                    os.remove(path)
                except OSError:
                    pass

    def _load(self, path: str):
        from spark_rapids_tpu.shuffle.serializer import deserialize_batch
        with open(path, "rb") as f:
            return deserialize_batch(f.read())

    def _drop(self, key: str, e: _ResultEntry) -> None:
        if e.batch is not None:
            self.mem_bytes -= e.nbytes
        if e.spill_path:
            self.disk_bytes -= e.nbytes
            try:
                os.remove(e.spill_path)
            except OSError:
                pass
        self._entries.pop(key, None)

    # -- public --------------------------------------------------------------
    def lookup(self, key: Optional[str], fingerprints):
        """Cached HostColumnarBatch or None; a fingerprint mismatch
        deletes the entry (file changed) and misses."""
        if key is None or self.max_bytes <= 0:
            return None
        with self._lock:
            e = self._entries.get(key)
            if e is None:
                self.stats["misses"] += 1
                return None
            if e.fingerprints != fingerprints:
                self._drop(key, e)
                self.stats["invalidations"] += 1
                self.stats["misses"] += 1
                emit("resultCache", op="invalidate", key=key[:12])
                return None
            self._entries.move_to_end(key)
            self.stats["hits"] += 1
            emit("resultCache", op="hit", key=key[:12])
            if e.batch is not None:
                return e.batch
            path = e.spill_path     # snapshot under the lock: a peer's
            # re-admission nulls it after we release
        # disk load outside the lock (IO under a hot lock stalls peers)
        try:
            if path is None:
                raise OSError("spill path gone")
            batch = self._load(path)
        except OSError:
            # raced a concurrent unspill-re-admission (serve its batch)
            # or a drop/rebalance/invalidate that unlinked the file (a
            # lost entry is a MISS) — never a query failure
            with self._lock:
                if self._entries.get(key) is e and e.batch is not None:
                    return e.batch
                self.stats["hits"] -= 1
                self.stats["misses"] += 1
            return None
        drop_path = None
        with self._lock:
            self.stats["unspills"] += 1
            # re-admit a hot entry while the budget has room, or every
            # hit of this key keeps paying the disk read
            if self._entries.get(key) is e and e.batch is None and \
                    self.mem_bytes + e.nbytes <= self.max_bytes:
                e.batch = batch
                self.mem_bytes += e.nbytes
                self.disk_bytes -= e.nbytes
                drop_path, e.spill_path = e.spill_path, None
        if drop_path:
            try:
                os.remove(drop_path)
            except OSError:
                pass
        return batch

    def put(self, key: Optional[str], fingerprints, batch,
            pins=()) -> bool:
        if key is None or self.max_bytes <= 0 or batch is None:
            return False
        nbytes = int(batch.nbytes())
        if nbytes > self.max_bytes:
            return False        # a single oversized result never caches
        with self._lock:
            old = self._entries.get(key)
            if old is not None:
                self._drop(key, old)
            e = _ResultEntry(batch, nbytes, fingerprints, pins)
            self._entries[key] = e
            self.mem_bytes += nbytes
            self.stats["inserts"] += 1
            victims = self._collect_victims()
        emit("resultCache", op="insert", key=key[:12], bytes=nbytes)
        self._spill_victims(victims)
        return True

    def _collect_victims(self):
        """Under memory pressure (caller holds ``_lock``): hard-evict
        what cannot spill, and return the LRU entries TO spill —
        serialization and the disk write happen outside the lock
        (``_spill_victims``), so concurrent lookups keep hitting the
        still-in-memory batches meanwhile."""
        victims = []
        pending = 0         # bytes leaving memory once the spills commit
        for key in list(self._entries):
            if self.mem_bytes - pending <= self.max_bytes:
                break
            e = self._entries[key]
            if e.batch is None:
                continue
            if self.spill_enabled and self.disk_bytes + pending + \
                    e.nbytes <= 4 * self.max_bytes:
                victims.append((key, e, e.batch))
                pending += e.nbytes
            else:
                self._drop(key, e)
                self.stats["evictions"] += 1
        return victims

    def resize(self, max_bytes: int) -> None:
        """Online budget change (``QueryServer.set_conf``): applies
        immediately — shrinking spills/evicts LRU entries down to the
        new bound before returning."""
        with self._lock:
            self.max_bytes = int(max_bytes)
            if self.max_bytes <= 0:
                for key in list(self._entries):
                    self._drop(key, self._entries[key])
                victims = []
            else:
                victims = self._collect_victims()
        self._spill_victims(victims)

    def invalidate_files(self, paths) -> int:
        """Catalog hook: drops every entry whose fingerprints touch any
        of ``paths`` (e.g. an overwrite the server itself performed)."""
        paths = {str(p) for p in paths}
        dropped = 0
        with self._lock:
            for key in list(self._entries):
                e = self._entries[key]
                if any(fp[0] in paths for fp in e.fingerprints):
                    self._drop(key, e)
                    dropped += 1
        if dropped:
            self.stats["invalidations"] += dropped
            emit("resultCache", op="invalidate", files=len(paths),
                 dropped=dropped)
        return dropped

    def clear(self) -> None:
        with self._lock:
            for key in list(self._entries):
                self._drop(key, self._entries[key])
