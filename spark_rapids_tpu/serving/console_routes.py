"""Serving-side console routes: the /server endpoint payload.

The console (aux/console.py) is engine-generic; what the serving layer
exposes — admission queue depth, per-stage latency histogram snapshots,
plan/result-cache hit rates and leased variants — lives here, next to
the structures it reads.  Live QueryServers are discovered through the
``serving.server.live_servers()`` weak registry; everything read is a
lock-protected snapshot (``stats()`` copies, ``live_stats()``,
``LatencyHistogram.snapshot()``), never a structure an executing query
holds a lock on.
"""

from __future__ import annotations

import math
from typing import Dict, List


def histogram_json(snap: Dict) -> Dict:
    """A ``LatencyHistogram.snapshot()`` made JSON-safe: the +Inf bucket
    bound becomes the Prometheus-style string ``"+Inf"``."""
    return {
        "buckets": [["+Inf" if math.isinf(le) else le, n]
                    for le, n in snap["buckets"]],
        "sum": round(snap["sum"], 6),
        "count": snap["count"],
    }


def _hit_rate(stats: Dict) -> float:
    hits = int(stats.get("hits", 0))
    misses = int(stats.get("misses", 0))
    total = hits + misses
    return round(hits / total, 6) if total else 0.0


def server_payload() -> dict:
    """The /server endpoint body: one row per live QueryServer plus the
    process-wide per-stage latency histogram snapshots."""
    from spark_rapids_tpu.serving import server as SRV
    servers: List[dict] = []
    for s in SRV.live_servers():
        st = s.stats()
        st.update(s.live_stats())
        st["plan_cache_hit_rate"] = _hit_rate(st["plan_cache"])
        st["result_cache_hit_rate"] = _hit_rate(st["result_cache"])
        servers.append(st)
    hists = {stage: histogram_json(snap)
             for stage, snap in SRV.latency_histograms().items()}
    return {"servers": servers, "latency_histograms": hists}
