"""QueryServer: concurrent multi-query serving over one engine runtime.

One server owns:

- a **worker pool** (``spark.rapids.serving.maxConcurrentQueries``
  threads) draining a submission queue;
- an **admission controller**: before executing, every query reserves
  device-pool bytes (``queryMemoryReservation``, Sparkle-style static
  partitioning of the shared pool) and waits — with timeout + doubling
  backoff — while the reservations don't fit.  Waits are surfaced
  through the PR 7 arbiter registry (``BLOCKED_ON_ADMISSION`` in
  ``stats()``/``dump()``) and emit ``servingAdmission`` events.  A
  starved pool BLOCKS submissions (then sheds them with
  :class:`AdmissionTimeout`); it never OOMs the engine;
- the two **cross-query caches** (serving/caches.py): an exact repeat
  of a query skips planning and compilation entirely (asserted by test
  via the stage compiler's trace counters), and a deterministic repeat
  skips execution too (result cache), both invalidated when any input
  file changes;
- the **online AutoTuner loop** (``serving.autotune.enabled``): after
  each query the PR 5 rule set evaluates the query's live event ring +
  the resource sampler's ``resourceSample`` feed; accepted deltas (an
  explicit allowlist: pipeline depth, concurrentGpuTasks, batch size)
  apply to the server conf — and the live semaphore — so they take
  effect for the NEXT admitted query, each emitting ``autotuneApplied``.

Per-query conf travels WITH the plan (the round-5 knobs ride exec
instances; the conf digest keys the plan cache), which is what makes
admitting N queries with evolving confs sound.
"""

from __future__ import annotations

import hashlib
import queue
import threading
import time
import weakref
from typing import Callable, Dict, List, Optional, Union

from spark_rapids_tpu import config as C
from spark_rapids_tpu.aux import events as EV
from spark_rapids_tpu.serving.caches import PlanCache, ResultCache
from spark_rapids_tpu.serving.signature import (conf_digest,
                                                plan_fingerprints,
                                                plan_signature)

#: conf keys the online tuner may change between queries; everything
#: else a rule recommends is reported (stats) but never auto-applied
ONLINE_TUNABLE_KEYS = frozenset({
    "spark.rapids.pipeline.depth",
    "spark.rapids.sql.concurrentGpuTasks",
    "spark.rapids.sql.batchSizeBytes",
})


class AdmissionTimeout(TimeoutError):
    """The submission waited past ``serving.queueTimeoutMs`` — the
    bounded queue sheds load instead of stacking it."""


#: serving latency histogram bounds (seconds): log-spaced from the
#: millisecond serving floor (ROADMAP item 2) up past the queue timeout
LATENCY_BUCKETS = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25,
                   0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0, float("inf"))

#: submission stage keys, decomposition order (queue wait -> admission
#: -> cache lookup -> plan -> compile -> execute -> collect)
STAGE_KEYS = ("queue_wait_s", "admit_wait_s", "lookup_s", "plan_s",
              "compile_s", "execute_s", "collect_s")


class LatencyHistogram:
    """One fixed-bucket latency histogram (Prometheus semantics: the
    exposition renders CUMULATIVE ``le`` buckets + ``_sum``/``_count``)."""

    def __init__(self, bounds=LATENCY_BUCKETS):
        self.bounds = tuple(bounds)
        self._counts = [0] * len(self.bounds)
        self._sum = 0.0
        self._count = 0
        self._lock = threading.Lock()

    def observe(self, seconds: float) -> None:
        v = max(0.0, float(seconds))
        with self._lock:
            for i, b in enumerate(self.bounds):
                if v <= b:
                    self._counts[i] += 1
                    break
            self._sum += v
            self._count += 1

    def snapshot(self) -> Dict:
        """Cumulative (le, count) pairs ending at +Inf, plus sum/count."""
        with self._lock:
            counts = list(self._counts)
            total, n = self._sum, self._count
        cum = 0
        buckets = []
        for b, c in zip(self.bounds, counts):
            cum += c
            buckets.append((b, cum))
        return {"buckets": buckets, "sum": total, "count": n}


#: process-wide stage -> histogram registry, rendered by
#: aux.events.render_prometheus (lazy import there; one registry per
#: process regardless of how many QueryServers run)
_HISTOGRAMS: Dict[str, LatencyHistogram] = {}
_HIST_LOCK = threading.Lock()


def observe_latency(stage: str, seconds: float) -> None:
    with _HIST_LOCK:
        h = _HISTOGRAMS.get(stage)
        if h is None:
            h = _HISTOGRAMS[stage] = LatencyHistogram()
    h.observe(seconds)


def latency_histograms() -> Dict[str, Dict]:
    """stage -> histogram snapshot for render_prometheus()."""
    with _HIST_LOCK:
        items = list(_HISTOGRAMS.items())
    return {stage: h.snapshot() for stage, h in items}


#: process-wide registry of running QueryServers (weak: a dropped,
#: never-stopped server must not leak here).  The console's /server
#: endpoint discovers live servers through it.
_SERVERS: "weakref.WeakSet" = weakref.WeakSet()
_SERVERS_LOCK = threading.Lock()


def live_servers() -> List["QueryServer"]:
    with _SERVERS_LOCK:
        return [s for s in _SERVERS if not s._stopped]


class AdmissionController:
    """Per-query memory reservations against the shared device pool.

    Admission succeeds when (a) a worker slot exists (callers are the
    bounded worker pool, so this is structural) and (b) the sum of
    admitted reservations + this query's fits the pool limit.  Waits
    ride a condition variable with doubling-backoff re-checks and are
    registered in the arbiter's serving view."""

    def __init__(self, max_concurrent: int, reserve_bytes: int,
                 timeout_ms: int, backoff_ms: int):
        self.max_concurrent = int(max_concurrent)
        self._reserve_bytes = int(reserve_bytes)
        self.timeout_ms = int(timeout_ms)
        self.backoff_ms = int(backoff_ms)
        self._cond = threading.Condition()
        self._admitted: Dict[int, int] = {}        # query id -> reserved
        self.stats = {"admitted": 0, "queued": 0, "timeouts": 0,
                      "queue_wait_s": 0.0}

    def _pool_limit(self) -> Optional[int]:
        from spark_rapids_tpu.memory.device_manager import get_runtime
        rt = get_runtime()
        return rt.catalog.device_limit if rt is not None else None

    def reservation_for(self, limit: Optional[int]) -> int:
        if self._reserve_bytes > 0:
            return self._reserve_bytes
        if limit is None:
            return 0
        return max(1, limit // max(1, self.max_concurrent))

    def _fits(self, reserve: int, limit: Optional[int]) -> bool:
        if limit is None or reserve <= 0:
            return True
        if len(self._admitted) >= self.max_concurrent:
            return False
        used = sum(self._admitted.values())
        # the FIRST query always admits even when its reservation alone
        # exceeds the pool (the arbiter + spill tier absorb a genuinely
        # oversized working set; admission only orders peers)
        return not self._admitted or used + reserve <= limit

    def admit(self, query_id: int, timeout_ms: Optional[int] = None,
              deadline: Optional[float] = None) -> int:
        """Blocks until admitted; returns the reserved byte count.
        Raises :class:`AdmissionTimeout` past the queue timeout.
        ``deadline`` (monotonic) wins over ``timeout_ms`` — the server
        passes ``submitted + queueTimeoutMs`` so time spent waiting for
        a WORKER counts against the same budget as the admission wait.
        The deadline bounds WAITING only, deliberately: a submission
        that can run the moment a worker picks it up runs even if its
        deadline lapsed in the worker queue — shedding runnable work a
        client is still blocked on would waste the whole wait."""
        from spark_rapids_tpu.memory.arbiter import TaskState, get_arbiter
        arb = get_arbiter()
        limit = self._pool_limit()
        reserve = self.reservation_for(limit)
        if deadline is None:
            deadline = time.monotonic() + \
                (timeout_ms if timeout_ms is not None else self.timeout_ms) \
                / 1000.0
        backoff = max(0.001, self.backoff_ms / 1000.0)
        waited = None
        timed_out = None
        n_admitted = 0
        with self._cond:
            while not self._fits(reserve, limit):
                now = time.monotonic()
                if waited is None:
                    waited = now
                    self.stats["queued"] += 1
                    # the arbiter registration + event emit pay foreign
                    # locks and possibly sink file I/O: drop the
                    # condition around them so queueing one waiter never
                    # taxes every OTHER waiter's wake/notify, then loop
                    # back to re-check _fits (state may have moved)
                    self._cond.release()
                    try:
                        arb.note_serving(query_id,
                                         TaskState.BLOCKED_ON_ADMISSION,
                                         reserve)
                        EV.emit("servingAdmission", op="queued",
                                serve_id=query_id, reserve_bytes=reserve)
                    finally:
                        self._cond.acquire()
                    continue
                if now >= deadline:
                    # collect the facts under the lock, raise outside it
                    self.stats["timeouts"] += 1
                    timed_out = now
                    n_admitted = len(self._admitted)
                    break
                self._cond.wait(min(backoff, deadline - now))
                backoff = min(backoff * 2, 32 * self.backoff_ms / 1000.0)
                limit = self._pool_limit()
            if timed_out is None:
                self._admitted[query_id] = reserve
                wait_s = 0.0 if waited is None \
                    else time.monotonic() - waited
                self.stats["admitted"] += 1
                self.stats["queue_wait_s"] += wait_s
        if timed_out is not None:
            arb.drop_serving(query_id)
            EV.emit("servingAdmission", op="timeout", serve_id=query_id,
                    waited_s=round(timed_out - waited, 4))
            raise AdmissionTimeout(
                f"query {query_id} not admitted within "
                f"{self.timeout_ms}ms (pool limit {limit}, "
                f"reservation {reserve}B, "
                f"{n_admitted} admitted)")
        arb.note_serving(query_id, TaskState.RUNNING, reserve)
        EV.emit("servingAdmission", op="admitted", serve_id=query_id,
                reserve_bytes=reserve, queue_wait_s=round(wait_s, 4))
        return reserve

    def release(self, query_id: int) -> None:
        from spark_rapids_tpu.memory.arbiter import get_arbiter
        with self._cond:
            self._admitted.pop(query_id, None)
            self._cond.notify_all()
        get_arbiter().drop_serving(query_id)
        EV.emit("servingAdmission", op="released", serve_id=query_id)


class Submission:
    """Handle for one submitted query."""

    _UNSET = object()

    def __init__(self, serve_id: int, tag: str):
        self.serve_id = serve_id
        self.tag = tag
        self.submitted = time.monotonic()
        self._done = threading.Event()
        self._batch = Submission._UNSET
        self.error: Optional[BaseException] = None
        #: how this query resolved: "result_cache" | "plan_cache" |
        #: "planned"; plus timing (``latency_s`` = submit-to-finish,
        #: queue wait included — the number a serving SLO is made of)
        self.info: Dict = {}

    def _finish(self, batch=None, error=None) -> None:
        self.info["latency_s"] = round(time.monotonic() - self.submitted, 6)
        self._batch = batch
        self.error = error
        self._done.set()

    def batch(self, timeout: Optional[float] = None):
        """The result HostColumnarBatch (blocks)."""
        if not self._done.wait(timeout):
            raise TimeoutError(f"query {self.serve_id} still running")
        if self.error is not None:
            raise self.error
        return self._batch

    def result(self, timeout: Optional[float] = None) -> List[dict]:
        """Rows as list-of-dicts (DataFrame.collect semantics)."""
        from spark_rapids_tpu.session import rows_from_host_batch
        return rows_from_host_batch(self.batch(timeout))


class QueryServer:
    """See module docstring.  ``queries`` are SQL text (against the
    session's temp views), DataFrames, or callables
    ``session -> DataFrame`` (re-invoked per execution)."""

    _ids = __import__("itertools").count(1)

    def __init__(self, session=None, conf=None):
        from spark_rapids_tpu.config import TpuConf
        from spark_rapids_tpu.session import TpuSession
        if session is None:
            if isinstance(conf, dict):
                conf = TpuConf(conf)
            session = TpuSession(conf)
        self.session = session
        self._conf = session.conf
        self._conf_lock = threading.Lock()
        cf = self._conf
        self.admission = AdmissionController(
            int(cf.get(C.SERVING_MAX_CONCURRENT.key)),
            C.parse_bytes(cf.get(C.SERVING_MEMORY_RESERVATION.key)),
            int(cf.get(C.SERVING_QUEUE_TIMEOUT_MS.key)),
            int(cf.get(C.SERVING_QUEUE_BACKOFF_MS.key)))
        self.plan_cache = PlanCache(
            int(cf.get(C.SERVING_PLAN_CACHE_MAX.key)),
            max_bytes=C.parse_bytes(
                cf.get(C.SERVING_PLAN_CACHE_MAX_BYTES.key)))
        self.result_cache = ResultCache(
            C.parse_bytes(cf.get(C.SERVING_RESULT_CACHE_MAX_BYTES.key)),
            spill=cf.get(C.SERVING_RESULT_CACHE_SPILL.key))
        self.autotune_enabled = cf.get(C.SERVING_AUTOTUNE_ENABLED.key)
        #: applied online deltas: [(key, old, new, reason, query_id)]
        self.autotune_applied: List[tuple] = []
        self._stopped = False
        #: orders submit() against stop() (an accepted submission is
        #: queued BEFORE the shutdown sentinels, so workers always
        #: process it and result() can never block forever) and guards
        #: the ring-sink registration toggle
        self._submit_lock = threading.Lock()
        #: out-of-query event capture (resourceSample feed for the
        #: tuner) — registered as a global sink only WHILE the online
        #: loop is on: every process-wide emit pays each installed sink,
        #: and a ring nobody reads is pure tax
        self._global_ring = EV.RingBufferSink(1024)
        self._ring_registered = False
        self._sync_ring_sink()
        #: (conf snapshot, digest) single-slot memo — see _conf_digest
        self._cdig = None
        self._queue: "queue.Queue" = queue.Queue()
        self._workers: List[threading.Thread] = []
        for i in range(self.admission.max_concurrent):
            t = threading.Thread(target=self._worker_loop,
                                 name=f"tpu-serve-{i}", daemon=True)
            t.start()
            self._workers.append(t)
        with _SERVERS_LOCK:
            _SERVERS.add(self)

    # -- conf ----------------------------------------------------------------
    @property
    def conf(self):
        with self._conf_lock:
            return self._conf

    def set_conf(self, key: str, value) -> "QueryServer":
        """Applies to queries admitted AFTER this call (the running ones
        keep the conf snapshot taken at their admission).  The serving
        layer's own knobs apply to the LIVE structures too — cache
        budgets resize (shrinking evicts immediately), queue timing
        updates — except ``maxConcurrentQueries``, which sizes the
        worker pool at construction."""
        with self._conf_lock:
            self._conf = self._conf.set(key, value)
            cf = self._conf
        if key.startswith("spark.rapids.serving."):
            self._apply_serving_conf(cf)
        return self

    def _conf_digest(self, conf) -> str:
        """conf_digest memoized on the snapshot's identity: TpuConf is
        immutable, the server conf only changes via set_conf, and
        re-canonicalizing the whole registry per submission would tax
        exactly the exact-hit path the caches exist to make cheap."""
        cached = self._cdig
        if cached is not None and cached[0] is conf:
            return cached[1]
        d = conf_digest(conf)
        self._cdig = (conf, d)
        return d

    def _apply_serving_conf(self, cf) -> None:
        self.result_cache.resize(
            C.parse_bytes(cf.get(C.SERVING_RESULT_CACHE_MAX_BYTES.key)))
        self.result_cache.spill_enabled = bool(
            cf.get(C.SERVING_RESULT_CACHE_SPILL.key))
        # plan-cache shrink trims lazily on the next insert (lookups
        # honor 0-disables immediately)
        self.plan_cache.max_plans = int(
            cf.get(C.SERVING_PLAN_CACHE_MAX.key))
        self.plan_cache.max_bytes = C.parse_bytes(
            cf.get(C.SERVING_PLAN_CACHE_MAX_BYTES.key))
        self.admission.timeout_ms = int(
            cf.get(C.SERVING_QUEUE_TIMEOUT_MS.key))
        self.admission.backoff_ms = int(
            cf.get(C.SERVING_QUEUE_BACKOFF_MS.key))
        self.autotune_enabled = cf.get(C.SERVING_AUTOTUNE_ENABLED.key)
        self._sync_ring_sink()

    def _sync_ring_sink(self) -> None:
        # under _submit_lock: concurrent set_conf calls must not
        # double-register, and set_conf racing (or following) stop()
        # must not resurrect the sink on a dead server
        with self._submit_lock:
            want = self.autotune_enabled and not self._stopped
            if want and not self._ring_registered:
                EV.add_global_sink(self._global_ring)
                self._ring_registered = True
            elif not want and self._ring_registered:
                EV.remove_global_sink(self._global_ring)
                self._ring_registered = False

    # -- submission ----------------------------------------------------------
    def submit(self, query: Union[str, object, Callable],
               tag: str = "") -> Submission:
        with self._submit_lock:
            if self._stopped:
                raise RuntimeError("QueryServer is stopped")
            sub = Submission(next(QueryServer._ids), tag or "query")
            self._queue.put((sub, query))
        return sub

    def execute(self, query, tag: str = "",
                timeout: Optional[float] = None) -> List[dict]:
        """Submit + wait: rows of one query."""
        return self.submit(query, tag).result(timeout)

    def stop(self) -> None:
        with self._submit_lock:
            self._stopped = True
            for _ in self._workers:
                self._queue.put(None)
        for t in self._workers:
            t.join(timeout=10.0)
        still_busy = [t for t in self._workers if t.is_alive()]
        self._workers = []
        # belt and suspenders: fail anything still queued (a worker that
        # died without draining) instead of leaving result() hanging
        drained_sentinels = 0
        while True:
            try:
                item = self._queue.get_nowait()
            except queue.Empty:
                break
            if item is None:
                drained_sentinels += 1
            else:
                item[0]._finish(
                    error=RuntimeError("QueryServer stopped"))
        # a worker still running a long query past the join timeout will
        # come back to queue.get(): give each one its sentinel back or
        # it parks (and pins the server) forever
        for _ in range(min(drained_sentinels, len(still_busy))):
            self._queue.put(None)
        self._sync_ring_sink()      # _stopped -> always deregisters
        self.result_cache.clear()
        self.plan_cache.clear()
        with _SERVERS_LOCK:
            _SERVERS.discard(self)

    def stats(self) -> Dict:
        pc = dict(self.plan_cache.stats)
        pc["bytes"] = self.plan_cache.total_bytes
        pc["max_bytes"] = self.plan_cache.max_bytes
        pc["leased"] = self.plan_cache.leased_count()
        return {
            "admission": dict(self.admission.stats),
            "plan_cache": pc,
            "result_cache": dict(self.result_cache.stats),
            "autotune_applied": len(self.autotune_applied),
        }

    def live_stats(self) -> Dict:
        """Point-in-time serving state for the console /server endpoint
        (the cumulative ``stats()`` counters tell rates, not depth)."""
        with self.admission._cond:
            admitted_now = len(self.admission._admitted)
            reserved = sum(self.admission._admitted.values())
        return {
            "queue_depth": self._queue.qsize(),
            "admitted_now": admitted_now,
            "reserved_bytes": reserved,
            "max_concurrent": self.admission.max_concurrent,
            "stopped": self._stopped,
        }

    # -- worker --------------------------------------------------------------
    def _worker_loop(self) -> None:
        while True:
            item = self._queue.get()
            if item is None:
                return
            sub, query = item
            try:
                self._serve(sub, query)
            except BaseException as e:  # noqa: BLE001 - handed to caller
                sub._finish(error=e)

    def _build_df(self, query):
        if isinstance(query, str):
            return self.session.sql(query)
        if callable(query) and not hasattr(query, "_plan"):
            return query(self.session)
        return query

    def _serve(self, sub: Submission, query) -> None:
        t0 = time.monotonic()
        stages = sub.info["stages"] = {k: 0.0 for k in STAGE_KEYS}
        stages["queue_wait_s"] = round(t0 - sub.submitted, 6)
        # result-cache probe BEFORE admission: a cached result needs no
        # device memory reservation, so a hit must not queue behind (or
        # steal a slot from) queries that actually execute
        try:
            probe = self._probe_result_cache(sub, query, stages)
        except BaseException as e:  # noqa: BLE001 - handed to caller
            sub._finish(error=e)
            self._observe_stages(sub)
            return
        if probe.get("cached") is not None:
            sub.info["resolved"] = "result_cache"
            sub._finish(batch=probe["cached"])
            self._observe_stages(sub)
            return
        reserved = self.admission.admit(
            sub.serve_id,
            deadline=sub.submitted + self.admission.timeout_ms / 1000.0)
        try:
            # conf snapshot AT ADMISSION: online deltas accepted while
            # this query was queued apply to it; deltas accepted during
            # its run apply only to later admissions
            conf = self.conf
            sub.info["reserved_bytes"] = reserved
            sub.info["admit_wait_s"] = round(time.monotonic() - t0, 4)
            stages["admit_wait_s"] = sub.info["admit_wait_s"]
            batch = self._execute(sub, query, conf, probe=probe)
            sub._finish(batch=batch)
        except BaseException as e:  # noqa: BLE001 - handed to caller
            sub._finish(error=e)
        finally:
            self.admission.release(sub.serve_id)
            self._observe_stages(sub)

    def _probe_result_cache(self, sub: Submission, query,
                            stages: Dict) -> Dict:
        """Builds the plan, signs it, and probes the result cache under
        the CURRENT conf.  The probe (plan/signature/digest) is handed
        to ``_execute`` so an admitted miss does not re-plan unless the
        online tuner changed the conf while the query waited."""
        t_lk = time.monotonic()
        conf = self.conf
        df = self._build_df(query)
        plan = df._plan
        sig = plan_signature(plan)
        fps = plan_fingerprints(plan)
        cdig = self._conf_digest(conf)
        rkey = None
        if sig is not None:
            rkey = hashlib.sha1(
                (cdig + ":" + sig.exact).encode()).hexdigest()
        cached = self.result_cache.lookup(rkey, fps)
        stages["lookup_s"] = round(time.monotonic() - t_lk, 6)
        return {"cached": cached, "plan": plan, "sig": sig, "fps": fps,
                "cdig": cdig, "rkey": rkey}

    def _observe_stages(self, sub: Submission) -> None:
        """End-of-submission latency decomposition: every stage (and the
        end-to-end latency) observes into the process-wide histograms
        rendered by render_prometheus(), and the per-stage sums ride a
        ``servingAdmission`` op="complete" event."""
        stages = sub.info.get("stages") or {}
        e2e = float(sub.info.get("latency_s", 0.0) or 0.0)
        observe_latency("e2e", e2e)
        for k in STAGE_KEYS:
            observe_latency(k[:-2], float(stages.get(k, 0.0) or 0.0))
        EV.emit("servingAdmission", op="complete", serve_id=sub.serve_id,
                latency_s=round(e2e, 6),
                resolved=str(sub.info.get("resolved", "")),
                error=sub.error is not None,
                **{k: round(float(stages.get(k, 0.0) or 0.0), 6)
                   for k in STAGE_KEYS})

    def _execute(self, sub: Submission, query, conf, probe=None):
        from spark_rapids_tpu.aux.tracing import query_scope
        from spark_rapids_tpu.serving.signature import plan_pins
        from spark_rapids_tpu.session import collect_with_speculation
        stages = sub.info.get("stages")
        t_lk = time.monotonic()
        if probe is not None and probe["cdig"] == self._conf_digest(conf):
            # pre-admission probe still valid: reuse its plan/signature
            # and re-check only the cache (a concurrent peer may have
            # published this result while we waited for admission)
            plan, sig, fps = probe["plan"], probe["sig"], probe["fps"]
            cdig, rkey = probe["cdig"], probe["rkey"]
        else:
            df = self._build_df(query)
            plan = df._plan
            sig = plan_signature(plan)
            fps = plan_fingerprints(plan)
            cdig = self._conf_digest(conf)
            rkey = None
            if sig is not None:
                rkey = hashlib.sha1(
                    (cdig + ":" + sig.exact).encode()).hexdigest()
        cached = self.result_cache.lookup(rkey, fps)
        if stages is not None:
            stages["lookup_s"] = round(
                stages.get("lookup_s", 0.0)
                + (time.monotonic() - t_lk), 6)
        if cached is not None:
            sub.info["resolved"] = "result_cache"
            return cached
        lease_box: Dict = {}

        def prepared_plan():
            from spark_rapids_tpu.aux.metrics import (MetricLevel,
                                                      instrument_plan)
            from spark_rapids_tpu.exec.basic import refresh_cte_epochs
            from spark_rapids_tpu.plan.overrides import TpuOverrides
            if "lease" not in lease_box:
                lease = self.plan_cache.lookup(cdig, sig, fps)
                if lease is not None:
                    # cached physical plan: NO planning, NO compile —
                    # just the per-execution preamble (fresh CTE epoch,
                    # metric reset; instrument_plan is idempotent)
                    sub.info["resolved"] = "plan_cache"
                    refresh_cte_epochs(lease.plan)
                    instrument_plan(lease.plan, MetricLevel.parse(
                        conf.get(C.METRICS_LEVEL.key, "MODERATE")))
                else:
                    sub.info["resolved"] = "planned"
                    executed = TpuOverrides(conf).apply(plan)
                    lease = self.plan_cache.insert(cdig, sig, fps,
                                                   executed)
                    if lease is None:       # cache disabled / unsigned
                        lease_box["plan"] = executed
                lease_box["lease"] = lease
            else:
                # speculation-overflow replay: exec nodes memoize
                # per-execution state (exchange stores, join build
                # caches) that the FAILED speculative pass poisoned
                # with truncated batches — an exact-mode replay must
                # never reuse it.  Re-plan fresh instances (the rare
                # path; the DataFrame action path re-plans per replay
                # for the same reason) and swap the rebuilt plan into
                # the cache so later hits never see the poisoned ones.
                executed = TpuOverrides(conf).apply(plan)
                lease = lease_box["lease"]
                if lease is not None:
                    lease._variant.plan = executed
                else:
                    lease_box["plan"] = executed
            lease = lease_box["lease"]
            out = lease.plan if lease is not None else lease_box["plan"]
            q = EV.active_query()
            if q is not None:
                q.attach_plan(out)
            return out

        def timed_prepared_plan():
            # plan_s accumulates across speculation replays (the rare
            # re-plan path invokes this more than once)
            t = time.monotonic()
            try:
                return prepared_plan()
            finally:
                if stages is not None:
                    stages["plan_s"] = round(
                        stages["plan_s"] + time.monotonic() - t, 6)

        from spark_rapids_tpu.aux import transitions as TR
        from spark_rapids_tpu.exec import stage_compiler as SC
        compile_s0 = float(SC.stats()["compile_s"])
        tr0 = TR.snapshot()
        t_exec = time.monotonic()
        qe = None
        try:
            with query_scope(conf, f"serve:{sub.tag}") as qe:
                batch = collect_with_speculation(conf,
                                                 timed_prepared_plan)
        except BaseException:
            # a FAILED execution may leave the plan's exec instances
            # with poisoned memoized state (a speculative pass that
            # died before its overflow check can have materialized
            # exchange stores from truncated joins) — the variant must
            # never serve a later exact hit.  Discard drops it from the
            # cache and releases the lease
            lease = lease_box.pop("lease", None)
            if lease is not None:
                self.plan_cache.discard(lease)
            raise
        finally:
            lease = lease_box.get("lease")
            if lease is not None:
                lease.release()
        if stages is not None:
            # decompose the execution wall: compile from the stage
            # compiler's measured delta (process-wide — concurrent
            # peers' compiles can bleed in, same caveat as every shared
            # counter), collect as the transition ledger's D2H fetch
            # seconds, execute as the clamped remainder
            exec_wall = max(0.0, time.monotonic() - t_exec)
            compile_s = max(0.0,
                            float(SC.stats()["compile_s"]) - compile_s0)
            collect_s = float(TR.snapshot().delta(tr0).get("d2h_s", 0.0))
            stages["compile_s"] = round(compile_s, 6)
            stages["collect_s"] = round(collect_s, 6)
            stages["execute_s"] = round(
                max(0.0, exec_wall - stages["plan_s"] - compile_s
                    - collect_s), 6)
        self.result_cache.put(rkey, fps, batch, pins=plan_pins(plan))
        if self.autotune_enabled and qe is not None:
            self._autotune_step(qe)
        return batch

    # -- online tuning loop --------------------------------------------------
    def _autotune_step(self, qe) -> None:
        """Between queries: evaluate the rule set over this query's live
        event ring + the sampler's resourceSample feed; apply accepted
        allowlisted deltas to the NEXT admitted query."""
        try:
            recs = self._evaluate_rules(qe)
        except Exception:   # noqa: BLE001 - tuning must never fail a query
            return
        for rec in recs:
            if rec.key not in ONLINE_TUNABLE_KEYS:
                continue
            self._apply_delta(rec, qe.query_id)

    def _evaluate_rules(self, qe) -> List:
        from spark_rapids_tpu.tools.autotune import autotune_query
        from spark_rapids_tpu.tools.reader import (ReadDiagnostics,
                                                   profiles_from_events)
        # the live feed: sampler events (global ring, NO_QUERY) first so
        # the reader buckets them as the run's sample stream, then the
        # query's own ring (spanMetrics/queryEnd included — finish ran)
        samples = [e for e in self._global_ring.events()
                   if e.kind == "resourceSample"]
        events = samples + qe.events()
        profiles, _ = profiles_from_events(events, ReadDiagnostics())
        prof = next((p for p in profiles if p.query_id == qe.query_id),
                    None)
        if prof is None:
            return []
        if not prof.conf:
            prof.conf = dict(qe.conf_snapshot or {})
        return autotune_query(prof)

    def _apply_delta(self, rec, query_id: int) -> None:
        from spark_rapids_tpu.config import TpuConf
        with self._conf_lock:
            current = self._conf.get(rec.key)
            if str(current) == str(rec.recommended):
                return
            try:
                new_conf = self._conf.set(rec.key, str(rec.recommended))
            except Exception:   # noqa: BLE001 - a rec failing validation
                return          # is dropped, never fatal
            self._conf = new_conf
        if rec.key == "spark.rapids.sql.concurrentGpuTasks":
            # the permit budget lives in the RUNTIME semaphore: apply
            # online (grows wake waiters; shrinks drain as tasks finish)
            from spark_rapids_tpu.memory.device_manager import get_runtime
            rt = get_runtime()
            if rt is not None:
                rt.semaphore.resize(int(rec.recommended))
        self.autotune_applied.append(
            (rec.key, current, rec.recommended, rec.reason, query_id))
        EV.emit("autotuneApplied", key=rec.key, old=str(current),
                new=str(rec.recommended), query_id=query_id,
                reason=rec.reason[:160])
