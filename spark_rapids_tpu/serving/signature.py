"""Normalized structural plan signatures — the serving caches' vocabulary.

A signature captures ALL result-affecting state of a CPU (pre-rewrite)
plan tree, split into two parts:

- the **normalized structure**: node classes, their result-affecting
  attributes, and every expression rendered with literal VALUES scrubbed
  to typed slots (the same normalization the PR 8 literal promotion and
  the PR 12 audit ``norm_sig`` apply) — so ``d_year = 1998`` and
  ``= 1999`` share one structure;
- the **literal values**, in scrub order — the exact-identity remainder.

Two queries with equal structures share one plan-cache ENTRY; equal
structures AND equal literal values are the same query (full hit: the
cached physical plan — and its compiled-executable set — re-executes
with zero planning and zero traces).

DEFAULT-DENY: a node whose ``__dict__`` carries state the canonicalizer
does not understand (callables — python UDFs, pandas fns — or foreign
objects) makes the whole plan unsigned (``None``), which simply disables
caching for it; being uncacheable is always correct, being wrongly
merged never is.  This mirrors ``plan/overrides._reuse_node_key``'s
posture, widened from exchanges to whole plans.

File inputs are NOT part of the structure: :func:`plan_fingerprints`
collects ``(path, mtime, size)`` per scanned file, and the caches compare
fingerprints at lookup — a changed file invalidates instead of silently
serving stale results.
"""

from __future__ import annotations

import dataclasses
import hashlib
from typing import List, Optional, Tuple

from spark_rapids_tpu import types as T
from spark_rapids_tpu.expressions.base import Expression, Literal
from spark_rapids_tpu.plan.base import Exec


class _Unsigned(Exception):
    """Raised when a plan carries state the signature cannot capture."""


class _SlotLiteral(Literal):
    """Scrubbed literal: renders a typed slot so the structure is
    value-independent; the value itself moves to the signature's
    ``lit_values``."""

    def __init__(self, slot: int, dtype):
        super().__init__(None, dtype)
        self.slot = slot

    def sql(self):
        return f"$sig{self.slot}:{self._dtype}"


def _scrub_expr(e: Expression, lits: List[str]) -> Expression:
    """Copy of ``e`` with every literal swapped for a slot; values append
    to ``lits`` in walk order."""
    if isinstance(e, Literal) and not isinstance(e, _SlotLiteral):
        lits.append(f"{e.value!r}:{e.data_type}")
        return _SlotLiteral(len(lits) - 1, e.data_type)
    if not e.children:
        return e
    return e.with_children([_scrub_expr(c, lits) for c in e.children])


#: node attributes that never affect results (or are captured through
#: the child structure / identity keys instead)
_IGNORED_ATTRS = frozenset({
    "children", "shuffle_env", "origin", "metrics", "predicate_pushed",
})


def _canon(v, lits: List[str]):
    """Canonical hashable form of one node attribute; raises
    :class:`_Unsigned` for anything it cannot prove result-neutral."""
    if v is None or isinstance(v, (bool, int, float, str, bytes)):
        return v
    if isinstance(v, Expression):
        return ("E", _scrub_expr(v, lits).sql())
    if isinstance(v, T.StructType):
        return ("T", str(v))
    if isinstance(v, T.DataType):
        return ("t", str(v))
    if isinstance(v, (list, tuple)):
        return tuple(_canon(x, lits) for x in v)
    if isinstance(v, frozenset):
        return ("fs",) + tuple(sorted(repr(_canon(x, lits)) for x in v))
    if isinstance(v, dict):
        return ("D",) + tuple(sorted(
            (str(k), _canon(x, lits)) for k, x in v.items()))
    # sort specs / window specs / partitionings: structured holders whose
    # result-affecting state is (class name + their public attributes)
    d = getattr(v, "__dict__", None)
    if d is not None and not callable(v):
        items = []
        for k in sorted(d):
            if k.startswith("_") or k in _IGNORED_ATTRS:
                continue
            items.append((k, _canon(d[k], lits)))
        # specs hide state behind properties too (SortSpec.ascending is
        # a plain attr; effective_nulls_first is derived) — the public
        # attrs above cover the constructor inputs
        return ("O", type(v).__name__, tuple(items))
    raise _Unsigned(f"{type(v).__name__} attribute is not signable")


def _node_signature(node: Exec, lits: List[str]) -> Tuple:
    from spark_rapids_tpu.exec.basic import CpuInMemoryScanExec
    from spark_rapids_tpu.io.multifile import MultiFileScanBase
    if isinstance(node, CpuInMemoryScanExec):
        # the device-column cache is shared by every plan over one source
        # DataFrame and distinct across sources: identity IS the data
        return ("mem", id(node._dev_cache), tuple(node.col_indices or ()),
                str(node._schema))
    if isinstance(node, MultiFileScanBase):
        pred = getattr(node, "predicate", None)
        return ("file", type(node).__name__,
                tuple(str(p) for p in node.paths),
                tuple(node.columns or ()) if hasattr(node, "columns")
                else (),
                None if pred is None else _scrub_expr(pred, lits).sql(),
                node._scan_cache_extra())
    items = []
    for k in sorted(node.__dict__):
        if k.startswith("_") or k in _IGNORED_ATTRS:
            continue
        items.append((k, _canon(node.__dict__[k], lits)))
    return (type(node).__name__, tuple(items))


@dataclasses.dataclass(frozen=True)
class PlanSignature:
    """(normalized structure digest, literal values).  ``norm`` is the
    sha1 of the full structural tuple; ``lit_values`` the scrubbed
    literal reprs in walk order."""
    norm: str
    lit_values: Tuple[str, ...]

    @property
    def exact(self) -> str:
        h = hashlib.sha1(self.norm.encode())
        for v in self.lit_values:
            h.update(b"\x00")
            h.update(v.encode())
        return h.hexdigest()


def plan_signature(plan: Exec) -> Optional[PlanSignature]:
    """Signature of a CPU plan tree, or ``None`` when any node carries
    unsignable state (python UDFs, foreign objects) — such plans simply
    bypass the caches."""
    lits: List[str] = []

    def walk(node: Exec) -> Tuple:
        return (_node_signature(node, lits),
                tuple(walk(c) for c in node.children))

    try:
        struct = walk(plan)
    except _Unsigned:
        return None
    norm = hashlib.sha1(repr(struct).encode()).hexdigest()
    return PlanSignature(norm, tuple(lits))


def plan_pins(plan: Exec) -> Tuple:
    """The objects whose IDENTITY the signature keys on (in-memory scan
    device caches): a result-cache entry must hold strong references to
    them, or a freed dict's recycled address could collide with a new
    table's and serve stale rows.  (The plan cache self-pins: its
    entries retain the physical plan, which references the scans.)"""
    from spark_rapids_tpu.exec.basic import CpuInMemoryScanExec
    return tuple(n._dev_cache for n in plan.collect_nodes()
                 if isinstance(n, CpuInMemoryScanExec))


def plan_fingerprints(plan: Exec) -> Tuple[Tuple[str, float, int], ...]:
    """(path, mtime, size) for every file any scan in ``plan`` reads —
    the caches' invalidation evidence.  Missing files fingerprint as
    (path, 0, -1) so a deleted input invalidates too."""
    import os
    from spark_rapids_tpu.io.multifile import MultiFileScanBase
    out = []
    for node in plan.collect_nodes():
        if isinstance(node, MultiFileScanBase):
            for p in node.paths:
                try:
                    st = os.stat(p)
                    out.append((str(p), st.st_mtime, st.st_size))
                except OSError:
                    out.append((str(p), 0.0, -1))
    return tuple(sorted(set(out)))


def conf_digest(conf) -> str:
    """Digest of the plan-affecting conf: the non-default entries minus
    the serving layer's own knobs and the event-log destination (neither
    changes what a plan computes).  Part of every plan-cache key — an
    online autotune delta (pipeline depth, batch size) legitimately
    changes the plans the overrides produce, so it must re-plan, never
    serve a stale shape.

    Values canonicalize through each entry's registered converter:
    ``TpuConf.set`` stores PARSED values while untouched defaults stay
    raw strings ('1g' vs 1073741824), and a digest that saw those as
    different would spuriously re-plan after every unrelated set_conf."""
    from spark_rapids_tpu import config as C

    def canon(entry, v):
        try:
            return repr(entry.converter(v) if isinstance(v, str) else v)
        except Exception:   # noqa: BLE001 - unparseable -> raw identity
            return repr(v)

    items = []
    for key, entry in C.registry().items():
        if key.startswith(("spark.rapids.serving.",
                           "spark.rapids.sql.eventLog.")):
            continue
        try:
            v = conf.get(key)
        except Exception:   # noqa: BLE001 - a digest must never fail
            continue
        cv = canon(entry, v)
        if cv != canon(entry, entry.default):
            items.append((key, cv))
    items.sort()
    return hashlib.sha1(repr(items).encode()).hexdigest()
