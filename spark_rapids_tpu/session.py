"""User-facing session + DataFrame API.

Plays the combined role of SparkSession and the plugin lifecycle (reference:
SQLPlugin -> RapidsDriverPlugin/RapidsExecutorPlugin, Plugin.scala:426/496):
constructing a session initializes the device runtime (device manager, buffer
catalog, semaphore) and installs the plan-rewrite rule; every action re-reads
the conf and applies TpuOverrides to the CPU plan (reference re-reads SQLConf
per query, GpuOverrides.scala:4564).
"""

from __future__ import annotations

import itertools
from typing import Dict, List, Optional, Sequence, Union

from spark_rapids_tpu import types as T
from spark_rapids_tpu import config as C
from spark_rapids_tpu.config import TpuConf
from spark_rapids_tpu.columnar.batch import (HostColumnarBatch,
                                             batch_from_arrow,
                                             batch_from_pydict)
from spark_rapids_tpu.expressions.base import (Alias, AttributeReference,
                                               Expression, Literal,
                                               bind_references, col, lit)
from spark_rapids_tpu.plan.base import Exec
from spark_rapids_tpu.plan.overrides import TpuOverrides


class TpuSession:
    _active: Optional["TpuSession"] = None

    def __init__(self, conf: Optional[Union[TpuConf, Dict]] = None,
                 init_device: bool = True):
        if isinstance(conf, dict):
            conf = TpuConf(conf)
        self.conf = conf or C.default_conf()
        if init_device and self.conf.is_sql_enabled:
            from spark_rapids_tpu.memory.device_manager import initialize
            self.runtime = initialize(self.conf)
        else:
            self.runtime = None
        from spark_rapids_tpu.shuffle.env import init_shuffle_env
        self.shuffle_env = init_shuffle_env(self.conf)
        # chaos layer: arm/disarm fault points from spark.rapids.chaos.*
        # at session construction (overrides.apply re-syncs per action)
        from spark_rapids_tpu.aux.faults import arm_from_conf
        arm_from_conf(self.conf)
        # live resource sampler (spark.rapids.sample.*): start/stop the
        # process singleton to match this session's conf
        from spark_rapids_tpu.aux.sampler import sync_from_conf
        sync_from_conf(self.conf)
        # hung-query watchdog (spark.rapids.watchdog.*): same singleton
        # lifecycle — dumps + escalates tasks that stop making progress
        from spark_rapids_tpu.memory.arbiter import sync_watchdog_from_conf
        sync_watchdog_from_conf(self.conf)
        # runtime lock-order validator (spark.rapids.debug.lockOrder)
        from spark_rapids_tpu.aux.lockorder import sync_from_conf \
            as sync_lockorder
        sync_lockorder(self.conf)
        # host-transition ledger (spark.rapids.sql.transitions.*): arm
        # the instrumented sync/transfer gateway
        from spark_rapids_tpu.aux.transitions import sync_from_conf \
            as sync_transitions
        sync_transitions(self.conf)
        # device mesh (spark.rapids.mesh.*): validate + activate from the
        # conf, emitting a meshTopology event; a bad shape fails HERE,
        # not at the first collective
        from spark_rapids_tpu.parallel.mesh import sync_from_conf \
            as sync_mesh
        sync_mesh(self.conf)
        # live engine console (spark.rapids.console.*): the HTTP
        # metrics/status endpoint, same process-singleton lifecycle
        from spark_rapids_tpu.aux.console import sync_from_conf \
            as sync_console
        sync_console(self.conf)
        #: temp views for the SQL front-end (name -> DataFrame)
        self._views: Dict[str, "DataFrame"] = {}
        #: row-based Hive UDF passthrough (name -> (fn, return_type));
        #: reference: rowBasedHiveUDFs.scala wraps metastore-registered
        #: UDFs for row-at-a-time CPU evaluation
        self._hive_udfs: Dict[str, tuple] = {}
        TpuSession._active = self

    # -- conf ---------------------------------------------------------------
    def set_conf(self, key: str, value) -> "TpuSession":
        """Sets one conf key.  Registered keys validate here (converter +
        checker run in the TpuConf rebuild — a bad
        ``spark.rapids.shuffle.fetch.timeoutMs`` or malformed chaos spec
        raises immediately, not mid-query); ``spark.rapids.chaos.*`` keys
        additionally re-arm the fault registry so chaos takes effect for
        the very next action."""
        self.conf = self.conf.set(key, value)
        if key.startswith("spark.rapids.chaos."):
            from spark_rapids_tpu.aux.faults import arm_from_conf
            arm_from_conf(self.conf)
        elif key.startswith(("spark.rapids.shuffle.fetch.",
                             "spark.rapids.shuffle.transport.")):
            self.shuffle_env.update_fetch_retry(self.conf)
        elif key.startswith(("spark.rapids.sample.",
                             "spark.rapids.sql.eventLog.")):
            # the sampler singleton tracks both its own knobs and the
            # event-log destination it mirrors samples into
            from spark_rapids_tpu.aux.sampler import sync_from_conf
            sync_from_conf(self.conf)
        elif key.startswith("spark.rapids.watchdog."):
            from spark_rapids_tpu.memory.arbiter import \
                sync_watchdog_from_conf
            sync_watchdog_from_conf(self.conf)
        elif key.startswith("spark.rapids.debug."):
            from spark_rapids_tpu.aux.lockorder import sync_from_conf \
                as sync_lockorder
            sync_lockorder(self.conf)
        elif key.startswith("spark.rapids.sql.transitions."):
            from spark_rapids_tpu.aux.transitions import sync_from_conf \
                as sync_transitions
            sync_transitions(self.conf)
        elif key.startswith("spark.rapids.mesh."):
            from spark_rapids_tpu.parallel.mesh import sync_from_conf \
                as sync_mesh
            sync_mesh(self.conf, allow_disable=True)
        elif key.startswith("spark.rapids.console."):
            from spark_rapids_tpu.aux.console import sync_from_conf \
                as sync_console
            sync_console(self.conf)
        return self

    # -- SQL ----------------------------------------------------------------
    def sql(self, text: str) -> "DataFrame":
        """Executes SQL text against registered temp views (the reference
        accepts arbitrary Spark SQL via Catalyst; here sql/ carries the
        parser + analyzer for the TPC-DS-class dialect)."""
        from spark_rapids_tpu.sql.analyzer import Analyzer
        from spark_rapids_tpu.sql.parser import parse
        return Analyzer(self).plan(parse(text))

    def create_or_replace_temp_view(self, name: str, df: "DataFrame") -> None:
        self._views[name.lower()] = df

    def register_hive_udf(self, name: str, fn, return_type) -> None:
        """Registers a row-based UDF callable from SQL by name — the
        Hive-UDF passthrough analog (reference: rowBasedHiveUDFs.scala:
        GpuRowBasedHiveSimpleUDF wraps the jar's function for CPU
        row-at-a-time eval; here the python callable plays that role and
        runs on the host tier with honest fallback tagging)."""
        self._hive_udfs[name.lower()] = (fn, return_type)

    createOrReplaceTempView = create_or_replace_temp_view

    def table(self, name: str) -> "DataFrame":
        df = self.catalog_lookup(name)
        if df is None:
            raise ValueError(f"table or view not found: {name}")
        return df

    def catalog_lookup(self, name: str) -> Optional["DataFrame"]:
        return self._views.get(name.lower())

    # -- dataframe constructors --------------------------------------------
    def create_dataframe(self, data, schema: Optional[T.StructType] = None,
                         num_partitions: int = 1) -> "DataFrame":
        import pyarrow as pa
        from spark_rapids_tpu.exec.basic import CpuInMemoryScanExec
        if isinstance(data, dict):
            hb = batch_from_pydict(data, schema)
        elif isinstance(data, (pa.Table, pa.RecordBatch)):
            hb = batch_from_arrow(data)
        elif isinstance(data, HostColumnarBatch):
            hb = data
        else:
            try:
                import pandas as pd
                if isinstance(data, pd.DataFrame):
                    hb = batch_from_arrow(pa.Table.from_pandas(data))
                else:
                    raise TypeError
            except TypeError:
                raise TypeError(f"cannot create DataFrame from {type(data)}")
        n = hb.row_count
        per = -(-n // num_partitions) if n else 1
        parts = [[hb.slice(i * per, min(per, n - i * per))]
                 for i in range(num_partitions) if i * per < n] or [[hb]]
        return DataFrame(CpuInMemoryScanExec(parts, hb.schema), self)

    def range(self, start: int, end: Optional[int] = None, step: int = 1,
              num_partitions: int = 1) -> "DataFrame":
        from spark_rapids_tpu.exec.basic import CpuRangeExec
        if end is None:
            start, end = 0, start
        return DataFrame(CpuRangeExec(start, end, step, num_partitions), self)

    class _Reader:
        """``session.read.option(...).csv(path)`` (DataFrameReader analog).

        Reader strategy + thread count come from the session conf
        (reference: RapidsConf READER_TYPE / MULTITHREAD_READ_NUM_THREADS)."""

        def __init__(self, session):
            self._s = session
            self._schema = None
            self._options = {}

        def schema(self, s) -> "TpuSession._Reader":
            self._schema = s
            return self

        def option(self, key, value) -> "TpuSession._Reader":
            self._options[key] = value
            return self

        def _common(self, type_entry):
            conf = self._s.conf
            return dict(
                reader_type=conf.get(type_entry.key),
                batch_rows=conf.get(C.MAX_READER_BATCH_SIZE_ROWS.key),
                num_threads=conf.get(C.MULTITHREADED_READ_NUM_THREADS.key))

        def parquet(self, *paths, columns=None) -> "DataFrame":
            from spark_rapids_tpu.io.parquet import CpuParquetScanExec
            return DataFrame(
                CpuParquetScanExec(list(paths), columns,
                                   **self._common(C.READER_TYPE)), self._s)

        def csv(self, *paths, columns=None) -> "DataFrame":
            from spark_rapids_tpu.io.text import CpuCsvScanExec
            opts = {k: v for k, v in self._options.items()
                    if k in ("header", "sep", "quote", "escape", "comment",
                             "null_value")}
            return DataFrame(CpuCsvScanExec(
                list(paths), user_schema=self._schema, columns=columns,
                **opts, **self._common(C.CSV_READER_TYPE)), self._s)

        def json(self, *paths, columns=None) -> "DataFrame":
            from spark_rapids_tpu.io.text import CpuJsonScanExec
            return DataFrame(CpuJsonScanExec(
                list(paths), user_schema=self._schema, columns=columns,
                **self._common(C.JSON_READER_TYPE)), self._s)

        def orc(self, *paths, columns=None) -> "DataFrame":
            from spark_rapids_tpu.io.orc import CpuOrcScanExec
            return DataFrame(
                CpuOrcScanExec(list(paths), columns=columns,
                               **self._common(C.ORC_READER_TYPE)), self._s)

        def text(self, *paths) -> "DataFrame":
            from spark_rapids_tpu.io.text import CpuTextScanExec
            return DataFrame(
                CpuTextScanExec(list(paths),
                                **self._common(C.READER_TYPE)), self._s)

        def hive_text(self, *paths, schema=None, serde=None,
                      columns=None) -> "DataFrame":
            """Hive text table (LazySimpleSerDe subset; reference:
            GpuHiveTableScanExec).  ``schema`` is required — the metastore
            provides it in Spark; ``serde`` = {field.delim,
            serialization.null.format, escape.delim}."""
            from spark_rapids_tpu.hive.table import CpuHiveTextScanExec
            sch = schema or self._schema
            if sch is None:
                raise ValueError("hive_text requires a schema (the "
                                 "metastore's role)")
            return DataFrame(
                CpuHiveTextScanExec(list(paths), sch, serde=serde,
                                    columns=columns,
                                    **self._common(C.READER_TYPE)),
                self._s)

        def avro(self, *paths, columns=None) -> "DataFrame":
            from spark_rapids_tpu.io.avro import CpuAvroScanExec
            return DataFrame(
                CpuAvroScanExec(list(paths), columns=columns,
                                **self._common(C.READER_TYPE)), self._s)

    @property
    def read(self) -> "_Reader":
        return TpuSession._Reader(self)

    def stop(self):
        from spark_rapids_tpu.aux.console import stop_console
        stop_console()
        from spark_rapids_tpu.aux.sampler import stop_sampler
        stop_sampler()
        from spark_rapids_tpu.memory.arbiter import stop_watchdog
        stop_watchdog()
        from spark_rapids_tpu.memory.device_manager import shutdown
        shutdown()
        if self.shuffle_env is not None:
            self.shuffle_env.shutdown()
        if TpuSession._active is self:
            TpuSession._active = None


def _to_expr(e) -> Expression:
    if isinstance(e, Expression):
        return e
    if isinstance(e, str):
        return col(e)
    return lit(e)


def rows_from_host_batch(batch) -> List[dict]:
    """List-of-dict rows from a HostColumnarBatch — THE collect row
    shape, shared by ``DataFrame.collect`` and the serving layer's
    ``Submission.result`` so served rows can never drift from
    DataFrame rows."""
    d = batch.to_pydict()
    names = list(d.keys())
    return [dict(zip(names, row)) for row in zip(*d.values())] \
        if names else []


def collect_with_speculation(conf, plan_factory) -> HostColumnarBatch:
    """THE speculative-sizing collect discipline, shared by DataFrame
    actions and the serving layer: run under a speculation scope, check
    every overflow flag with one sync, and replay the whole action in
    exact mode if any fired.  ``plan_factory()`` returns the prepared
    physical plan — called again for the replay so the factory can
    re-arm per-execution state (CTE epochs) or re-plan."""
    from spark_rapids_tpu import config as C
    from spark_rapids_tpu.ops.speculation import (SpeculationOverflow,
                                                  no_speculation,
                                                  speculation_scope)
    if not conf.get(C.SPECULATIVE_SIZING_ENABLED.key):
        with no_speculation():
            return plan_factory().collect_host()
    try:
        with speculation_scope() as ctx:
            out = plan_factory().collect_host()
            if ctx is not None:
                ctx.check()   # one sync over every overflow flag
            return out
    except SpeculationOverflow:
        # a speculative output bucket was too small somewhere: replay
        # the whole action with exact (sync-per-decision) sizing
        with no_speculation():
            return plan_factory().collect_host()


class DataFrame:
    """Lazy plan builder over CPU physical execs; actions run the rewrite."""

    def __init__(self, plan: Exec, session: TpuSession):
        self._plan = plan
        self._session = session

    @property
    def schema(self) -> T.StructType:
        return self._plan.schema

    @property
    def columns(self) -> List[str]:
        return self._plan.schema.names

    # -- transformations ----------------------------------------------------
    def select(self, *exprs) -> "DataFrame":
        from spark_rapids_tpu.exec.basic import CpuProjectExec
        bound = [bind_references(_to_expr(e), self.schema) for e in exprs]
        plan, bound = self._plan_windows(bound)
        plan, bound = self._plan_pandas_udfs(plan, bound)
        return DataFrame(CpuProjectExec(bound, plan), self._session)

    def _plan_pandas_udfs(self, plan, bound_exprs):
        """Extracts PandasUDFCalls from a projection into one
        CpuArrowEvalPythonExec appending their result columns, then
        rewrites the projection to reference them (reference:
        GpuArrowEvalPythonExec extraction of PythonUDF)."""
        from spark_rapids_tpu.exec.python_execs import CpuArrowEvalPythonExec
        from spark_rapids_tpu.expressions.base import BoundReference
        from spark_rapids_tpu.expressions.python_udf import PandasUDFCall
        calls = []
        for e in bound_exprs:
            calls.extend(e.collect(lambda x: isinstance(x, PandasUDFCall)))
        if not calls:
            return plan, bound_exprs
        base = len(plan.schema.fields)
        udfs = []
        replacement = {}
        for i, c in enumerate(calls):
            udfs.append((f"__pudf{base + i}", c.fn, list(c.children),
                         c.data_type))
            replacement[id(c)] = BoundReference(base + i, c.data_type, True)
        plan = CpuArrowEvalPythonExec(udfs, plan)

        def rewrite(e):
            if id(e) in replacement:
                return replacement[id(e)]
            if not e.children:
                return e
            return e.with_children([rewrite(ch) for ch in e.children])

        return plan, [rewrite(e) for e in bound_exprs]

    def _plan_windows(self, bound_exprs):
        """Extracts WindowExpressions from a projection: one CpuWindowExec
        per (partition, order) spec group appending columns, then rewrites
        the projection to reference them (Spark's ExtractWindowExpressions
        + the reference's GpuWindowExecMeta grouping)."""
        from spark_rapids_tpu.exec.exchange import CpuShuffleExchangeExec
        from spark_rapids_tpu.exec.window import CpuWindowExec
        from spark_rapids_tpu.expressions.base import BoundReference
        from spark_rapids_tpu.expressions.window_exprs import WindowExpression
        from spark_rapids_tpu.plan.partitioning import (HashPartitioning,
                                                        SinglePartitioning)
        wexprs = []
        for e in bound_exprs:
            wexprs.extend(e.collect(
                lambda x: isinstance(x, WindowExpression)))
        if not wexprs:
            return self._plan, bound_exprs
        groups = {}
        for w in wexprs:
            groups.setdefault(w.spec.group_key(), []).append(w)
        plan = self._plan
        replacement = {}
        for key, ws in groups.items():
            spec = ws[0].spec
            if plan.num_partitions > 1:
                if spec.partition_exprs:
                    part = HashPartitioning(spec.partition_exprs,
                                            plan.num_partitions)
                else:
                    part = SinglePartitioning()
                plan = CpuShuffleExchangeExec(
                    part, plan, shuffle_env=self._session.shuffle_env)
            base = len(plan.schema.fields)
            cols = [(f"_w{base + i}", w) for i, w in enumerate(ws)]
            plan = CpuWindowExec(cols, plan)
            for i, w in enumerate(ws):
                f = plan.schema.fields[base + i]
                replacement[id(w)] = BoundReference(base + i, f.data_type,
                                                    f.nullable)

        def rewrite(e):
            # top-down identity rewrite (transform_up copies nodes before
            # visiting, which would defeat the id() lookup)
            if id(e) in replacement:
                return replacement[id(e)]
            if not e.children:
                return e
            return e.with_children([rewrite(c) for c in e.children])

        return plan, [rewrite(e) for e in bound_exprs]

    @staticmethod
    def _no_windows(expr, where: str):
        from spark_rapids_tpu.expressions.window_exprs import WindowExpression
        if expr.collect(lambda x: isinstance(x, WindowExpression)):
            raise ValueError(
                f"window expressions are not allowed in {where}; compute "
                "them in a select()/with_column() first")
        return expr

    def filter(self, condition) -> "DataFrame":
        from spark_rapids_tpu.exec.basic import CpuFilterExec
        cond = bind_references(_to_expr(condition), self.schema)
        self._no_windows(cond, "filter()")
        return DataFrame(CpuFilterExec(cond, self._plan), self._session)

    where = filter

    def with_column(self, name: str, expr) -> "DataFrame":
        from spark_rapids_tpu.exec.basic import CpuProjectExec
        exprs = []
        replaced = False
        for f in self.schema.fields:
            if f.name == name:
                exprs.append(Alias(_to_expr(expr), name))
                replaced = True
            else:
                exprs.append(col(f.name))
        if not replaced:
            exprs.append(Alias(_to_expr(expr), name))
        bound = [bind_references(e, self.schema) for e in exprs]
        plan, bound = self._plan_windows(bound)
        return DataFrame(CpuProjectExec(bound, plan), self._session)

    def drop(self, *cols) -> "DataFrame":
        names = {str(c) for c in cols}
        keep = [col(f.name) for f in self.schema.fields
                if f.name not in names]
        if len(keep) == len(self.schema.fields):
            return self
        return self.select(*keep)

    def with_column_renamed(self, old: str, new: str) -> "DataFrame":
        if old not in self.columns:
            return self
        return self.select(*[
            Alias(col(f.name), new if f.name == old else f.name)
            for f in self.schema.fields])

    @property
    def na(self) -> "DataFrameNaFunctions":
        return DataFrameNaFunctions(self)

    def intersect(self, other: "DataFrame") -> "DataFrame":
        """Distinct rows present in both (Spark INTERSECT).  NOTE: columns
        match BY NAME here (engine restriction), not positionally as in
        Spark SQL set operations.  The right side needs no distinct: a
        left-semi join ignores duplicate matches."""
        on = list(self.columns)
        return self.distinct().join(other, on=on,
                                    how="left_semi", null_safe=True)

    def except_distinct(self, other: "DataFrame") -> "DataFrame":
        """Distinct rows of self absent from other (Spark EXCEPT
        [DISTINCT]; there is intentionally no exceptAll alias — multiset
        semantics are not implemented).  Columns match BY NAME."""
        on = list(self.columns)
        return self.distinct().join(other, on=on, how="left_anti",
                                    null_safe=True)

    # back-compat for the earlier name
    except_all_distinct = except_distinct

    def limit(self, n: int) -> "DataFrame":
        from spark_rapids_tpu.exec.basic import (CpuGlobalLimitExec,
                                                 CpuLimitExec)
        from spark_rapids_tpu.exec.exchange import CpuShuffleExchangeExec
        from spark_rapids_tpu.exec.expand import CpuTakeOrderedAndProjectExec
        from spark_rapids_tpu.exec.sort import CpuSortExec
        from spark_rapids_tpu.plan.partitioning import RangePartitioning
        plan = self._plan
        if isinstance(plan, CpuSortExec) and plan.global_sort:
            # ORDER BY + LIMIT collapses to TakeOrderedAndProject: local
            # top-K replaces the range-partition exchange entirely
            # (reference: the TakeOrderedAndProjectExec rule in GpuOverrides)
            child = plan.children[0]
            if isinstance(child, CpuShuffleExchangeExec) and \
                    isinstance(child.partitioning, RangePartitioning):
                child = child.children[0]
            return DataFrame(
                CpuTakeOrderedAndProjectExec(n, plan.specs, child),
                self._session)
        plan = CpuLimitExec(n, plan)  # local limit per partition
        if self._plan.num_partitions > 1:
            plan = CpuGlobalLimitExec(n, plan)
        return DataFrame(plan, self._session)

    def union(self, other: "DataFrame") -> "DataFrame":
        from spark_rapids_tpu.exec.basic import CpuUnionExec
        return DataFrame(CpuUnionExec([self._plan, other._plan]),
                         self._session)

    def sample(self, fraction: float, seed: int = 0) -> "DataFrame":
        from spark_rapids_tpu.exec.basic import CpuSampleExec
        return DataFrame(CpuSampleExec(fraction, seed, self._plan),
                         self._session)

    def explode(self, column, alias: str = "col", outer: bool = False,
                position: bool = False) -> "DataFrame":
        """One output row per array element; other columns repeat.  With
        ``position`` adds the element ordinal (posexplode); ``outer`` keeps
        null/empty rows (explode_outer)."""
        from spark_rapids_tpu.exec.generate import CpuGenerateExec
        gen = bind_references(_to_expr(column), self.schema)
        self._no_windows(gen, "explode")
        return DataFrame(CpuGenerateExec(gen, self._plan, outer=outer,
                                         position=position,
                                         element_name=alias),
                         self._session)

    def posexplode(self, column, alias: str = "col",
                   outer: bool = False) -> "DataFrame":
        return self.explode(column, alias, outer, position=True)

    def repartition(self, n: int, *cols) -> "DataFrame":
        """Round-robin repartition, or hash repartition when keys given."""
        from spark_rapids_tpu.exec.exchange import CpuShuffleExchangeExec
        from spark_rapids_tpu.plan.partitioning import (HashPartitioning,
                                                        RoundRobinPartitioning)
        if cols:
            keys = [bind_references(_to_expr(c), self.schema) for c in cols]
            part = HashPartitioning(keys, n)
        else:
            part = RoundRobinPartitioning(n)
        return DataFrame(
            CpuShuffleExchangeExec(part, self._plan,
                                   shuffle_env=self._session.shuffle_env),
            self._session)

    def coalesce(self, n: int) -> "DataFrame":
        """Shuffle-free partition merge (Spark coalesce contract)."""
        from spark_rapids_tpu.exec.basic import CpuCoalescePartitionsExec
        return DataFrame(CpuCoalescePartitionsExec(n, self._plan),
                         self._session)

    def _sort_specs(self, cols, kw_ascending):
        from spark_rapids_tpu.exec.sort import SortSpec
        specs = []
        for c in cols:
            if isinstance(c, SortSpec):
                specs.append(SortSpec(
                    bind_references(c.expr, self.schema), c.ascending,
                    c.nulls_first))
            else:
                specs.append(SortSpec(
                    bind_references(_to_expr(c), self.schema), kw_ascending))
        for s in specs:
            self._no_windows(s.expr, "sort keys")
        return specs

    def order_by(self, *cols, ascending: bool = True) -> "DataFrame":
        """Global total-order sort: range-partition then per-partition sort
        (Spark SortExec(global=true) over RangePartitioning)."""
        from spark_rapids_tpu.exec.exchange import CpuShuffleExchangeExec
        from spark_rapids_tpu.exec.sort import CpuSortExec
        from spark_rapids_tpu.plan.partitioning import RangePartitioning
        specs = self._sort_specs(cols, ascending)
        plan = self._plan
        if plan.num_partitions > 1:
            def _is_array(e):
                try:
                    return isinstance(e.data_type, T.ArrayType)
                except Exception:    # noqa: BLE001
                    return False
            if any(_is_array(s.expr) for s in specs):
                # no range-partitioner for array keys (either engine):
                # global sort collapses to one partition instead
                from spark_rapids_tpu.exec.basic import \
                    CpuCoalescePartitionsExec
                plan = CpuCoalescePartitionsExec(1, plan)
            else:
                plan = CpuShuffleExchangeExec(
                    RangePartitioning(specs, plan.num_partitions), plan,
                    shuffle_env=self._session.shuffle_env)
        return DataFrame(CpuSortExec(specs, plan, global_sort=True),
                         self._session)

    sort = order_by

    def sort_within_partitions(self, *cols, ascending: bool = True
                               ) -> "DataFrame":
        from spark_rapids_tpu.exec.sort import CpuSortExec
        return DataFrame(CpuSortExec(self._sort_specs(cols, ascending),
                                     self._plan), self._session)

    def join(self, other: "DataFrame", on=None, how: str = "inner",
             condition=None, null_safe: bool = False) -> "DataFrame":
        """Equi-join on column names (USING semantics: key columns emitted
        once), with an optional extra non-equi ``condition`` over the
        combined row; ``on=None`` with a condition = nested-loop join.
        Wrap the right side in functions.broadcast() to force a broadcast
        hash join (reference: GpuBroadcastHashJoinExec rule)."""
        from spark_rapids_tpu.exec.joins import (
            CpuBroadcastHashJoinExec, CpuBroadcastNestedLoopJoinExec,
            CpuShuffledHashJoinExec, _normalize_how)
        from spark_rapids_tpu.exec.exchange import CpuShuffleExchangeExec
        from spark_rapids_tpu.expressions.base import BoundReference
        from spark_rapids_tpu.expressions.conditional import Coalesce
        from spark_rapids_tpu.plan.partitioning import HashPartitioning
        import spark_rapids_tpu.ops.join_ops as J
        jt = _normalize_how(how)
        lplan, rplan = self._plan, other._plan
        lschema, rschema = lplan.schema, rplan.schema
        combined = T.StructType(list(lschema.fields) + list(rschema.fields))
        cond = None
        if condition is not None:
            cond = bind_references(_to_expr(condition), combined)
        if on is None or jt == J.CROSS:
            if jt in (J.RIGHT_OUTER, J.FULL_OUTER):
                raise NotImplementedError(
                    f"{jt} without equi-join keys is not supported; "
                    "provide `on` columns")
            plan = CpuBroadcastNestedLoopJoinExec([], [], jt, cond, lplan,
                                                  rplan)
            return DataFrame(plan, self._session)
        names = [on] if isinstance(on, str) else list(on)
        lkeys = [bind_references(col(n), lschema) for n in names]
        rkeys = [bind_references(col(n), rschema) for n in names]
        ns = [null_safe] * len(names)
        broadcastable = getattr(other, "_broadcast_hint", False) and \
            jt in (J.INNER, J.LEFT_OUTER, J.LEFT_SEMI, J.LEFT_ANTI)
        if broadcastable:
            plan = CpuBroadcastHashJoinExec(lkeys, rkeys, jt, cond, lplan,
                                            rplan, ns)
        else:
            nparts = max(lplan.num_partitions, rplan.num_partitions)
            if nparts > 1:
                env = self._session.shuffle_env
                lplan = CpuShuffleExchangeExec(
                    HashPartitioning(lkeys, nparts), lplan, shuffle_env=env)
                rplan = CpuShuffleExchangeExec(
                    HashPartitioning(rkeys, nparts), rplan, shuffle_env=env)
                # keys bind identically post-shuffle (same child schema)
            plan = CpuShuffledHashJoinExec(lkeys, rkeys, jt, cond, lplan,
                                           rplan, ns)
        df = DataFrame(plan, self._session)
        if jt in (J.LEFT_SEMI, J.LEFT_ANTI):
            return df
        # USING projection: key cols once (left / right / coalesced per join
        # type, Spark semantics), then remaining left cols, then right cols
        nl = len(lschema.fields)
        out_schema = plan.schema
        key_l = {lschema.field_index(n) for n in names}
        key_r = {rschema.field_index(n) for n in names}
        exprs = []
        for n in names:
            li = lschema.field_index(n)
            ri = nl + rschema.field_index(n)
            lf = out_schema.fields[li]
            rf = out_schema.fields[ri]
            lref = BoundReference(li, lf.data_type, lf.nullable)
            rref = BoundReference(ri, rf.data_type, rf.nullable)
            if jt == J.FULL_OUTER:
                exprs.append(Alias(Coalesce(lref, rref), n))
            elif jt == J.RIGHT_OUTER:
                exprs.append(Alias(rref, n))
            else:
                exprs.append(Alias(lref, n))
        for i, f in enumerate(lschema.fields):
            if i not in key_l:
                of = out_schema.fields[i]
                exprs.append(Alias(
                    BoundReference(i, of.data_type, of.nullable), f.name))
        for i, f in enumerate(rschema.fields):
            if i not in key_r:
                of = out_schema.fields[nl + i]
                exprs.append(Alias(
                    BoundReference(nl + i, of.data_type, of.nullable),
                    f.name))
        from spark_rapids_tpu.exec.basic import CpuProjectExec
        return DataFrame(CpuProjectExec(exprs, plan), self._session)

    def cross_join(self, other: "DataFrame") -> "DataFrame":
        return self.join(other, on=None, how="cross")

    crossJoin = cross_join

    def group_by(self, *cols) -> "GroupedData":
        keys = [self._no_windows(bind_references(_to_expr(c), self.schema),
                                 "grouping keys") for c in cols]
        return GroupedData(self, keys)

    groupBy = group_by

    def rollup(self, *cols) -> "GroupedData":
        """GROUP BY ROLLUP(k1..kn): grouping sets (k1..kn), (k1..kn-1), …, ().
        Physical plan: Expand fan-out + grouping-id key (Spark's lowering)."""
        keys = [self._no_windows(bind_references(_to_expr(c), self.schema),
                                 "grouping keys") for c in cols]
        sets = [tuple(range(i)) for i in range(len(keys), -1, -1)]
        return GroupedData(self, keys, grouping_sets=sets,
                           key_names=[str(c) for c in cols])

    def cube(self, *cols) -> "GroupedData":
        """GROUP BY CUBE(k1..kn): all 2^n grouping sets."""
        import itertools
        keys = [self._no_windows(bind_references(_to_expr(c), self.schema),
                                 "grouping keys") for c in cols]
        idx = range(len(keys))
        sets = []
        for r in range(len(keys), -1, -1):
            sets.extend(itertools.combinations(idx, r))
        return GroupedData(self, keys, grouping_sets=sets,
                           key_names=[str(c) for c in cols])

    def grouping_sets(self, cols, sets) -> "GroupedData":
        """Explicit GROUPING SETS over named key columns; ``sets`` is a list
        of tuples of key names."""
        keys = [self._no_windows(bind_references(_to_expr(c), self.schema),
                                 "grouping keys") for c in cols]
        name_to_idx = {str(c): i for i, c in enumerate(cols)}
        idx_sets = [tuple(sorted(name_to_idx[n] for n in s)) for s in sets]
        return GroupedData(self, keys, grouping_sets=idx_sets,
                           key_names=[str(c) for c in cols])

    def agg(self, *agg_exprs) -> "DataFrame":
        """Global aggregation (no grouping keys)."""
        return GroupedData(self, []).agg(*agg_exprs)

    def distinct(self) -> "DataFrame":
        return self.group_by(*self.columns).agg()

    def map_in_pandas(self, fn, schema: T.StructType) -> "DataFrame":
        """Vectorized python: fn(pandas.DataFrame) -> pandas.DataFrame per
        batch (reference GpuMapInPandasExec; host tier)."""
        from spark_rapids_tpu.exec.python_execs import CpuMapInPandasExec
        return DataFrame(CpuMapInPandasExec(fn, schema, self._plan),
                         self._session)

    def cache(self) -> "DataFrame":
        """Materializes this plan once into compressed parquet-encoded host
        batches (reference: ParquetCachedBatchSerializer); later actions
        scan the cache."""
        from spark_rapids_tpu.io.cache_serializer import CpuCachedScanExec
        executed = self._executed_plan()
        scan = CpuCachedScanExec(self.schema, executed.num_partitions)
        scan.materialize(executed)
        return DataFrame(scan, self._session)

    drop_duplicates = distinct

    # -- actions ------------------------------------------------------------
    def _executed_plan(self) -> Exec:
        overrides = TpuOverrides(self._session.conf)
        plan = overrides.apply(self._plan)
        # an active QueryExecution mirrors the plan it is about to run as
        # its span tree (re-attaching on a speculation replay is fine)
        from spark_rapids_tpu.aux import events as EV
        q = EV.active_query()
        if q is not None:
            q.attach_plan(plan)
        return plan

    def collect_batch(self) -> HostColumnarBatch:
        from spark_rapids_tpu.aux.tracing import query_scope
        with query_scope(self._session.conf, "collect"):
            return self._collect_batch_traced()

    def _collect_batch_traced(self) -> HostColumnarBatch:
        return collect_with_speculation(self._session.conf,
                                        self._executed_plan)

    def to_pydict(self) -> Dict[str, list]:
        return self.collect_batch().to_pydict()

    def to_arrow(self):
        import pyarrow as pa
        return pa.Table.from_batches([self.collect_batch().to_arrow()])

    def to_pandas(self):
        return self.to_arrow().to_pandas()

    def collect(self) -> List[dict]:
        return rows_from_host_batch(self.collect_batch())

    def count(self) -> int:
        from spark_rapids_tpu.aux import events as EV
        from spark_rapids_tpu.aux.tracing import query_scope
        from spark_rapids_tpu.columnar.column import sum_counts
        from spark_rapids_tpu.plan.pruning import prune_columns
        # count needs row counts only: prune every column the plan's own
        # filters/keys don't reference, then sum deferred device counts
        # with ONE sync total
        plan = self._plan
        if self._session.conf.get(C.COLUMN_PRUNING_ENABLED.key, True):
            plan = prune_columns(plan, required=set())
        overrides = TpuOverrides(self._session.conf)
        with query_scope(self._session.conf, "count"):
            # already pruned above (with the tighter empty required-set);
            # don't pay a second tree walk inside apply()
            executed = overrides.apply(plan, skip_pruning=True)
            q = EV.active_query()
            if q is not None:
                q.attach_plan(executed)
            return sum_counts([b.row_count for b in executed.execute_all()])

    def write_parquet(self, path: str) -> None:
        from spark_rapids_tpu.aux.tracing import query_scope
        from spark_rapids_tpu.io.parquet import write_parquet
        with query_scope(self._session.conf, "write_parquet"):
            write_parquet(self._executed_plan().execute_all(), path,
                          self.schema)

    def write_hive_text(self, path: str, serde=None) -> None:
        """Hive text table write (reference: GpuHiveTextFileFormat)."""
        from spark_rapids_tpu.aux.tracing import query_scope
        from spark_rapids_tpu.hive.table import write_hive_text
        with query_scope(self._session.conf, "write_hive_text"):
            write_hive_text(self._executed_plan().execute_all(), path,
                            self.schema, serde=serde)

    @property
    def write(self):
        """Directory-style writer: ``df.write.mode("overwrite").parquet(p)``."""
        from spark_rapids_tpu.io.writer import DataFrameWriter
        return DataFrameWriter(self)

    # -- introspection ------------------------------------------------------
    def explain(self, mode: str = "formatted",
                analyze: bool = False) -> str:
        """Shows CPU plan, TPU-rewritten plan, and fallback reasons
        (reference: ExplainPlan.explainPotentialGpuPlan).

        ``analyze=True`` (Spark's EXPLAIN ANALYZE) EXECUTES the plan under
        a QueryExecution trace and renders the tree annotated with
        per-node rows/batches/opTime plus attributed spill/retry, and the
        query-level task-metric summary."""
        if analyze:
            from spark_rapids_tpu.aux.tracing import QueryExecution
            qe = QueryExecution.from_conf(self._session.conf,
                                          "explain(analyze=True)")
            with qe:
                # joins this QueryExecution via query_scope's
                # already-active path; attach happens in _executed_plan
                self.collect_batch()
            return qe.render_tree()
        overrides = TpuOverrides(self._session.conf)
        final = overrides.apply(self._plan, for_explain=True)
        reasons = overrides.last_meta.explain(all_nodes=True) \
            if overrides.last_meta else ""
        out = (f"== Physical Plan (input) ==\n{self._plan.tree_string()}\n"
               f"== TPU Plan ==\n{final.tree_string()}\n"
               f"== Placement ==\n{reasons}")
        elided = overrides.last_elided
        out += (f"\n== Distribution ==\nexchangeElided={len(elided)}"
                + "".join(f"\n  - {e.desc()}" for e in elided))
        cost = self._cost_section(final)
        if cost:
            out += f"\n{cost}"
        return out

    def _cost_section(self, final: Exec) -> str:
        """Report-only ``== Cost ==`` explain section from the calibrated
        machine profile (``spark.rapids.history.machineProfilePath``,
        produced by ``tools history calibrate``).  Empty string when no
        profile is configured/loadable — explain never fails over it."""
        conf = self._session.conf
        path = conf.get(C.HISTORY_MACHINE_PROFILE_PATH.key)
        if not path or not conf.get(C.HISTORY_COST_MODEL_ENABLED.key):
            return ""
        from spark_rapids_tpu.plan.cost import (load_machine_profile,
                                                predict_plan_costs,
                                                render_cost_section)
        profile = load_machine_profile(path)
        if profile is None:
            return f"== Cost ==\nmachine profile unreadable: {path}"
        try:
            rows = predict_plan_costs(final, profile)
            return render_cost_section(rows, profile)
        except Exception as exc:    # noqa: BLE001 - report-only section
            return f"== Cost ==\nprediction failed: {exc}"

    def __repr__(self):
        return f"DataFrame[{self.schema.simple_name}]"


class DataFrameNaFunctions:
    """df.na.fill / df.na.drop (Spark DataFrameNaFunctions)."""

    def __init__(self, df: DataFrame):
        self._df = df

    def fill(self, value, subset=None) -> DataFrame:
        from spark_rapids_tpu.expressions.conditional import Coalesce
        names = set(subset) if subset is not None else None
        proj = []
        for f in self._df.schema.fields:
            use = names is None or f.name in names
            # bool is an int subclass: check it FIRST so fill(True) only
            # touches boolean columns (Spark semantics)
            if isinstance(value, bool):
                compatible = isinstance(f.data_type, T.BooleanType)
            elif isinstance(value, (int, float)):
                compatible = f.data_type.is_numeric
            elif isinstance(value, str):
                compatible = isinstance(f.data_type, T.StringType)
            else:
                compatible = False
            if use and compatible:
                proj.append(Alias(Coalesce(col(f.name),
                                           lit(value, f.data_type)),
                                  f.name))
            else:
                proj.append(col(f.name))
        return self._df.select(*proj)

    def drop(self, how: str = "any", subset=None) -> DataFrame:
        from spark_rapids_tpu.expressions.conditional import AtLeastNNonNulls
        if how not in ("any", "all"):
            raise ValueError(f"how must be 'any' or 'all', got {how!r}")
        names = list(subset) if subset is not None else self._df.columns
        need = len(names) if how == "any" else 1
        return self._df.filter(
            AtLeastNNonNulls(need, *[col(n) for n in names]))


class GroupedData:
    """df.group_by(keys) -> .agg(...); assembles the two-stage physical
    aggregation (partial -> hash exchange -> final), Spark's
    EnsureRequirements pattern for aggregation."""

    def __init__(self, df: DataFrame, keys, grouping_sets=None,
                 key_names=None):
        self._df = df
        self._keys = keys
        self._grouping_sets = grouping_sets  # list of tuples of key indices
        self._key_names = key_names
        self._pivot = None
        #: expose __grouping_id as the LAST output column (grouping())
        self._keep_gid = False

    def _expand_for_grouping_sets(self):
        """Lowers ROLLUP/CUBE/GROUPING SETS to Expand + regular group-by
        (Spark's rewrite): one projection per grouping set emitting
        [k1-or-null, …, kn-or-null, grouping_id, *child columns]; the
        grouping id joins the keys so a null produced by the rollup never
        merges with a genuine null key from another set."""
        from spark_rapids_tpu.exec.expand import CpuExpandExec
        from spark_rapids_tpu.expressions.base import (BoundReference,
                                                       Literal)
        child = self._df._plan
        schema = child.schema
        nk = len(self._keys)
        key_names = self._key_names or [f"k{i}" for i in range(nk)]
        child_refs = [BoundReference(i, f.data_type, f.nullable, f.name)
                      for i, f in enumerate(schema.fields)]
        projections = []
        for s in self._grouping_sets:
            gid = 0  # Spark semantics: bit i set when key i is NOT grouped
            for i in range(nk):
                if i not in s:
                    gid |= 1 << (nk - 1 - i)
            proj = [self._keys[i] if i in s
                    else Literal(None, self._keys[i].data_type)
                    for i in range(nk)]
            proj.append(Literal(gid, T.LONG))
            proj.extend(child_refs)
            projections.append(proj)
        names = (key_names + ["__grouping_id"]
                 + [f.name for f in schema.fields])
        expand = CpuExpandExec(projections, names, child)
        # re-key on the expanded columns: keys + grouping id
        new_keys = [_bound_ref(i, expand.schema) for i in range(nk + 1)]
        # aggregate inputs shift past the nk+1 key columns
        shift = nk + 1

        def rebind(e):
            def fix(node):
                if isinstance(node, BoundReference):
                    return BoundReference(node.ordinal + shift,
                                          node.data_type, node.nullable,
                                          node.ref_name)
                return node
            return e.transform_up(fix)
        return expand, new_keys, rebind, nk

    def agg(self, *agg_exprs) -> "DataFrame":
        from spark_rapids_tpu.exec.aggregate import (COMPLETE, FINAL,
                                                     PARTIAL,
                                                     CpuHashAggregateExec)
        from spark_rapids_tpu.exec.exchange import CpuShuffleExchangeExec
        from spark_rapids_tpu.expressions.aggregates import (
            AggregateExpression, AggregateFunction)
        from spark_rapids_tpu.expressions.python_udf import PandasUDFCall
        from spark_rapids_tpu.plan.partitioning import (HashPartitioning,
                                                        SinglePartitioning)
        schema = self._df.schema
        pandas_calls = [e for e in agg_exprs if isinstance(
            e.children[0] if isinstance(e, Alias) else e, PandasUDFCall)]
        if pandas_calls:
            if len(pandas_calls) != len(agg_exprs):
                raise TypeError("pandas-UDF aggregations cannot mix with "
                                "builtin aggregates in one agg()")
            return self._agg_in_pandas(agg_exprs)
        raw = []
        for e in agg_exprs:
            name = None
            if isinstance(e, Alias):
                name, e = e.alias_name, e.children[0]
            if not isinstance(e, AggregateFunction):
                raise TypeError(f"not an aggregate expression: {e}")
            e = bind_references(e, schema)
            DataFrame._no_windows(e, "aggregations")
            raw.append((e, name))
        if self._pivot is not None:
            # pivot lowering: one conditional aggregate per (value, agg) —
            # agg inputs null out where the pivot column != value
            from spark_rapids_tpu.expressions.conditional import If
            from spark_rapids_tpu.expressions.predicates import EqualTo
            pc, values = self._pivot
            pivoted = []
            for v in values:
                cond = EqualTo(pc, lit(v))
                for e, name in raw:
                    import copy
                    pe = copy.copy(e)
                    pe.children = [
                        If(cond, c, Literal(None, c.data_type))
                        for c in e.children]
                    label = f"{v}" if len(raw) == 1 else                         f"{v}_{name or e.sql()}"
                    pivoted.append((pe, label))
            raw = pivoted
        aggs = [AggregateExpression(e, name or e.sql())
                for e, name in raw]
        child = self._df._plan
        if self._grouping_sets is not None:
            return self._agg_grouping_sets(aggs)
        if any(a.func.requires_complete for a in aggs):
            # variable-length-state aggregates (collect/percentile): hash
            # shuffle the RAW rows by key, then one COMPLETE pass per
            # partition (Spark's ObjectHashAggregate pattern)
            nk = len(self._keys)
            if child.num_partitions > 1 and nk:
                part = HashPartitioning(self._keys, child.num_partitions)
                child = CpuShuffleExchangeExec(
                    part, child, shuffle_env=self._df._session.shuffle_env)
            elif child.num_partitions > 1:
                from spark_rapids_tpu.exec.basic import \
                    CpuCoalescePartitionsExec
                child = CpuCoalescePartitionsExec(1, child)
            return DataFrame(
                CpuHashAggregateExec(self._keys, aggs, COMPLETE, child),
                self._df._session)
        if child.num_partitions == 1:
            plan = CpuHashAggregateExec(self._keys, aggs, COMPLETE, child)
        else:
            partial = CpuHashAggregateExec(self._keys, aggs, PARTIAL, child)
            nk = len(self._keys)
            if nk:
                key_refs = [_bound_ref(i, partial.schema) for i in range(nk)]
                part = HashPartitioning(key_refs, child.num_partitions)
            else:
                part = SinglePartitioning()
            exchange = CpuShuffleExchangeExec(
                part, partial, shuffle_env=self._df._session.shuffle_env)
            final_keys = [_bound_ref(i, partial.schema) for i in range(nk)]
            plan = CpuHashAggregateExec(final_keys, aggs, FINAL, exchange)
        return DataFrame(plan, self._df._session)

    def _agg_grouping_sets(self, aggs) -> "DataFrame":
        from spark_rapids_tpu.exec.aggregate import (COMPLETE, FINAL,
                                                     PARTIAL,
                                                     CpuHashAggregateExec)
        from spark_rapids_tpu.exec.basic import CpuProjectExec
        from spark_rapids_tpu.exec.exchange import CpuShuffleExchangeExec
        from spark_rapids_tpu.expressions.aggregates import AggregateExpression
        from spark_rapids_tpu.plan.partitioning import HashPartitioning
        expand, new_keys, rebind, nk = self._expand_for_grouping_sets()
        aggs = [AggregateExpression(rebind(a.func), a.out_name)
                for a in aggs]
        if expand.num_partitions == 1:
            plan = CpuHashAggregateExec(new_keys, aggs, COMPLETE, expand)
        else:
            partial = CpuHashAggregateExec(new_keys, aggs, PARTIAL, expand)
            key_refs = [_bound_ref(i, partial.schema)
                        for i in range(len(new_keys))]
            exchange = CpuShuffleExchangeExec(
                HashPartitioning(key_refs, expand.num_partitions), partial,
                shuffle_env=self._df._session.shuffle_env)
            final_keys = [_bound_ref(i, partial.schema)
                          for i in range(len(new_keys))]
            plan = CpuHashAggregateExec(final_keys, aggs, FINAL, exchange)
        # drop the internal grouping id: keys, then agg outputs — unless
        # grouping() needs it, in which case it rides LAST so key/agg
        # ordinal math stays unchanged
        out = [_bound_ref(i, plan.schema) for i in range(nk)]
        out += [_bound_ref(i, plan.schema)
                for i in range(nk + 1, len(plan.schema.fields))]
        if self._keep_gid:
            out.append(Alias(_bound_ref(nk, plan.schema), "__grouping_id"))
        return DataFrame(CpuProjectExec(out, plan), self._df._session)

    def pivot(self, pivot_col, values) -> "GroupedData":
        """df.group_by(k).pivot(c, [v1, v2]).agg(sum(x)): each pivot value
        becomes a column via conditional aggregation (Spark's pivot
        lowering: agg(expr WHERE c == v) per value)."""
        if self._grouping_sets is not None:
            raise ValueError("pivot cannot follow rollup/cube")
        pc = bind_references(_to_expr(pivot_col), self._df.schema)
        out = GroupedData(self._df, self._keys)
        out._pivot = (pc, list(values))
        return out

    _pivot = None

    def _shuffled_child(self):
        """Child hash-partitioned by the grouping keys (the raw-row
        shuffle every grouped pandas exec needs)."""
        from spark_rapids_tpu.exec.exchange import CpuShuffleExchangeExec
        from spark_rapids_tpu.plan.partitioning import HashPartitioning
        child = self._df._plan
        if child.num_partitions > 1 and self._keys:
            child = CpuShuffleExchangeExec(
                HashPartitioning(self._keys, child.num_partitions), child,
                shuffle_env=self._df._session.shuffle_env)
        return child

    def _grouping_key_names(self):
        """Plain-column key names; grouped pandas execs group the pandas
        frame BY NAME, so expression keys cannot be honored (clean
        planning-time error instead of a KeyError mid-execution)."""
        names = []
        for k in self._keys:
            name = getattr(k, "ref_name", None)
            if not name:
                raise ValueError(
                    f"grouped pandas operations require plain column "
                    f"grouping keys, got expression {k.sql()!r}; project "
                    "it into a column first")
            names.append(name)
        return names

    def _pandas_udf_specs(self, agg_exprs):
        """[(out_name, fn, bound input exprs, dtype)] from
        Alias(PandasUDFCall)/PandasUDFCall aggregates."""
        from spark_rapids_tpu.expressions.python_udf import PandasUDFCall
        schema = self._df.schema
        udfs = []
        for i, e in enumerate(agg_exprs):
            name = None
            if isinstance(e, Alias):
                name, e = e.alias_name, e.children[0]
            assert isinstance(e, PandasUDFCall)
            bound = bind_references(e, schema)
            udfs.append((name or bound.sql(), bound.fn,
                         list(bound.children), bound.data_type))
        return udfs

    def _agg_in_pandas(self, agg_exprs) -> "DataFrame":
        """group_by(keys).agg(pandas_udf(...)(col)): one output row per
        group (reference GpuAggregateInPandasExec)."""
        from spark_rapids_tpu.exec.python_execs import \
            CpuAggregateInPandasExec
        if self._grouping_sets is not None:
            raise ValueError("pandas-UDF aggregation cannot follow "
                             "rollup/cube")
        return DataFrame(
            CpuAggregateInPandasExec(self._grouping_key_names(),
                                     self._pandas_udf_specs(agg_exprs),
                                     self._shuffled_child()),
            self._df._session)

    def window_in_pandas(self, *agg_exprs) -> "DataFrame":
        """Whole-partition pandas UDFs appended as columns, one value per
        group broadcast to its rows (reference GpuWindowInPandasExec's
        unbounded-frame shape)."""
        from spark_rapids_tpu.exec.python_execs import CpuWindowInPandasExec
        if self._grouping_sets is not None:
            raise ValueError("window_in_pandas cannot follow rollup/cube")
        return DataFrame(
            CpuWindowInPandasExec(self._grouping_key_names(),
                                  self._pandas_udf_specs(agg_exprs),
                                  self._shuffled_child()),
            self._df._session)

    def cogroup(self, other: "GroupedData") -> "CoGroupedData":
        """pyspark parity: df.group_by(k).cogroup(df2.group_by(k))
        .apply_in_pandas(fn, schema)."""
        return CoGroupedData(self, other)

    def apply_in_pandas(self, fn, schema: T.StructType) -> "DataFrame":
        """Grouped pandas apply: shuffle raw rows by the keys, then
        fn(group_pdf) -> pdf per group (reference
        GpuFlatMapGroupsInPandasExec)."""
        from spark_rapids_tpu.exec.exchange import CpuShuffleExchangeExec
        from spark_rapids_tpu.exec.python_execs import \
            CpuFlatMapGroupsInPandasExec
        from spark_rapids_tpu.plan.partitioning import HashPartitioning
        if self._grouping_sets is not None:
            raise ValueError("apply_in_pandas cannot follow rollup/cube")
        child = self._df._plan
        key_names = self._grouping_key_names()
        if child.num_partitions > 1 and self._keys:
            child = CpuShuffleExchangeExec(
                HashPartitioning(self._keys, child.num_partitions), child,
                shuffle_env=self._df._session.shuffle_env)
        return DataFrame(
            CpuFlatMapGroupsInPandasExec(key_names, fn, schema, child),
            self._df._session)

    # sugar
    def count(self) -> "DataFrame":
        from spark_rapids_tpu.expressions.aggregates import Count
        return self.agg(Alias(Count(lit(1)), "count"))

    def sum(self, *cols) -> "DataFrame":
        from spark_rapids_tpu.expressions.aggregates import Sum
        return self.agg(*[Alias(Sum(_to_expr(c)), f"sum({c})")
                          for c in cols])

    def avg(self, *cols) -> "DataFrame":
        from spark_rapids_tpu.expressions.aggregates import Average
        return self.agg(*[Alias(Average(_to_expr(c)), f"avg({c})")
                          for c in cols])

    def min(self, *cols) -> "DataFrame":
        from spark_rapids_tpu.expressions.aggregates import Min
        return self.agg(*[Alias(Min(_to_expr(c)), f"min({c})")
                          for c in cols])

    def max(self, *cols) -> "DataFrame":
        from spark_rapids_tpu.expressions.aggregates import Max
        return self.agg(*[Alias(Max(_to_expr(c)), f"max({c})")
                          for c in cols])


class CoGroupedData:
    """Two grouped frames co-grouped by their keys (reference:
    GpuFlatMapCoGroupsInPandasExec)."""

    def __init__(self, left: "GroupedData", right: "GroupedData"):
        if len(left._keys) != len(right._keys):
            raise ValueError("cogroup requires the same number of keys on "
                             "both sides")
        self._left = left
        self._right = right

    def apply_in_pandas(self, fn, schema: T.StructType) -> "DataFrame":
        from spark_rapids_tpu.exec.exchange import CpuShuffleExchangeExec
        from spark_rapids_tpu.exec.python_execs import \
            CpuFlatMapCoGroupsInPandasExec
        from spark_rapids_tpu.plan.partitioning import HashPartitioning
        lplan = self._left._df._plan
        rplan = self._right._df._plan
        n = max(lplan.num_partitions, rplan.num_partitions)
        senv = self._left._df._session.shuffle_env
        if n > 1:
            lplan = CpuShuffleExchangeExec(
                HashPartitioning(self._left._keys, n), lplan,
                shuffle_env=senv)
            rplan = CpuShuffleExchangeExec(
                HashPartitioning(self._right._keys, n), rplan,
                shuffle_env=senv)
        return DataFrame(
            CpuFlatMapCoGroupsInPandasExec(
                self._left._grouping_key_names(),
                self._right._grouping_key_names(),
                fn, schema, lplan, rplan),
            self._left._df._session)


def _bound_ref(i: int, schema: T.StructType):
    f = schema.fields[i]
    from spark_rapids_tpu.expressions.base import BoundReference
    return Alias(BoundReference(i, f.data_type, f.nullable), f.name)
