"""Accelerated shuffle subsystem.

Reference: SURVEY.md §2.8 — the UCX peer-to-peer shuffle stack
(`com/nvidia/spark/rapids/shuffle/`): `RapidsShuffleTransport` SPI,
client/server state machines with bounce buffers, flatbuffers control
protocol, `ShuffleBufferCatalog`/`ShuffleReceivedBufferCatalog`, the
driver-side `RapidsShuffleHeartbeatManager`, and the MULTITHREADED
writer/reader mode (`RapidsShuffleInternalManagerBase.scala:238,569`).

TPU redesign: RDMA bounce buffers become fixed-size staging buffers over
whatever byte transport links executors (in-process loopback here; DCN/gRPC
in a deployment); ICI all-to-all (parallel/collective.py) replaces NVLink
peer copies inside a slice.  The catalog + windowed-transfer + heartbeat
architecture is preserved — that is the part the reference proves out, and
it is what the mocked-transport tests exercise without a cluster
(SURVEY.md §4 takeaway)."""

from spark_rapids_tpu.shuffle.catalog import (  # noqa: F401
    ShuffleBlockId, ShuffleBufferCatalog, ShuffleReceivedBufferCatalog)
from spark_rapids_tpu.shuffle.client_server import (  # noqa: F401
    FetchRetryPolicy, ShuffleClient, ShuffleFetchFailed, ShuffleServer)
from spark_rapids_tpu.shuffle.protocol import (  # noqa: F401
    BlockMeta, MetadataRequest, MetadataResponse, TransferRequest,
    TransferResponse, decode_message, encode_message)
from spark_rapids_tpu.shuffle.transport import (  # noqa: F401
    BounceBufferManager, Connection, InProcessTransport, Transaction,
    TransactionStatus, Transport, WindowedBlockIterator)
