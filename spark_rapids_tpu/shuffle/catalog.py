"""Shuffle buffer catalogs.

Reference: ShuffleBufferCatalog (map-side shuffle payloads tracked as
spillable buffers, RapidsCachingWriter registers batches
RapidsShuffleInternalManagerBase.scala:1034-1057) and
ShuffleReceivedBufferCatalog (fetched blocks on the reduce side).

Payloads live as serialized frames registered with the memory runtime's
tiered catalog when available (spill-to-disk under pressure), else plain
host bytes.
"""

from __future__ import annotations

import dataclasses
import threading
from typing import Dict, Iterator, List, Optional, Tuple

from spark_rapids_tpu.shuffle.serializer import (deserialize_batch,
                                                 serialize_batch)


@dataclasses.dataclass(frozen=True, order=True)
class ShuffleBlockId:
    shuffle_id: int
    map_id: int
    partition_id: int


class ShuffleBufferCatalog:
    """Map-side store: (shuffle, map, reduce-partition) -> serialized frames.

    Thread-safe: the multithreaded writer registers from pool threads."""

    def __init__(self, codec: str = "none"):
        self.codec = codec
        self._lock = threading.Lock()
        self._blocks: Dict[ShuffleBlockId, List[bytes]] = {}
        #: block -> owning executor id (None for locally produced blocks);
        #: drop_owner invalidates a dead executor's blocks on heartbeat
        #: expiry so stale data can never serve a post-expiry fetch
        self._owners: Dict[ShuffleBlockId, Optional[str]] = {}

    def add_batch(self, block: ShuffleBlockId, hb,
                  owner: Optional[str] = None) -> int:
        """Serializes and registers one batch; returns frame length."""
        frame = serialize_batch(hb, self.codec)
        self.add_frame(block, frame, owner=owner)
        return len(frame)

    def add_frame(self, block: ShuffleBlockId, frame: bytes,
                  owner: Optional[str] = None) -> None:
        with self._lock:
            self._blocks.setdefault(block, []).append(frame)
            if owner is not None or block not in self._owners:
                self._owners[block] = owner

    def drop_owner(self, executor_id: str) -> List[ShuffleBlockId]:
        """FetchFailed-style invalidation: removes every block registered
        as owned by ``executor_id`` (wired to heartbeat expiry); returns
        the dropped block ids so callers can schedule map re-runs."""
        with self._lock:
            dead = [b for b, o in self._owners.items() if o == executor_id]
            for b in dead:
                self._blocks.pop(b, None)
                self._owners.pop(b, None)
        if dead:
            from spark_rapids_tpu.aux.events import emit
            emit("shuffleBlocksInvalidated", executor_id=executor_id,
                 blocks=len(dead))
        return sorted(dead)

    def block_ids(self, shuffle_id: int,
                  partition_id: Optional[int] = None) -> List[ShuffleBlockId]:
        with self._lock:
            return sorted(
                b for b in self._blocks
                if b.shuffle_id == shuffle_id
                and (partition_id is None or b.partition_id == partition_id))

    def frames(self, block: ShuffleBlockId) -> List[bytes]:
        with self._lock:
            return list(self._blocks.get(block, ()))

    def block_sizes(self, shuffle_id: int, partition_id: int
                    ) -> List[Tuple[ShuffleBlockId, int]]:
        """(block, total bytes) for a reduce partition — the metadata the
        server answers MetadataRequests from."""
        out = []
        for b in self.block_ids(shuffle_id, partition_id):
            out.append((b, sum(len(f) for f in self.frames(b))))
        return out

    def read_batches(self, block: ShuffleBlockId):
        for frame in self.frames(block):
            yield deserialize_batch(frame)

    def drop_partition(self, shuffle_id: int, partition_id: int) -> None:
        """Releases a reduce partition's frames once the fetch is consumed
        (bounded catalog growth across queries)."""
        with self._lock:
            dead = [b for b in self._blocks
                    if b.shuffle_id == shuffle_id
                    and b.partition_id == partition_id]
            for b in dead:
                del self._blocks[b]
                self._owners.pop(b, None)

    def unregister_shuffle(self, shuffle_id: int) -> int:
        with self._lock:
            dead = [b for b in self._blocks if b.shuffle_id == shuffle_id]
            for b in dead:
                del self._blocks[b]
                self._owners.pop(b, None)
            return len(dead)

    def nbytes(self) -> int:
        with self._lock:
            return sum(len(f) for fr in self._blocks.values() for f in fr)


class ShuffleReceivedBufferCatalog:
    """Reduce-side store for fetched frames (reference:
    ShuffleReceivedBufferCatalog); frames arrive in bounce-buffer windows
    and are reassembled before registration."""

    def __init__(self):
        self._lock = threading.Lock()
        self._frames: Dict[ShuffleBlockId, List[bytes]] = {}

    def add_frame(self, block: ShuffleBlockId, frame: bytes) -> None:
        with self._lock:
            self._frames.setdefault(block, []).append(frame)

    def read_batches(self, block: ShuffleBlockId):
        with self._lock:
            frames = list(self._frames.get(block, ()))
        for f in frames:
            yield deserialize_batch(f)

    def blocks(self) -> List[ShuffleBlockId]:
        with self._lock:
            return sorted(self._frames)

    def drop(self, block: ShuffleBlockId) -> None:
        with self._lock:
            self._frames.pop(block, None)
