"""Shuffle client/server state machines.

Reference: RapidsShuffleClient.scala (481 — doFetch: MetadataRequest ->
TransferRequest -> receive into bounce buffers -> reassemble) and
RapidsShuffleServer.scala (450 — BufferSendState drains blocks through
bounce buffers).  The flow is the reference's, byte-for-byte simpler:

  client                          server
    |--- MetadataRequest ---------->|   (which blocks exist for partition)
    |<-- MetadataResponse ----------|
    |--- TransferRequest ----------->|  (start sending block set)
    |<== BlockFrameHeader + bytes ==|  (windowed via bounce buffers)
    |<-- TransferResponse ----------|
"""

from __future__ import annotations

import threading
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from spark_rapids_tpu.shuffle.catalog import (ShuffleBlockId,
                                              ShuffleBufferCatalog,
                                              ShuffleReceivedBufferCatalog)
from spark_rapids_tpu.shuffle.protocol import (BlockFrameHeader, BlockMeta,
                                               MetadataRequest,
                                               MetadataResponse,
                                               TransferRequest,
                                               TransferResponse,
                                               decode_message, encode_message)
from spark_rapids_tpu.shuffle.transport import (BounceBufferManager,
                                                Connection,
                                                TransactionStatus)


class BufferSendState:
    """Server-side per-transfer cursor: drains the requested blocks through
    bounce buffers window by window (reference: BufferSendState in
    RapidsShuffleServer.scala)."""

    def __init__(self, req_id: int, blocks: Sequence[ShuffleBlockId],
                 catalog: ShuffleBufferCatalog,
                 bounce: BounceBufferManager):
        self.req_id = req_id
        self.catalog = catalog
        self.bounce = bounce
        # flatten every frame of every block (frame = one serialized batch)
        self.frames: List[Tuple[ShuffleBlockId, int, int, bytes]] = []
        for b in blocks:
            fr = catalog.frames(b)
            for i, f in enumerate(fr):
                self.frames.append((b, i, len(fr), f))
        self._idx = 0

    @property
    def done(self) -> bool:
        return self._idx >= len(self.frames)

    def send_next(self, conn: Connection) -> None:
        """Sends one frame as bounce-buffer-sized CHUNKS, each its own
        data-plane send — at most one bounce buffer of this frame is in
        flight at a time (real windowing/backpressure; the receiver
        reassembles by chunk offset)."""
        block, fi, fc, frame = self.frames[self._idx]
        self._idx += 1
        total = len(frame)
        sent = 0
        while sent < total or sent == 0:
            buf = self.bounce.acquire()
            try:
                take = min(self.bounce.buffer_size, total - sent)
                buf.data[:take] = frame[sent:sent + take]
                header = BlockFrameHeader(self.req_id, block, fi, fc,
                                          take, sent, total)
                txn = conn.send_data(encode_message(header),
                                     bytes(buf.data[:take]))
                txn.wait()
            finally:
                buf.close()
            if txn.status is not TransactionStatus.SUCCESS:
                raise ConnectionError(f"send failed: {txn.error_message}")
            sent += take
            if total == 0:
                break


class ShuffleServer:
    """Serves one executor's map output (reference: RapidsShuffleServer).

    Registered as the transport handler for this executor id; replies to
    control messages and pushes data frames back over the requesting
    connection."""

    def __init__(self, executor_id: str, catalog: ShuffleBufferCatalog,
                 transport, bounce: Optional[BounceBufferManager] = None):
        self.executor_id = executor_id
        self.catalog = catalog
        self.transport = transport
        self.bounce = bounce or BounceBufferManager()
        self._reply_to: Dict[int, str] = {}
        self._lock = threading.Lock()

    # -- transport handler interface ----------------------------------------
    def handle_request(self, message: bytes) -> bytes:
        msg = decode_message(message)
        if isinstance(msg, MetadataRequest):
            blocks = self.catalog.block_sizes(msg.shuffle_id,
                                              msg.partition_id)
            metas = tuple(BlockMeta(b, sz, len(self.catalog.frames(b)))
                          for b, sz in blocks)
            return encode_message(MetadataResponse(msg.req_id, metas))
        if isinstance(msg, TransferRequest):
            # reply-to identity rides in the request (socket transport) or
            # via the in-process note_reply_to side channel (mock tests)
            peer = msg.reply_to or None
            if peer is None:
                with self._lock:
                    peer = self._reply_to.pop(msg.req_id, None)
            if peer is None:
                return encode_message(TransferResponse(
                    msg.req_id, False, "unknown reply-to peer"))
            try:
                self._send_blocks(msg, peer)
                return encode_message(TransferResponse(msg.req_id, True))
            except Exception as e:    # noqa: BLE001 - to the client as nack
                return encode_message(TransferResponse(msg.req_id, False,
                                                       str(e)))
        raise ValueError(f"server cannot handle {type(msg).__name__}")

    def handle_data(self, header: bytes, payload: bytes) -> None:
        raise ValueError("server does not accept data frames")

    # -- server internals ---------------------------------------------------
    def note_reply_to(self, req_id: int, peer_executor_id: str) -> None:
        """In-process stand-in for the transport's channel peer identity."""
        with self._lock:
            self._reply_to[req_id] = peer_executor_id

    def _send_blocks(self, msg: TransferRequest, peer: str) -> None:
        state = BufferSendState(msg.req_id, msg.blocks, self.catalog,
                                self.bounce)
        conn = self.transport.connect(peer)
        while not state.done:
            state.send_next(conn)
        from spark_rapids_tpu.aux.events import emit
        emit("shuffleSend", peer=peer, req_id=msg.req_id,
             blocks=len(msg.blocks), frames=len(state.frames),
             bytes=sum(len(f[3]) for f in state.frames))


class ShuffleClient:
    """Fetches blocks from peer executors (reference: RapidsShuffleClient).

    One instance per executor; receives data frames via the transport
    handler interface and reassembles them into the received catalog."""

    #: max wait for in-flight data frames after a transfer ack
    data_timeout_s = 30.0

    def __init__(self, executor_id: str, transport,
                 received: Optional[ShuffleReceivedBufferCatalog] = None):
        self.executor_id = executor_id
        self.transport = transport
        self.received = received or ShuffleReceivedBufferCatalog()
        self._req_counter = 0
        self._lock = threading.Lock()
        self._pending: Dict[int, Dict] = {}
        self._partial: Dict = {}        # (req, block, frame) -> bytearray
        self._partial_got: Dict = {}

    def _next_req(self) -> int:
        with self._lock:
            self._req_counter += 1
            return self._req_counter

    # -- transport handler interface (data plane) ---------------------------
    def handle_request(self, message: bytes) -> bytes:
        raise ValueError("client does not serve requests")

    def handle_data(self, header: bytes, payload: bytes) -> None:
        h = decode_message(header)
        if not isinstance(h, BlockFrameHeader):
            raise ValueError("client expected a BlockFrameHeader")
        if len(payload) != h.nbytes:
            raise ValueError(
                f"chunk length mismatch: header {h.nbytes}, got "
                f"{len(payload)}")
        total = h.total_bytes or h.nbytes
        key = (h.req_id, h.block, h.frame_index)
        with self._lock:
            buf = self._partial.get(key)
            if buf is None:
                buf = self._partial[key] = bytearray(total)
                self._partial_got[key] = 0
            buf[h.chunk_offset:h.chunk_offset + h.nbytes] = payload
            self._partial_got[key] += h.nbytes
            if self._partial_got[key] < total:
                return
            frame = bytes(self._partial.pop(key))
            self._partial_got.pop(key)
            st = self._pending.get(h.req_id)
            if st is not None:
                st["frames"] += 1
        self.received.add_frame(h.block, frame)

    # -- fetch flow ---------------------------------------------------------
    @staticmethod
    def _peer_id(server_or_peer) -> str:
        return server_or_peer if isinstance(server_or_peer, str) \
            else server_or_peer.executor_id

    def fetch_metadata(self, server_or_peer, shuffle_id: int,
                       partition_id: int) -> MetadataResponse:
        req = MetadataRequest(self._next_req(), shuffle_id, partition_id)
        conn = self.transport.connect(self._peer_id(server_or_peer))
        txn = conn.request(encode_message(req)).wait()
        if txn.status is not TransactionStatus.SUCCESS:
            raise ConnectionError(f"metadata fetch failed: "
                                  f"{txn.error_message}")
        resp = decode_message(txn.response)
        assert isinstance(resp, MetadataResponse)
        return resp

    def do_fetch(self, server_or_peer, shuffle_id: int,
                 partition_id: int) -> List[ShuffleBlockId]:
        """Full fetch of one reduce partition from one peer (a local
        ShuffleServer or a remote peer's executor id); returns the fetched
        block ids (frames land in self.received)."""
        meta = self.fetch_metadata(server_or_peer, shuffle_id, partition_id)
        if not meta.blocks:
            return []
        req_id = self._next_req()
        with self._lock:
            self._pending[req_id] = {"frames": 0}
        try:
            expected = sum(m.num_frames for m in meta.blocks)
            treq = TransferRequest(req_id,
                                   tuple(m.block for m in meta.blocks),
                                   reply_to=self.executor_id)
            if not isinstance(server_or_peer, str):
                server_or_peer.note_reply_to(req_id, self.executor_id)
            conn = self.transport.connect(self._peer_id(server_or_peer))
            txn = conn.request(encode_message(treq)).wait()
            if txn.status is not TransactionStatus.SUCCESS:
                raise ConnectionError(
                    f"transfer failed: {txn.error_message}")
            resp = decode_message(txn.response)
            if not (isinstance(resp, TransferResponse) and resp.ok):
                raise ConnectionError(
                    f"transfer rejected: {getattr(resp, 'detail', '?')}")
            # over a real transport the response races the data channel:
            # frames may still be in flight when the ack lands
            import time as _time
            deadline = _time.monotonic() + self.data_timeout_s
            while True:
                with self._lock:
                    got = self._pending[req_id]["frames"]
                if got >= expected:
                    break
                if _time.monotonic() > deadline:
                    raise ConnectionError(
                        f"short transfer: {got}/{expected} frames")
                _time.sleep(0.005)
            from spark_rapids_tpu.aux.events import emit
            emit("shuffleFetch", peer=self._peer_id(server_or_peer),
                 shuffle_id=shuffle_id, partition=partition_id,
                 blocks=len(meta.blocks), frames=expected,
                 bytes=sum(m.nbytes for m in meta.blocks))
            return [m.block for m in meta.blocks]
        finally:
            # error or success: release tracking + any partial chunks so a
            # flaky peer cannot grow client state unboundedly
            with self._lock:
                self._pending.pop(req_id, None)
                stale = [k for k in self._partial if k[0] == req_id]
                for k in stale:
                    self._partial.pop(k, None)
                    self._partial_got.pop(k, None)
