"""Shuffle client/server state machines.

Reference: RapidsShuffleClient.scala (481 — doFetch: MetadataRequest ->
TransferRequest -> receive into bounce buffers -> reassemble) and
RapidsShuffleServer.scala (450 — BufferSendState drains blocks through
bounce buffers).  The flow is the reference's, byte-for-byte simpler:

  client                          server
    |--- MetadataRequest ---------->|   (which blocks exist for partition)
    |<-- MetadataResponse ----------|
    |--- TransferRequest ----------->|  (start sending block set)
    |<== BlockFrameHeader + bytes ==|  (windowed via bounce buffers)
    |<-- TransferResponse ----------|
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from spark_rapids_tpu.shuffle.catalog import (ShuffleBlockId,
                                              ShuffleBufferCatalog,
                                              ShuffleReceivedBufferCatalog)
from spark_rapids_tpu.shuffle.protocol import (BlockFrameHeader, BlockMeta,
                                               MetadataRequest,
                                               MetadataResponse,
                                               TransferRequest,
                                               TransferResponse,
                                               decode_message, encode_message)
from spark_rapids_tpu.shuffle.transport import (BounceBufferManager,
                                                Connection,
                                                TransactionStatus)


class ShuffleFetchFailed(ConnectionError):
    """A reduce partition could not be fetched after exhausting retries
    and failover peers (the FetchFailedException analog): carries enough
    lineage identity for the exchange to re-run the producing map tasks."""

    def __init__(self, shuffle_id: int, partition_id: int, peer: str,
                 cause: str):
        super().__init__(
            f"fetch failed: shuffle {shuffle_id} partition {partition_id} "
            f"from {peer!r}: {cause}")
        self.shuffle_id = shuffle_id
        self.partition_id = partition_id
        self.peer = peer
        self.cause = cause


@dataclasses.dataclass
class FetchRetryPolicy:
    """Client-side fetch resilience knobs (conf: the
    ``spark.rapids.shuffle.fetch.*`` keys; ShuffleEnv materializes one per
    session).  Backoff doubles per attempt with deterministic jitter —
    attempt k of request r waits ``base * 2**k`` perturbed by a hash of
    (r, k), capped at ``max_wait_s`` — so chaos tests replay identically."""

    timeout_s: float = 30.0       # per-attempt data-frame wait
    max_retries: int = 3          # attempts beyond the first, per peer
    base_wait_s: float = 0.05
    max_wait_s: float = 2.0

    @staticmethod
    def from_conf(conf) -> "FetchRetryPolicy":
        from spark_rapids_tpu import config as C
        return FetchRetryPolicy(
            timeout_s=conf.get(C.SHUFFLE_FETCH_TIMEOUT_MS.key) / 1000.0,
            max_retries=conf.get(C.SHUFFLE_FETCH_MAX_RETRIES.key),
            base_wait_s=conf.get(C.SHUFFLE_FETCH_RETRY_WAIT_MS.key) / 1000.0,
            max_wait_s=conf.get(
                C.SHUFFLE_FETCH_RETRY_MAX_WAIT_MS.key) / 1000.0)

    def backoff_s(self, req_id: int, attempt: int) -> float:
        base = min(self.base_wait_s * (2 ** attempt), self.max_wait_s)
        # deterministic jitter in [0.5, 1.0) x base: decorrelates peers
        # retrying in lockstep without wall-clock/PRNG nondeterminism
        frac = 0.5 + (hash((req_id, attempt)) % 1024) / 2048.0
        return base * frac


class BufferSendState:
    """Server-side per-transfer cursor: drains the requested blocks through
    bounce buffers window by window (reference: BufferSendState in
    RapidsShuffleServer.scala)."""

    def __init__(self, req_id: int, blocks: Sequence[ShuffleBlockId],
                 catalog: ShuffleBufferCatalog,
                 bounce: BounceBufferManager):
        self.req_id = req_id
        self.catalog = catalog
        self.bounce = bounce
        # flatten every frame of every block (frame = one serialized batch)
        self.frames: List[Tuple[ShuffleBlockId, int, int, bytes]] = []
        for b in blocks:
            fr = catalog.frames(b)
            for i, f in enumerate(fr):
                self.frames.append((b, i, len(fr), f))
        self._idx = 0

    @property
    def done(self) -> bool:
        return self._idx >= len(self.frames)

    def send_next(self, conn: Connection) -> None:
        """Sends one frame as bounce-buffer-sized CHUNKS, each its own
        data-plane send — at most one bounce buffer of this frame is in
        flight at a time (real windowing/backpressure; the receiver
        reassembles by chunk offset)."""
        block, fi, fc, frame = self.frames[self._idx]
        self._idx += 1
        total = len(frame)
        sent = 0
        while sent < total or sent == 0:
            buf = self.bounce.acquire()
            try:
                take = min(self.bounce.buffer_size, total - sent)
                buf.data[:take] = frame[sent:sent + take]
                header = BlockFrameHeader(self.req_id, block, fi, fc,
                                          take, sent, total)
                txn = conn.send_data(encode_message(header),
                                     bytes(buf.data[:take]))
                txn.wait()
            finally:
                buf.close()
            if txn.status is not TransactionStatus.SUCCESS:
                raise ConnectionError(f"send failed: {txn.error_message}")
            sent += take
            if total == 0:
                break


class ShuffleServer:
    """Serves one executor's map output (reference: RapidsShuffleServer).

    Registered as the transport handler for this executor id; replies to
    control messages and pushes data frames back over the requesting
    connection."""

    def __init__(self, executor_id: str, catalog: ShuffleBufferCatalog,
                 transport, bounce: Optional[BounceBufferManager] = None):
        self.executor_id = executor_id
        self.catalog = catalog
        self.transport = transport
        self.bounce = bounce or BounceBufferManager()
        self._reply_to: Dict[int, str] = {}
        self._lock = threading.Lock()

    # -- transport handler interface ----------------------------------------
    def handle_request(self, message: bytes) -> bytes:
        msg = decode_message(message)
        if isinstance(msg, MetadataRequest):
            blocks = self.catalog.block_sizes(msg.shuffle_id,
                                              msg.partition_id)
            metas = tuple(BlockMeta(b, sz, len(self.catalog.frames(b)))
                          for b, sz in blocks)
            return encode_message(MetadataResponse(msg.req_id, metas))
        if isinstance(msg, TransferRequest):
            # reply-to identity rides in the request (socket transport) or
            # via the in-process note_reply_to side channel (mock tests)
            peer = msg.reply_to or None
            if peer is None:
                with self._lock:
                    peer = self._reply_to.pop(msg.req_id, None)
            if peer is None:
                return encode_message(TransferResponse(
                    msg.req_id, False, "unknown reply-to peer"))
            try:
                self._send_blocks(msg, peer)
                return encode_message(TransferResponse(msg.req_id, True))
            except Exception as e:    # noqa: BLE001 - to the client as nack
                return encode_message(TransferResponse(msg.req_id, False,
                                                       str(e)))
        raise ValueError(f"server cannot handle {type(msg).__name__}")

    def handle_data(self, header: bytes, payload: bytes) -> None:
        raise ValueError("server does not accept data frames")

    # -- server internals ---------------------------------------------------
    def note_reply_to(self, req_id: int, peer_executor_id: str) -> None:
        """In-process stand-in for the transport's channel peer identity."""
        with self._lock:
            self._reply_to[req_id] = peer_executor_id

    def _send_blocks(self, msg: TransferRequest, peer: str) -> None:
        from spark_rapids_tpu.aux.faults import maybe_fire
        maybe_fire("shuffle.send")
        state = BufferSendState(msg.req_id, msg.blocks, self.catalog,
                                self.bounce)
        conn = self.transport.connect(peer)
        while not state.done:
            state.send_next(conn)
        from spark_rapids_tpu.aux.events import emit
        emit("shuffleSend", peer=peer, req_id=msg.req_id,
             blocks=len(msg.blocks), frames=len(state.frames),
             bytes=sum(len(f[3]) for f in state.frames))


class ShuffleClient:
    """Fetches blocks from peer executors (reference: RapidsShuffleClient).

    One instance per executor; receives data frames via the transport
    handler interface and reassembles them into the received catalog.
    Transient failures (dropped frames, peer restarts, injected chaos)
    retry with bounded exponential backoff per the ``FetchRetryPolicy``;
    exhausted peers fail over to alternates before surfacing a
    ``ShuffleFetchFailed`` for the lineage layer."""

    def __init__(self, executor_id: str, transport,
                 received: Optional[ShuffleReceivedBufferCatalog] = None,
                 retry: Optional[FetchRetryPolicy] = None):
        self.executor_id = executor_id
        self.transport = transport
        self.received = received or ShuffleReceivedBufferCatalog()
        self.retry = retry or FetchRetryPolicy()
        self._req_counter = 0
        self._lock = threading.Lock()
        self._pending: Dict[int, Dict] = {}
        self._partial: Dict = {}        # (req, block, frame) -> bytearray
        self._partial_got: Dict = {}

    @property
    def data_timeout_s(self) -> float:
        """Per-attempt wait for in-flight data frames after a transfer ack
        (the policy is the single source of truth — was a hardcoded class
        attribute before the conf-driven FetchRetryPolicy)."""
        return self.retry.timeout_s

    def _next_req(self) -> int:
        with self._lock:
            self._req_counter += 1
            return self._req_counter

    # -- transport handler interface (data plane) ---------------------------
    def handle_request(self, message: bytes) -> bytes:
        raise ValueError("client does not serve requests")

    def handle_data(self, header: bytes, payload: bytes) -> None:
        h = decode_message(header)
        if not isinstance(h, BlockFrameHeader):
            raise ValueError("client expected a BlockFrameHeader")
        if len(payload) != h.nbytes:
            raise ValueError(
                f"chunk length mismatch: header {h.nbytes}, got "
                f"{len(payload)}")
        total = h.total_bytes or h.nbytes
        key = (h.req_id, h.block, h.frame_index)
        with self._lock:
            if h.req_id not in self._pending:
                # late frame of a request that already timed out/failed:
                # registering it would combine with the RETRY's frames
                # and duplicate rows (and stale _partial chunks would
                # accrete forever) — drop it on the floor
                return
            buf = self._partial.get(key)
            if buf is None:
                buf = self._partial[key] = bytearray(total)
                self._partial_got[key] = 0
            buf[h.chunk_offset:h.chunk_offset + h.nbytes] = payload
            self._partial_got[key] += h.nbytes
            if self._partial_got[key] < total:
                return
            frame = bytes(self._partial.pop(key))
            self._partial_got.pop(key)
            self._pending[h.req_id]["frames"] += 1
            # registered under the SAME lock hold as the pending check:
            # the attempt's failure cleanup (which drops these blocks)
            # serializes against us, so a frame is either visible to
            # that cleanup or rejected at entry — never added late
            self.received.add_frame(h.block, frame)

    # -- fetch flow ---------------------------------------------------------
    @staticmethod
    def _peer_id(server_or_peer) -> str:
        return server_or_peer if isinstance(server_or_peer, str) \
            else server_or_peer.executor_id

    def fetch_metadata(self, server_or_peer, shuffle_id: int,
                       partition_id: int) -> MetadataResponse:
        req = MetadataRequest(self._next_req(), shuffle_id, partition_id)
        conn = self.transport.connect(self._peer_id(server_or_peer))
        txn = conn.request(encode_message(req)).wait()
        if txn.status is not TransactionStatus.SUCCESS:
            raise ConnectionError(f"metadata fetch failed: "
                                  f"{txn.error_message}")
        resp = decode_message(txn.response)
        assert isinstance(resp, MetadataResponse)
        return resp

    def do_fetch(self, server_or_peer, shuffle_id: int,
                 partition_id: int,
                 alternates: Sequence = ()) -> List[ShuffleBlockId]:
        """Full fetch of one reduce partition (retry + failover wrapper
        around ``_do_fetch_once``): transient errors retry against the
        same peer with backoff; a peer that exhausts its attempt budget
        fails over to the next candidate in ``alternates`` (a restarted
        or replica executor the heartbeat layer re-registered).  Raises
        ``ShuffleFetchFailed`` when every candidate is exhausted — the
        signal the exchange's lineage recovery consumes."""
        from spark_rapids_tpu.aux.events import emit
        from spark_rapids_tpu.aux.faults import note_recovery
        policy = self.retry
        candidates = [server_or_peer, *alternates]
        last_error = "?"
        for ci, cand in enumerate(candidates):
            peer = self._peer_id(cand)
            if ci > 0:
                note_recovery("fetch_failovers")
                emit("fetchFailover",
                     from_peer=self._peer_id(candidates[ci - 1]),
                     to_peer=peer, shuffle_id=shuffle_id,
                     partition=partition_id)
            for attempt in range(policy.max_retries + 1):
                from spark_rapids_tpu.aux.faults import maybe_fire
                try:
                    maybe_fire("shuffle.fetch")
                    return self._do_fetch_once(cand, shuffle_id,
                                               partition_id)
                except (ConnectionError, TimeoutError) as e:
                    # TimeoutError: a transport wait expired (dead peer /
                    # exhausted bounce buffers) — retryable exactly like
                    # a dropped connection
                    last_error = f"{type(e).__name__}: {e}"
                    if attempt >= policy.max_retries:
                        break
                    wait = policy.backoff_s(self._req_counter, attempt)
                    note_recovery("fetch_retries")
                    emit("fetchRetry", peer=peer, shuffle_id=shuffle_id,
                         partition=partition_id, attempt=attempt + 1,
                         wait_ms=round(wait * 1000, 3),
                         error=last_error[:160])
                    if wait > 0:
                        time.sleep(wait)
        raise ShuffleFetchFailed(shuffle_id, partition_id,
                                 self._peer_id(candidates[-1]), last_error)

    def _do_fetch_once(self, server_or_peer, shuffle_id: int,
                       partition_id: int) -> List[ShuffleBlockId]:
        """One fetch attempt of one reduce partition from one peer (a
        local ShuffleServer or a remote peer's executor id); returns the
        fetched block ids (frames land in self.received)."""
        meta = self.fetch_metadata(server_or_peer, shuffle_id, partition_id)
        if not meta.blocks:
            return []
        req_id = self._next_req()
        with self._lock:
            self._pending[req_id] = {"frames": 0}
        try:
            return self._transfer(server_or_peer, shuffle_id, partition_id,
                                  meta, req_id)
        except BaseException:
            # an attempt is all-or-nothing: frames already reassembled
            # into the received catalog would DUPLICATE on retry
            for m in meta.blocks:
                self.received.drop(m.block)
            raise

    def _transfer(self, server_or_peer, shuffle_id: int, partition_id: int,
                  meta: MetadataResponse, req_id: int):
        try:
            expected = sum(m.num_frames for m in meta.blocks)
            treq = TransferRequest(req_id,
                                   tuple(m.block for m in meta.blocks),
                                   reply_to=self.executor_id)
            if not isinstance(server_or_peer, str):
                server_or_peer.note_reply_to(req_id, self.executor_id)
            conn = self.transport.connect(self._peer_id(server_or_peer))
            txn = conn.request(encode_message(treq)).wait()
            if txn.status is not TransactionStatus.SUCCESS:
                raise ConnectionError(
                    f"transfer failed: {txn.error_message}")
            resp = decode_message(txn.response)
            if not (isinstance(resp, TransferResponse) and resp.ok):
                raise ConnectionError(
                    f"transfer rejected: {getattr(resp, 'detail', '?')}")
            # over a real transport the response races the data channel:
            # frames may still be in flight when the ack lands
            import time as _time
            deadline = _time.monotonic() + self.data_timeout_s
            while True:
                with self._lock:
                    got = self._pending[req_id]["frames"]
                if got >= expected:
                    break
                if _time.monotonic() > deadline:
                    raise ConnectionError(
                        f"short transfer: {got}/{expected} frames")
                _time.sleep(0.005)
            from spark_rapids_tpu.aux.events import emit
            emit("shuffleFetch", peer=self._peer_id(server_or_peer),
                 shuffle_id=shuffle_id, partition=partition_id,
                 blocks=len(meta.blocks), frames=expected,
                 bytes=sum(m.nbytes for m in meta.blocks))
            return [m.block for m in meta.blocks]
        finally:
            # error or success: release tracking + any partial chunks so a
            # flaky peer cannot grow client state unboundedly
            with self._lock:
                self._pending.pop(req_id, None)
                stale = [k for k in self._partial if k[0] == req_id]
                for k in stale:
                    self._partial.pop(k, None)
                    self._partial_got.pop(k, None)
