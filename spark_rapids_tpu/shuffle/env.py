"""Shuffle environment: mode selection + shared machinery per session.

Reference: GpuShuffleEnv.scala (:186 — picks default / MULTITHREADED / UCX
mode from conf and owns the shuffle-wide singletons) wired from executor
init (Plugin.scala:550-557).
"""

from __future__ import annotations

import threading
from concurrent.futures import ThreadPoolExecutor
from typing import Optional

from spark_rapids_tpu import config as C


class ShuffleEnv:
    """Owns the per-session shuffle machinery according to
    ``spark.rapids.shuffle.mode``:

    - DEFAULT:       in-exec host store (exchange.py's store)
    - MULTITHREADED: threaded writer/reader over spill files
    - CACHED:        catalog + client/server over the in-process transport
                     (the UCX-mode architecture; a DCN transport slots in)
    """

    MODES = ("DEFAULT", "MULTITHREADED", "CACHED")
    CODECS = ("none", "lz4", "zlib")

    def __init__(self, conf):
        mode = conf.get(C.SHUFFLE_MANAGER_MODE.key).upper()
        if mode == "CACHE_ONLY":      # reference naming
            mode = "CACHED"
        if mode not in self.MODES:
            raise ValueError(
                f"unknown {C.SHUFFLE_MANAGER_MODE.key}={mode!r} "
                f"(supported: {', '.join(self.MODES)} or CACHE_ONLY)")
        self.mode = mode
        self.codec = conf.get(C.SHUFFLE_COMPRESSION_CODEC.key).lower()
        if self.codec not in self.CODECS:
            raise ValueError(
                f"unknown {C.SHUFFLE_COMPRESSION_CODEC.key}="
                f"{self.codec!r} (supported: {', '.join(self.CODECS)})")
        self.writer_threads = int(conf.get(C.SHUFFLE_WRITER_THREADS.key))
        self.reader_threads = int(conf.get(C.SHUFFLE_READER_THREADS.key))
        # fetch resilience knobs (spark.rapids.shuffle.fetch.*): one
        # policy per session, handed to every client this env creates
        from spark_rapids_tpu.shuffle.client_server import FetchRetryPolicy
        self.fetch_retry = FetchRetryPolicy.from_conf(conf)
        self._apply_transport_timeout(conf)
        self._dir = None
        self._atexit_registered = False
        self._lock = threading.Lock()
        self._writer_pool: Optional[ThreadPoolExecutor] = None
        self._reader_pool: Optional[ThreadPoolExecutor] = None
        self._catalog = None
        self._transport = None
        self._client = None
        self._server = None
        self._hb_manager = None
        self._shuffle_counter = 0

    def next_shuffle_id(self) -> int:
        with self._lock:
            self._shuffle_counter += 1
            return self._shuffle_counter

    def heartbeat_manager(self, timeout_s: float = 60.0):
        """The session's driver-side liveness registry, pre-wired so
        heartbeat expiry invalidates the dead executor's blocks in this
        env's shuffle catalog (the FetchFailed-style invalidation feeding
        the exchange's lineage recovery).  Deployments that assemble
        their own manager/catalog pair must wire
        ``manager.add_expiry_listener(catalog.drop_owner)`` themselves —
        this accessor is where the engine does it."""
        from spark_rapids_tpu.shuffle.heartbeat import \
            ShuffleHeartbeatManager
        with self._lock:
            if self._hb_manager is None:
                mgr = ShuffleHeartbeatManager(timeout_s=timeout_s)

                def drop_dead_blocks(eid: str) -> None:
                    cat = self._catalog    # may register after the mgr
                    if cat is not None:
                        cat.drop_owner(eid)

                mgr.add_expiry_listener(drop_dead_blocks)
                self._hb_manager = mgr
            return self._hb_manager

    @staticmethod
    def _apply_transport_timeout(conf) -> None:
        """Bounds the otherwise-unbounded transport waits
        (``Transaction.wait(None)`` / bounce-buffer ``acquire(None)``)
        from ``spark.rapids.shuffle.transport.timeoutMs``: a dead peer
        surfaces as a retryable TimeoutError through the fetch-retry
        policy instead of pinning a sender thread forever."""
        from spark_rapids_tpu.shuffle import transport as _T
        _T.DEFAULT_WAIT_TIMEOUT_S = \
            conf.get(C.SHUFFLE_TRANSPORT_TIMEOUT_MS.key) / 1000.0

    def update_fetch_retry(self, conf) -> None:
        """Re-reads the spark.rapids.shuffle.fetch.* / transport.* keys
        (set_conf after session init must take effect, not just validate)
        and pushes the new policy into the already-created client, if
        any."""
        from spark_rapids_tpu.shuffle.client_server import FetchRetryPolicy
        policy = FetchRetryPolicy.from_conf(conf)
        self._apply_transport_timeout(conf)
        with self._lock:
            self.fetch_retry = policy
            if self._client is not None:
                self._client.retry = policy

    @property
    def shuffle_dir(self) -> str:
        """One spill directory per env, removed at shutdown (the reference
        parks shuffle files under Spark's block-manager dirs, which Spark
        cleans up the same way).  Sessions left unstopped are swept at
        interpreter exit."""
        import atexit
        import tempfile
        with self._lock:
            if self._dir is None:
                self._dir = tempfile.mkdtemp(prefix="tpu_shuffle_")
                if not self._atexit_registered:
                    self._atexit_registered = True
                    atexit.register(self.shutdown)
            return self._dir

    @property
    def writer_pool(self) -> ThreadPoolExecutor:
        with self._lock:
            if self._writer_pool is None:
                self._writer_pool = ThreadPoolExecutor(
                    max_workers=max(1, self.writer_threads),
                    thread_name_prefix="shuffle-writer")
            return self._writer_pool

    @property
    def reader_pool(self) -> ThreadPoolExecutor:
        with self._lock:
            if self._reader_pool is None:
                self._reader_pool = ThreadPoolExecutor(
                    max_workers=max(1, self.reader_threads),
                    thread_name_prefix="shuffle-reader")
            return self._reader_pool

    # -- CACHED (transport) mode singletons ---------------------------------
    def cached_machinery(self):
        """(catalog, client, server) for the single in-process executor."""
        from spark_rapids_tpu.shuffle.catalog import (
            ShuffleBufferCatalog, ShuffleReceivedBufferCatalog)
        from spark_rapids_tpu.shuffle.client_server import (ShuffleClient,
                                                            ShuffleServer)
        from spark_rapids_tpu.shuffle.transport import InProcessTransport
        with self._lock:
            if self._catalog is None:
                self._catalog = ShuffleBufferCatalog(self.codec)
                self._transport = InProcessTransport()
                self._server = ShuffleServer("exec-0", self._catalog,
                                             self._transport)
                self._client = ShuffleClient("exec-0-client",
                                             self._transport,
                                             retry=self.fetch_retry)
                self._transport.register_handler("exec-0", self._server)
                self._transport.register_handler("exec-0-client",
                                                 self._client)
            return self._catalog, self._client, self._server

    def shutdown(self):
        import shutil
        with self._lock:
            if self._writer_pool is not None:
                self._writer_pool.shutdown(wait=False)
                self._writer_pool = None   # lazily recreated if reused
            if self._reader_pool is not None:
                self._reader_pool.shutdown(wait=False)
                self._reader_pool = None
            if self._dir is not None:
                shutil.rmtree(self._dir, ignore_errors=True)
                self._dir = None


_ACTIVE: Optional[ShuffleEnv] = None
_ACTIVE_LOCK = threading.Lock()


def init_shuffle_env(conf) -> ShuffleEnv:
    global _ACTIVE
    with _ACTIVE_LOCK:
        _ACTIVE = ShuffleEnv(conf)
        return _ACTIVE


def get_shuffle_env() -> Optional[ShuffleEnv]:
    return _ACTIVE
