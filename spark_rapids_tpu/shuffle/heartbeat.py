"""Executor liveness for the accelerated shuffle.

Reference: RapidsShuffleHeartbeatManager.scala (234 — driver-side registry;
executors register + heartbeat via plugin RPC, Plugin.scala:436-447) and
RapidsShuffleHeartbeatEndpoint (executor side).  New peers are disseminated
through heartbeat responses; lost peers age out and their blocks surface as
fetch failures, which the engine's normal stage retry handles (no custom
elastic layer — SURVEY.md §5 failure detection)."""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Callable, Dict, List, Optional


@dataclasses.dataclass
class ExecutorInfo:
    executor_id: str
    endpoint: str                 # opaque transport address
    last_heartbeat: float
    registration_order: int


class ShuffleHeartbeatManager:
    """Driver-side registry (reference: RapidsShuffleHeartbeatManager).

    register() returns every known peer; heartbeat() returns peers that
    appeared since the caller last asked (the reference's delta protocol)."""

    def __init__(self, timeout_s: float = 60.0,
                 clock: Callable[[], float] = time.monotonic):
        self._timeout = timeout_s
        self._clock = clock
        self._lock = threading.Lock()
        self._executors: Dict[str, ExecutorInfo] = {}
        self._order = 0
        self._last_seen_order: Dict[str, int] = {}
        #: called with each expired executor id (shuffle catalogs register
        #: here to invalidate the dead executor's blocks — the
        #: FetchFailed-style invalidation feeding lineage recovery)
        self._expiry_listeners: List[Callable[[str], None]] = []

    def add_expiry_listener(self, cb: Callable[[str], None]) -> None:
        with self._lock:
            self._expiry_listeners.append(cb)

    def register_executor(self, executor_id: str,
                          endpoint: str = "") -> List[ExecutorInfo]:
        with self._lock:
            self._order += 1
            self._executors[executor_id] = ExecutorInfo(
                executor_id, endpoint, self._clock(), self._order)
            self._last_seen_order[executor_id] = self._order
            peers = [e for e in self._sorted()
                     if e.executor_id != executor_id]
        from spark_rapids_tpu.aux.events import emit
        emit("executorRegistered", executor_id=executor_id,
             peers=len(peers))
        return peers

    def executor_heartbeat(self, executor_id: str) -> List[ExecutorInfo]:
        """Refreshes liveness; returns peers registered since this
        executor's last call (delta dissemination)."""
        with self._lock:
            info = self._executors.get(executor_id)
            if info is None:
                raise KeyError(f"executor {executor_id!r} never registered")
            info.last_heartbeat = self._clock()
            seen = self._last_seen_order.get(executor_id, 0)
            self._last_seen_order[executor_id] = self._order
            return [e for e in self._sorted()
                    if e.registration_order > seen
                    and e.executor_id != executor_id]

    def expire_dead(self) -> List[str]:
        """Drops executors whose heartbeat aged out; returns their ids.
        Each expiry emits a ``workerExpired`` event (plus the legacy
        ``executorLost``) and notifies expiry listeners so shuffle
        catalogs can drop the dead executor's blocks."""
        now = self._clock()
        with self._lock:
            dead = [eid for eid, e in self._executors.items()
                    if now - e.last_heartbeat > self._timeout]
            for eid in dead:
                del self._executors[eid]
                self._last_seen_order.pop(eid, None)
            listeners = list(self._expiry_listeners)
        from spark_rapids_tpu.aux.events import emit
        from spark_rapids_tpu.aux.faults import note_recovery
        for eid in dead:
            note_recovery("workers_expired")
            emit("workerExpired", executor_id=eid,
                 timeout_s=self._timeout)
            emit("executorLost", executor_id=eid)
            for cb in listeners:
                try:
                    cb(eid)
                except Exception:   # noqa: BLE001 - one bad listener
                    import logging  # must not block liveness accounting
                    logging.getLogger(__name__).exception(
                        "shuffle expiry listener failed for %s", eid)
        return dead

    def live_executors(self) -> List[ExecutorInfo]:
        with self._lock:
            return self._sorted()

    def _sorted(self) -> List[ExecutorInfo]:
        return sorted(self._executors.values(),
                      key=lambda e: e.registration_order)


class ExecutorHeartbeatEndpoint:
    """Executor-side loop driving registration + periodic heartbeats
    (reference: RapidsShuffleHeartbeatEndpoint).  ``on_new_peer`` wires
    discovered peers into the local client's connection table."""

    def __init__(self, executor_id: str, manager: ShuffleHeartbeatManager,
                 on_new_peer: Optional[Callable[[ExecutorInfo], None]] = None):
        self.executor_id = executor_id
        self.manager = manager
        self.on_new_peer = on_new_peer
        self.known_peers: Dict[str, ExecutorInfo] = {}

    def register(self) -> None:
        for peer in self.manager.register_executor(self.executor_id):
            self._add_peer(peer)

    def heartbeat(self) -> None:
        for peer in self.manager.executor_heartbeat(self.executor_id):
            self._add_peer(peer)

    def _add_peer(self, peer: ExecutorInfo) -> None:
        if peer.executor_id not in self.known_peers:
            self.known_peers[peer.executor_id] = peer
            if self.on_new_peer is not None:
                self.on_new_peer(peer)
