"""Shuffle control-plane wire protocol.

Reference: the flatbuffers schemas in sql-plugin/src/main/format/
(ShuffleCommon.fbs, ShuffleMetadataRequest/Response.fbs,
ShuffleTransferRequest/Response.fbs).  Same message shapes, packed with
``struct`` instead of flatbuffers (one fixed header + length-prefixed
fields — no schema compiler needed and the layout stays inspectable).
"""

from __future__ import annotations

import dataclasses
import struct
from typing import List, Tuple

from spark_rapids_tpu.shuffle.catalog import ShuffleBlockId

_MAGIC = b"TSHF"
_MSG_TYPES = {}


def _register(code):
    def deco(cls):
        cls.code = code
        _MSG_TYPES[code] = cls
        return cls
    return deco


@dataclasses.dataclass(frozen=True)
class BlockMeta:
    """One fetchable block: identity + payload size + frame count."""
    block: ShuffleBlockId
    nbytes: int
    num_frames: int

    def pack(self) -> bytes:
        return struct.pack("<qqqqq", self.block.shuffle_id,
                           self.block.map_id, self.block.partition_id,
                           self.nbytes, self.num_frames)

    @staticmethod
    def unpack(buf: memoryview) -> "BlockMeta":
        s, m, p, nb, nf = struct.unpack_from("<qqqqq", buf)
        return BlockMeta(ShuffleBlockId(s, m, p), nb, nf)

    SIZE = 40


@_register(1)
@dataclasses.dataclass(frozen=True)
class MetadataRequest:
    """Which blocks exist for (shuffle, reduce partition)? (reference:
    ShuffleMetadataRequest.fbs)"""
    req_id: int
    shuffle_id: int
    partition_id: int

    def pack_body(self) -> bytes:
        return struct.pack("<qqq", self.req_id, self.shuffle_id,
                           self.partition_id)

    @staticmethod
    def unpack_body(buf: memoryview) -> "MetadataRequest":
        return MetadataRequest(*struct.unpack_from("<qqq", buf))


@_register(2)
@dataclasses.dataclass(frozen=True)
class MetadataResponse:
    req_id: int
    blocks: Tuple[BlockMeta, ...]

    def pack_body(self) -> bytes:
        out = [struct.pack("<qi", self.req_id, len(self.blocks))]
        for b in self.blocks:
            out.append(b.pack())
        return b"".join(out)

    @staticmethod
    def unpack_body(buf: memoryview) -> "MetadataResponse":
        req_id, n = struct.unpack_from("<qi", buf)
        off = 12
        blocks = []
        for _ in range(n):
            blocks.append(BlockMeta.unpack(buf[off:]))
            off += BlockMeta.SIZE
        return MetadataResponse(req_id, tuple(blocks))


@_register(3)
@dataclasses.dataclass(frozen=True)
class TransferRequest:
    """Start sending these blocks (reference: ShuffleTransferRequest.fbs).

    ``reply_to``: the requesting executor's id — over a real transport the
    server pushes data frames back by connecting to this peer (in-process
    tests may leave it empty and use the server's note_reply_to side
    channel instead)."""
    req_id: int
    blocks: Tuple[ShuffleBlockId, ...]
    reply_to: str = ""

    def pack_body(self) -> bytes:
        rt = self.reply_to.encode()
        out = [struct.pack("<qii", self.req_id, len(self.blocks), len(rt)),
               rt]
        for b in self.blocks:
            out.append(struct.pack("<qqq", b.shuffle_id, b.map_id,
                                   b.partition_id))
        return b"".join(out)

    @staticmethod
    def unpack_body(buf: memoryview) -> "TransferRequest":
        req_id, n, rt_len = struct.unpack_from("<qii", buf)
        off = 16
        reply_to = bytes(buf[off:off + rt_len]).decode()
        off += rt_len
        blocks = []
        for _ in range(n):
            s, m, p = struct.unpack_from("<qqq", buf, off)
            blocks.append(ShuffleBlockId(s, m, p))
            off += 24
        return TransferRequest(req_id, tuple(blocks), reply_to)


@_register(4)
@dataclasses.dataclass(frozen=True)
class TransferResponse:
    """Acknowledges a transfer; failure detail carried as status text."""
    req_id: int
    ok: bool
    detail: str = ""

    def pack_body(self) -> bytes:
        d = self.detail.encode()
        return struct.pack("<qBi", self.req_id, int(self.ok), len(d)) + d

    @staticmethod
    def unpack_body(buf: memoryview) -> "TransferResponse":
        req_id, ok, n = struct.unpack_from("<qBi", buf)
        d = bytes(buf[13:13 + n]).decode()
        return TransferResponse(req_id, bool(ok), d)


@_register(5)
@dataclasses.dataclass(frozen=True)
class BlockFrameHeader:
    """Precedes each data CHUNK on the data channel: which block/frame it
    belongs to, the chunk's byte range, and the frame's total size — one
    chunk per bounce-buffer window (reference: BufferSendState windows +
    BufferMeta in ShuffleCommon.fbs)."""
    req_id: int
    block: ShuffleBlockId
    frame_index: int
    frame_count: int
    nbytes: int            # bytes in THIS chunk
    chunk_offset: int = 0  # offset of this chunk within the frame
    total_bytes: int = 0   # full frame size (0 legacy = nbytes)

    def pack_body(self) -> bytes:
        return struct.pack("<qqqqiiqqq", self.req_id, self.block.shuffle_id,
                           self.block.map_id, self.block.partition_id,
                           self.frame_index, self.frame_count, self.nbytes,
                           self.chunk_offset, self.total_bytes)

    @staticmethod
    def unpack_body(buf: memoryview) -> "BlockFrameHeader":
        r, s, m, p, fi, fc, nb, co, tb = struct.unpack_from("<qqqqiiqqq",
                                                            buf)
        return BlockFrameHeader(r, ShuffleBlockId(s, m, p), fi, fc, nb,
                                co, tb)


def encode_message(msg) -> bytes:
    body = msg.pack_body()
    return _MAGIC + struct.pack("<Bi", msg.code, len(body)) + body


def decode_message(data: bytes):
    if data[:4] != _MAGIC:
        raise ValueError("bad shuffle message magic")
    code, n = struct.unpack_from("<Bi", data, 4)
    cls = _MSG_TYPES.get(code)
    if cls is None:
        raise ValueError(f"unknown shuffle message code {code}")
    body = memoryview(data)[9:9 + n]
    return cls.unpack_body(body)
