"""Columnar batch wire format.

Reference: JCudfSerialization (host columnar wire format used by the
default-mode shuffle serializer, GpuColumnarBatchSerializer.scala:127) +
TableCompressionCodec/NvcompLZ4CompressionCodec for compressed payloads.

Format: arrow IPC stream (the host columnar layout of this engine) with an
optional LZ4 frame (native/tpucol codec, crc-checked) around the bytes.
"""

from __future__ import annotations

import io
from typing import Optional

from spark_rapids_tpu.columnar.batch import HostColumnarBatch, batch_from_arrow


def serialize_batch(hb: HostColumnarBatch, codec: str = "none") -> bytes:
    import pyarrow as pa
    rb = hb.to_arrow()
    sink = io.BytesIO()
    with pa.ipc.new_stream(sink, rb.schema) as w:
        w.write_batch(rb)
    raw = sink.getvalue()
    if codec == "lz4":
        from spark_rapids_tpu.native import lz4_compress
        return b"\x01" + lz4_compress(raw)
    if codec == "zlib":
        import zlib
        return b"\x02" + zlib.compress(raw, 1)
    if codec in ("none", ""):
        return b"\x00" + raw
    raise ValueError(f"unknown shuffle codec {codec!r} "
                     "(supported: none, lz4, zlib)")


def deserialize_batch(data: bytes) -> HostColumnarBatch:
    import pyarrow as pa
    tag, payload = data[0], data[1:]
    if tag == 1:
        from spark_rapids_tpu.native import lz4_decompress
        payload = lz4_decompress(payload)
    elif tag == 2:
        import zlib
        payload = zlib.decompress(payload)
    elif tag != 0:
        raise ValueError(f"bad shuffle frame tag {tag}")
    with pa.ipc.open_stream(io.BytesIO(payload)) as r:
        tab = r.read_all()
    return batch_from_arrow(tab)
