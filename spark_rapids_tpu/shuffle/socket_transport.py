"""TCP socket transport for the accelerated shuffle.

Reference: the UCX transport (shuffle-plugin/.../UCX.scala:1119,
UCXShuffleTransport.scala, UCXConnection.scala) — listeners, endpoints and
active messages over RDMA.  The TPU build's cross-process data plane is
DCN/TCP (ICI collectives cover the in-slice path, parallel/collective.py);
this transport implements the same Transport/Connection SPI the
client/server state machines already run against, over real sockets:

- one listening endpoint per executor; every frame is
  ``[type u8][tag u64][header u32-len][payload u32-len][header][payload]``
- REQUEST frames dispatch to the registered server handler, the return
  value travels back as a RESPONSE with the same tag
- DATA frames dispatch to the registered client handler (the server pushes
  them by connecting back to the requester's endpoint, resolved through
  the peer table the heartbeat layer maintains)
- a dead peer surfaces as ConnectionError on connect/request — the
  fetch-failure signal the engine's retry layer consumes (reference:
  lost UCX peers produce fetch failures -> Spark stage retry)
"""

from __future__ import annotations

import socket
import struct
import threading
from typing import Callable, Dict, Optional, Tuple

from spark_rapids_tpu.shuffle.transport import (Connection, Transaction,
                                                TransactionStatus, Transport)

_REQ, _RESP, _DATA = 1, 2, 3
_HDR = struct.Struct("<BQII")


def _send_frame(sock: socket.socket, ftype: int, tag: int,
                header: bytes, payload: bytes, lock: threading.Lock) -> None:
    buf = _HDR.pack(ftype, tag, len(header), len(payload))
    with lock:
        sock.sendall(buf)
        if header:
            sock.sendall(header)
        if payload:
            sock.sendall(payload)


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    out = bytearray()
    while len(out) < n:
        chunk = sock.recv(n - len(out))
        if not chunk:
            raise ConnectionError("peer closed")
        out.extend(chunk)
    return bytes(out)


class _SocketConnection(Connection):
    """Outbound channel to one peer (socket + response waiters)."""

    def __init__(self, peer_executor_id: str, addr: Tuple[str, int],
                 owner: "SocketTransport"):
        super().__init__(peer_executor_id)
        self._owner = owner
        self._sock = socket.create_connection(
            addr, timeout=owner.connect_timeout_s)
        self._sock.settimeout(None)
        self._wlock = threading.Lock()
        self._send_lock = threading.Lock()
        self._waiters: Dict[int, Transaction] = {}
        self._dead: Optional[str] = None
        t = threading.Thread(target=self._reader, daemon=True,
                             name=f"shuffle-conn-{peer_executor_id}")
        t.start()

    def _reader(self):
        try:
            while True:
                raw = _recv_exact(self._sock, _HDR.size)
                ftype, tag, hlen, plen = _HDR.unpack(raw)
                header = _recv_exact(self._sock, hlen) if hlen else b""
                payload = _recv_exact(self._sock, plen) if plen else b""
                if ftype == _RESP:
                    with self._wlock:
                        txn = self._waiters.pop(tag, None)
                    if txn is not None:
                        txn.complete(TransactionStatus.SUCCESS,
                                     response=header)
                elif ftype == _DATA:
                    # a peer may push data frames on this channel too
                    self._owner._dispatch_data(header, payload)
        except (ConnectionError, OSError) as e:
            self._fail_all(str(e) or "connection lost")

    def _fail_all(self, why: str):
        self._dead = why
        with self._wlock:
            waiters, self._waiters = dict(self._waiters), {}
        for txn in waiters.values():
            txn.complete(TransactionStatus.ERROR, error=why)
        self._owner._drop_connection(self.peer_executor_id, self)

    def request(self, message: bytes,
                cb: Optional[Callable] = None) -> Transaction:
        txn = self._new_txn()
        txn.start(cb)
        if self._dead:
            txn.complete(TransactionStatus.ERROR, error=self._dead)
            return txn
        with self._wlock:
            self._waiters[txn.txn_id] = txn
        try:
            _send_frame(self._sock, _REQ, txn.txn_id, message, b"",
                        self._send_lock)
        except (ConnectionError, OSError) as e:
            with self._wlock:
                self._waiters.pop(txn.txn_id, None)
            txn.complete(TransactionStatus.ERROR, error=str(e))
        return txn

    def send_data(self, header: bytes, payload: bytes,
                  cb: Optional[Callable] = None) -> Transaction:
        txn = self._new_txn()
        txn.start(cb)
        try:
            _send_frame(self._sock, _DATA, txn.txn_id, header, payload,
                        self._send_lock)
            txn.complete(TransactionStatus.SUCCESS)
        except (ConnectionError, OSError) as e:
            txn.complete(TransactionStatus.ERROR, error=str(e))
        return txn

    def close(self):
        try:
            self._sock.close()
        except OSError:
            pass


class SocketTransport(Transport):
    """Listening endpoint + outbound connection table for one executor.

    Handlers (a ShuffleServer for requests, a ShuffleClient for data) are
    wired after construction; the peer table maps executor ids to
    ``host:port`` endpoints and is fed by the heartbeat layer
    (ExecutorInfo.endpoint carries the address, heartbeat.py)."""

    def __init__(self, executor_id: str, host: str = "127.0.0.1",
                 port: int = 0, connect_timeout_s: float = 10.0):
        self.executor_id = executor_id
        #: connection-setup deadline (was hardcoded); a dead peer must
        #: fail fast enough for the client's retry/failover budget
        self.connect_timeout_s = connect_timeout_s
        self._server_handler = None
        self._data_handler = None
        self._peers: Dict[str, Tuple[str, int]] = {}
        self._conns: Dict[str, _SocketConnection] = {}
        self._lock = threading.Lock()
        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listener.bind((host, port))
        self._listener.listen(16)
        self.address: Tuple[str, int] = self._listener.getsockname()
        self._closing = False
        t = threading.Thread(target=self._accept_loop, daemon=True,
                             name=f"shuffle-listen-{executor_id}")
        t.start()

    # -- wiring --------------------------------------------------------------
    def set_handlers(self, server_handler, data_handler) -> None:
        self._server_handler = server_handler
        self._data_handler = data_handler

    def update_peer(self, executor_id: str, host: str, port: int) -> None:
        with self._lock:
            self._peers[executor_id] = (host, port)
            # a re-registered peer (restart) invalidates the old channel
            stale = self._conns.pop(executor_id, None)
        if stale is not None:
            stale.close()

    @property
    def endpoint(self) -> str:
        return f"{self.address[0]}:{self.address[1]}"

    # -- inbound -------------------------------------------------------------
    def _accept_loop(self):
        while not self._closing:
            try:
                sock, _addr = self._listener.accept()
            except OSError:
                return
            threading.Thread(target=self._serve_conn, args=(sock,),
                             daemon=True).start()

    def _serve_conn(self, sock: socket.socket):
        wlock = threading.Lock()
        try:
            while True:
                raw = _recv_exact(sock, _HDR.size)
                ftype, tag, hlen, plen = _HDR.unpack(raw)
                header = _recv_exact(sock, hlen) if hlen else b""
                payload = _recv_exact(sock, plen) if plen else b""
                if ftype == _REQ:
                    try:
                        resp = self._server_handler.handle_request(header)
                    except Exception as e:   # noqa: BLE001 - to the peer
                        resp = b""
                        # surface the failure by closing: the peer sees a
                        # failed transaction
                        raise ConnectionError(str(e))
                    _send_frame(sock, _RESP, tag, resp, b"", wlock)
                elif ftype == _DATA:
                    self._dispatch_data(header, payload)
        except (ConnectionError, OSError):
            try:
                sock.close()
            except OSError:
                pass

    def _dispatch_data(self, header: bytes, payload: bytes):
        self._data_handler.handle_data(header, payload)

    # -- outbound ------------------------------------------------------------
    def connect(self, peer_executor_id: str) -> Connection:
        from spark_rapids_tpu.aux.faults import maybe_fire
        maybe_fire("shuffle.connect")
        with self._lock:
            conn = self._conns.get(peer_executor_id)
            if conn is not None and conn._dead is None:
                return conn
            addr = self._peers.get(peer_executor_id)
        if addr is None:
            raise ConnectionError(f"unknown peer {peer_executor_id!r} "
                                  "(not registered via heartbeat)")
        try:
            conn = _SocketConnection(peer_executor_id, addr, self)
        except OSError as e:
            raise ConnectionError(
                f"cannot reach {peer_executor_id} at {addr}: {e}") from e
        with self._lock:
            self._conns[peer_executor_id] = conn
        return conn

    def _drop_connection(self, peer_executor_id: str, conn) -> None:
        with self._lock:
            if self._conns.get(peer_executor_id) is conn:
                del self._conns[peer_executor_id]

    def shutdown(self) -> None:
        self._closing = True
        try:
            self._listener.close()
        except OSError:
            pass
        with self._lock:
            conns, self._conns = list(self._conns.values()), {}
        for c in conns:
            c.close()
