"""Multithreaded shuffle writer/reader.

Reference: RapidsShuffleThreadedWriterBase / ReaderBase
(RapidsShuffleInternalManagerBase.scala:238,569) — thread pools parallelize
serialization + disk I/O per task, with a BytesInFlightLimiter (:529)
bounding buffered bytes.  Here the writer serializes each reduce
partition's batches on a pool and appends them to per-map spill files; the
reader deserializes fetched frames on a pool.
"""

from __future__ import annotations

import io
import os
import struct
import tempfile
import threading
from concurrent.futures import ThreadPoolExecutor
from typing import Dict, Iterator, List, Optional, Sequence, Tuple

from spark_rapids_tpu.shuffle.catalog import ShuffleBlockId
from spark_rapids_tpu.shuffle.serializer import (deserialize_batch,
                                                 serialize_batch)


class BytesInFlightLimiter:
    """Bounds bytes buffered across pool threads (reference:
    BytesInFlightLimiter — acquire blocks until room frees up)."""

    def __init__(self, max_bytes: int):
        self.max_bytes = max_bytes
        self._in_flight = 0
        self._cv = threading.Condition()

    def acquire(self, n: int) -> None:
        with self._cv:
            # a single oversized payload must still make progress
            while self._in_flight and self._in_flight + n > self.max_bytes:
                self._cv.wait()
            self._in_flight += n

    def release(self, n: int) -> None:
        with self._cv:
            self._in_flight -= n
            self._cv.notify_all()

    @property
    def in_flight(self) -> int:
        with self._cv:
            return self._in_flight


class ThreadedShuffleWriter:
    """Writes one map task's output: per-partition batches are serialized
    on the pool and appended to one spill file + an index (the classic
    sort-shuffle file pair, parallelized like the reference's MULTITHREADED
    mode).

    The spill ``directory`` is owned by the caller (ShuffleEnv passes its
    session directory and removes it at shutdown); the mkdtemp fallback is
    for standalone use, where the caller must clean up."""

    def __init__(self, shuffle_id: int, map_id: int, num_partitions: int,
                 pool: ThreadPoolExecutor, directory: Optional[str] = None,
                 codec: str = "none"):
        self.shuffle_id = shuffle_id
        self.map_id = map_id
        self.num_partitions = num_partitions
        self.pool = pool
        self.codec = codec
        self.dir = directory or tempfile.mkdtemp(prefix="tpu_shuffle_")

    def write(self, partitioned_batches: Sequence[Tuple[int, object]]
              ) -> "MapOutputInfo":
        """partitioned_batches: iterable of (reduce_partition, host_batch).
        Serialization runs on the pool; results are collected in submission
        order so batch order within a reduce partition is deterministic
        (matching the reference writer and DEFAULT mode)."""
        futs = [(pid, self.pool.submit(serialize_batch, hb, self.codec))
                for pid, hb in partitioned_batches]
        frames: Dict[int, List[bytes]] = {}
        for pid, f in futs:
            frames.setdefault(pid, []).append(f.result())
        # write the data file partition by partition + offsets index
        path = os.path.join(self.dir,
                            f"shuffle_{self.shuffle_id}_{self.map_id}.data")
        offsets = [0]
        counts = []
        with open(path, "wb") as out:
            for pid in range(self.num_partitions):
                fr = frames.get(pid, [])
                counts.append(len(fr))
                for data in fr:
                    out.write(struct.pack("<q", len(data)))
                    out.write(data)
                offsets.append(out.tell())
        return MapOutputInfo(self.shuffle_id, self.map_id, path,
                             offsets, counts)


class MapOutputInfo:
    """Where one map task's output lives (file + per-partition offsets) —
    the MapStatus analog."""

    def __init__(self, shuffle_id: int, map_id: int, path: str,
                 offsets: List[int], frame_counts: List[int]):
        self.shuffle_id = shuffle_id
        self.map_id = map_id
        self.path = path
        self.offsets = offsets
        self.frame_counts = frame_counts

    def partition_bytes(self, pid: int) -> int:
        return self.offsets[pid + 1] - self.offsets[pid]

    def read_frames(self, pid: int) -> Iterator[bytes]:
        n = self.partition_bytes(pid)
        if n == 0:
            return
        with open(self.path, "rb") as f:
            f.seek(self.offsets[pid])
            end = self.offsets[pid + 1]
            while f.tell() < end:
                (ln,) = struct.unpack("<q", f.read(8))
                yield f.read(ln)


class ThreadedShuffleReader:
    """Reads one reduce partition across map outputs, deserializing frames
    on the pool (reference: RapidsShuffleThreadedReaderBase)."""

    def __init__(self, pool: ThreadPoolExecutor,
                 limiter: Optional[BytesInFlightLimiter] = None):
        self.pool = pool
        self.limiter = limiter or BytesInFlightLimiter(128 << 20)

    def read(self, outputs: Sequence[MapOutputInfo], pid: int):
        """Yields host batches for partition ``pid`` in map order.  The
        limiter bounds RAW frame bytes held by concurrent loads (acquired
        around the read+deserialize window; the decoded batches are the
        caller's memory, as in the reference reader)."""
        def load(out: MapOutputInfo):
            res = []
            for frame in out.read_frames(pid):
                self.limiter.acquire(len(frame))
                try:
                    res.append(deserialize_batch(frame))
                finally:
                    self.limiter.release(len(frame))
            return res

        futs = [self.pool.submit(load, o) for o in outputs]
        for f in futs:
            yield from f.result()
