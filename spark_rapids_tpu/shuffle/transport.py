"""Shuffle transport SPI: connections, transactions, bounce buffers.

Reference: RapidsShuffleTransport.scala (581 LoC SPI), BounceBufferManager
(166), WindowedBlockIterator (179), UCXConnection/UCXTransaction in
shuffle-plugin/.  The SPI shape is preserved so the client/server state
machines are transport-agnostic and testable with mocks — exactly how the
reference tests multi-node without a cluster (tests/.../shuffle/,
RapidsShuffleClientSuite.scala:28).

InProcessTransport is the loopback implementation (single-host executors /
tests); a DCN-backed implementation plugs in behind the same classes.
"""

from __future__ import annotations

import dataclasses
import enum
import threading
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from spark_rapids_tpu.shuffle.catalog import ShuffleBlockId

#: conf-driven default for otherwise-unbounded transport waits
#: (``spark.rapids.shuffle.transport.timeoutMs``, set by ShuffleEnv):
#: ``Transaction.wait(None)`` and ``BounceBufferManager.acquire(None)``
#: resolve ``None`` to this, so a dead peer surfaces as a retryable
#: ``TimeoutError`` through the fetch-retry policy instead of pinning a
#: sender thread forever.
DEFAULT_WAIT_TIMEOUT_S = 120.0


class TransactionStatus(enum.Enum):
    NOT_STARTED = "not_started"
    IN_PROGRESS = "in_progress"
    SUCCESS = "success"
    ERROR = "error"
    CANCELLED = "cancelled"


class Transaction:
    """One request/response or send/receive exchange (reference:
    UCXTransaction).  Completion invokes the callback exactly once."""

    def __init__(self, txn_id: int):
        self.txn_id = txn_id
        self.status = TransactionStatus.NOT_STARTED
        self.error_message: Optional[str] = None
        self.response: Optional[bytes] = None
        self._cb: Optional[Callable[["Transaction"], None]] = None
        self._done = threading.Event()

    def start(self, cb: Optional[Callable[["Transaction"], None]]):
        self.status = TransactionStatus.IN_PROGRESS
        self._cb = cb
        return self

    def complete(self, status: TransactionStatus,
                 response: Optional[bytes] = None,
                 error: Optional[str] = None):
        self.status = status
        self.response = response
        self.error_message = error
        self._done.set()
        if self._cb is not None:
            cb, self._cb = self._cb, None
            cb(self)

    def wait(self, timeout: Optional[float] = None) -> "Transaction":
        """``timeout=None`` means the conf-backed transport default, NOT
        forever — an unbounded wait on a dead peer pins the thread."""
        if timeout is None:
            timeout = DEFAULT_WAIT_TIMEOUT_S
        if not self._done.wait(timeout):
            raise TimeoutError(f"transaction {self.txn_id} timed out "
                               f"after {timeout}s")
        return self


class Connection:
    """A channel to one peer (reference: ClientConnection/ServerConnection).

    request():  control-plane round trip (metadata / transfer-start).
    send_data(): data-plane frame push (bounce-buffer contents).
    """

    def __init__(self, peer_executor_id: str):
        self.peer_executor_id = peer_executor_id
        self._txn_counter = 0
        self._lock = threading.Lock()

    def _new_txn(self) -> Transaction:
        with self._lock:
            self._txn_counter += 1
            return Transaction(self._txn_counter)

    def request(self, message: bytes,
                cb: Optional[Callable] = None) -> Transaction:
        raise NotImplementedError

    def send_data(self, header: bytes, payload: bytes,
                  cb: Optional[Callable] = None) -> Transaction:
        raise NotImplementedError


class Transport:
    """Factory for connections + the bounce-buffer pools (reference:
    RapidsShuffleTransport SPI: connect/makeClient/bounce buffer mgmt)."""

    def connect(self, peer_executor_id: str) -> Connection:
        raise NotImplementedError

    def shutdown(self) -> None:
        pass


# ---------------------------------------------------------------------------
# Bounce buffers
# ---------------------------------------------------------------------------

class BounceBuffer:
    __slots__ = ("size", "data", "_mgr")

    def __init__(self, size: int, mgr: "BounceBufferManager"):
        self.size = size
        self.data = bytearray(size)
        self._mgr = mgr

    def close(self):
        self._mgr._release(self)


class BounceBufferManager:
    """Fixed pool of staging buffers (reference: BounceBufferManager.scala).
    Acquisition blocks when exhausted — the natural backpressure that keeps
    at most pool-size transfers in flight."""

    def __init__(self, buffer_size: int = 4 << 20, count: int = 8):
        self.buffer_size = buffer_size
        self._sem = threading.Semaphore(count)
        self._lock = threading.Lock()
        self._free: List[BounceBuffer] = [BounceBuffer(buffer_size, self)
                                          for _ in range(count)]
        self.total = count

    def acquire(self, timeout: Optional[float] = None) -> BounceBuffer:
        """``timeout=None`` resolves to the transport default: a peer
        that never drains its windows must not park senders forever."""
        if timeout is None:
            timeout = DEFAULT_WAIT_TIMEOUT_S
        if not self._sem.acquire(timeout=timeout):
            raise TimeoutError(
                f"no bounce buffer available after {timeout}s")
        with self._lock:
            return self._free.pop()

    def _release(self, buf: BounceBuffer):
        with self._lock:
            self._free.append(buf)
        self._sem.release()

    @property
    def available(self) -> int:
        with self._lock:
            return len(self._free)


@dataclasses.dataclass(frozen=True)
class BlockRange:
    """A contiguous byte range of one block assigned to a window."""
    block: ShuffleBlockId
    offset: int
    length: int
    block_size: int

    @property
    def is_final(self) -> bool:
        return self.offset + self.length == self.block_size


class WindowedBlockIterator:
    """Packs a sequence of (block, size) into bounce-buffer-sized windows
    (reference: WindowedBlockIterator.scala — tested standalone there too).

    Each window is a list of BlockRanges whose lengths sum to <= window
    bytes; large blocks span several windows."""

    def __init__(self, blocks: Sequence[Tuple[ShuffleBlockId, int]],
                 window_bytes: int):
        if window_bytes <= 0:
            raise ValueError("window must be positive")
        self._blocks = [(b, s) for b, s in blocks if s > 0]
        self._window = window_bytes
        self._bi = 0
        self._off = 0

    def __iter__(self):
        return self

    def __next__(self) -> List[BlockRange]:
        if self._bi >= len(self._blocks):
            raise StopIteration
        out: List[BlockRange] = []
        room = self._window
        while room > 0 and self._bi < len(self._blocks):
            block, size = self._blocks[self._bi]
            take = min(room, size - self._off)
            out.append(BlockRange(block, self._off, take, size))
            room -= take
            self._off += take
            if self._off >= size:
                self._bi += 1
                self._off = 0
        return out


# ---------------------------------------------------------------------------
# In-process transport (loopback implementation of the SPI)
# ---------------------------------------------------------------------------

class _InProcessConnection(Connection):
    def __init__(self, peer_executor_id: str, registry):
        super().__init__(peer_executor_id)
        self._registry = registry

    def _peer_handler(self):
        h = self._registry.get(self.peer_executor_id)
        if h is None:
            raise ConnectionError(
                f"no executor registered as {self.peer_executor_id!r}")
        return h

    def request(self, message: bytes, cb=None) -> Transaction:
        txn = self._new_txn().start(cb)
        try:
            resp = self._peer_handler().handle_request(message)
            txn.complete(TransactionStatus.SUCCESS, response=resp)
        except Exception as e:   # noqa: BLE001 - surfaced via transaction
            txn.complete(TransactionStatus.ERROR, error=str(e))
        return txn

    def send_data(self, header: bytes, payload: bytes, cb=None) -> Transaction:
        txn = self._new_txn().start(cb)
        try:
            self._peer_handler().handle_data(header, payload)
            txn.complete(TransactionStatus.SUCCESS)
        except Exception as e:   # noqa: BLE001
            txn.complete(TransactionStatus.ERROR, error=str(e))
        return txn


class InProcessTransport(Transport):
    """Loopback transport: executors in one process (tests, local mode).
    Handlers register per executor id; connections dispatch synchronously."""

    def __init__(self, bounce_buffers: Optional[BounceBufferManager] = None):
        self._handlers: Dict[str, object] = {}
        self.bounce_buffers = bounce_buffers or BounceBufferManager()

    def register_handler(self, executor_id: str, handler) -> None:
        """handler: .handle_request(bytes)->bytes, .handle_data(h, p)."""
        self._handlers[executor_id] = handler

    def connect(self, peer_executor_id: str) -> Connection:
        return _InProcessConnection(peer_executor_id, self._handlers)
