"""Shuffle worker process (executor analog for cross-process tests).

Each worker owns a ShuffleBufferCatalog + ShuffleServer + ShuffleClient
over a SocketTransport, and is driven by pickled commands on a
multiprocessing Pipe from the driver (the reference's executor receives
work over Spark RPC; the control channel is stand-in driver RPC, the DATA
plane is the real socket transport between workers):

  ("peers", {executor_id: (host, port)})       update peer table
  ("load", shuffle_id, map_id, partition, n_rows, seed)
                                               generate + register blocks
  ("fetch", peer_id, shuffle_id, partition)    fetch over the socket;
                                               replies ("ok", rows, ksum)
                                               or ("fetch_failed", why)
  ("chaos", point, n, skip)                    arm a fault point inside
                                               the worker (aux/faults.py)
  ("exit",)                                    shut down

The worker heartbeats ("hb", executor_id) over the pipe every 0.2s; the
driver feeds these into ShuffleHeartbeatManager (liveness detection of a
killed worker = heartbeat expiry, reference
RapidsShuffleHeartbeatManager.scala).
"""

from __future__ import annotations

import os
import threading
import time


def run_worker(executor_id: str, port: int, ctrl) -> None:
    # workers never touch the device: the shuffle data plane is host-side
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import numpy as np

    from spark_rapids_tpu.columnar.batch import batch_from_pydict
    from spark_rapids_tpu.shuffle.catalog import (ShuffleBlockId,
                                                  ShuffleBufferCatalog,
                                                  ShuffleReceivedBufferCatalog)
    from spark_rapids_tpu.shuffle.client_server import (FetchRetryPolicy,
                                                        ShuffleClient,
                                                        ShuffleServer)
    from spark_rapids_tpu.shuffle.socket_transport import SocketTransport

    transport = SocketTransport(executor_id, port=port)
    catalog = ShuffleBufferCatalog()
    received = ShuffleReceivedBufferCatalog()
    server = ShuffleServer(executor_id, catalog, transport)
    # short per-attempt timeout + tight backoff: a dead peer must surface
    # as fetch_failed well inside the test harness timeout
    client = ShuffleClient(executor_id, transport, received,
                           retry=FetchRetryPolicy(timeout_s=10.0,
                                                  max_retries=1,
                                                  base_wait_s=0.05,
                                                  max_wait_s=0.2))
    transport.set_handlers(server, client)

    stop = threading.Event()

    def heartbeats():
        while not stop.is_set():
            try:
                ctrl.send(("hb", executor_id, transport.endpoint))
            except (BrokenPipeError, OSError):
                return
            stop.wait(0.2)

    threading.Thread(target=heartbeats, daemon=True).start()
    ctrl.send(("ready", executor_id, transport.endpoint))

    while True:
        cmd = ctrl.recv()
        kind = cmd[0]
        if kind == "exit":
            stop.set()
            transport.shutdown()
            ctrl.send(("bye",))
            return
        if kind == "peers":
            for pid, (host, pport) in cmd[1].items():
                transport.update_peer(pid, host, pport)
            ctrl.send(("peers_ok",))
        elif kind == "load":
            _sid, _mid, _pid, n_rows, seed = cmd[1:]
            rng = np.random.default_rng(seed)
            hb = batch_from_pydict({
                "k": rng.integers(0, 1000, n_rows).astype(np.int64),
                "v": np.round(rng.standard_normal(n_rows), 6),
                "s": np.array([f"row{i}" for i in range(n_rows)],
                              dtype=object),
            })
            # two frames per block exercises frame reassembly
            half = n_rows // 2
            blk = ShuffleBlockId(_sid, _mid, _pid)
            catalog.add_batch(blk, hb.slice(0, half))
            catalog.add_batch(blk, hb.slice(half, n_rows - half))
            ksum = int(np.sum(np.asarray(hb.columns[0].arrow)))
            # observability hook: routes to any sink the worker process
            # registered (aux.events global sinks); otherwise free
            from spark_rapids_tpu.aux.events import emit
            emit("shuffleBlockLoaded", executor_id=executor_id,
                 shuffle_id=_sid, map_id=_mid, partition=_pid,
                 rows=n_rows)
            ctrl.send(("loaded", n_rows, ksum))
        elif kind == "chaos":
            from spark_rapids_tpu.aux import faults
            _point, _n, _skip = cmd[1:]
            exc = faults.CHAOS_POINTS.get(_point, (None, None))[1]
            faults.arm_fault(_point, _n, _skip, exc)
            ctrl.send(("chaos_ok", _point))
        elif kind == "fetch":
            peer_id, sid, pid = cmd[1:]
            try:
                blocks = client.do_fetch(peer_id, sid, pid)
                rows = 0
                ksum = 0
                for b in blocks:
                    for hb in received.read_batches(b):
                        rows += hb.row_count
                        ksum += int(np.sum(np.asarray(
                            hb.columns[0].arrow)))
                    received.drop(b)
                from spark_rapids_tpu.aux.events import emit
                emit("shuffleWorkerFetch", executor_id=executor_id,
                     peer=peer_id, shuffle_id=sid, partition=pid,
                     rows=rows)
                ctrl.send(("ok", rows, ksum))
            except Exception as e:    # noqa: BLE001 - fetch failure signal
                ctrl.send(("fetch_failed",
                           f"{type(e).__name__}: {e}"))
        else:
            ctrl.send(("error", f"unknown command {kind!r}"))
