"""SQL front-end: text -> logical AST -> physical plan over the engine.

Reference parity: the reference accepts arbitrary Spark SQL because Spark
parses/analyzes it and hands over physical plans (SQLPlugin.scala:28,
GpuOverrides.scala:4562).  This engine is standalone, so it carries its own
parser + analyzer for the TPC-DS-class dialect: SELECT with joins,
GROUP BY/ROLLUP, HAVING, window functions, CTEs, set operations,
scalar/IN/EXISTS subqueries (correlated ones decorrelated to joins),
CASE, CAST, INTERVAL and date arithmetic.
"""

from spark_rapids_tpu.sql.parser import parse

__all__ = ["parse"]
